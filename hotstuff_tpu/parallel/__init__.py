"""Parallel layer: multi-chip sharding of the crypto batch kernels.

The reference's parallelism axes (SURVEY.md §2.7) map to TPU as:
committee/batch parallelism -> sharding the signature-verification batch
across a ``jax.sharding.Mesh`` of chips; the QC-validity decision is a
cross-chip ``psum`` reduction. There is no model/sequence dimension in a
BFT framework — the scaling axes are committee size and batch size.
"""

from .mesh import (
    ShardedBatchVerifier,
    default_mesh,
    make_sharded_qc_check,
    make_sharded_verify,
)

__all__ = [
    "ShardedBatchVerifier",
    "default_mesh",
    "make_sharded_qc_check",
    "make_sharded_verify",
]
