"""Device-mesh sharding for the Ed25519 batch-verify kernel.

TPU-first design: the verification batch is embarrassingly parallel over
signatures, so the batch axis is sharded over the mesh's ``dp`` axis with
``shard_map`` — each chip runs the fused double-scalar-multiplication
scan on its slice with ZERO communication; only the final "is the whole
QC valid" bit is a one-word ``psum`` over ICI. This is the
committee-size scaling story for the BASELINE.json 256-node configs:
a 256-vote QC shards 32 signatures per chip on a v5e-8.

All functions work identically on a real TPU slice or on the virtual
8-device CPU mesh used in tests (conftest sets
``--xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _SHARD_CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_CHECK_KW = "check_rep"


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable shard_map: forwards the replication/vma
    consistency switch under whichever name this jax spells it."""
    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_SHARD_CHECK_KW: check_vma},
    )

from ..tpu import curve
from ..tpu.ed25519 import BatchVerifier
from ..telemetry import spans as _spans

DP_AXIS = "dp"


def mesh_devices_from_env() -> int | None:
    """``HOTSTUFF_MESH_DEVICES`` as a positive device count, or None when
    unset/invalid (None means "use every visible device").  This is the
    env half of the node CLI's ``--mesh-devices`` bridge: it is read at
    backend materialization so run/run-many/deploy and the bench
    subprocesses all size the production mesh the same way."""
    raw = os.environ.get("HOTSTUFF_MESH_DEVICES", "").strip()
    if not raw:
        return None
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n > 0 else None


def default_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D data-parallel mesh over the first ``n_devices`` devices."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (DP_AXIS,))


# in_specs for (ax, ay, az, at, s_bits, k_bits, r_y, r_sign): batch axis is
# axis 0 everywhere except the bit-planes, where it is axis 1.
_IN_SPECS = (
    P(DP_AXIS),
    P(DP_AXIS),
    P(DP_AXIS),
    P(DP_AXIS),
    P(None, DP_AXIS),
    P(None, DP_AXIS),
    P(DP_AXIS),
    P(DP_AXIS),
)


def _local_verify(ax, ay, az, at, s_bits, k_bits, r_y, r_sign):
    p = curve.dual_scalar_mult(s_bits, k_bits, (ax, ay, az, at))
    return curve.compressed_equals(p, r_y, r_sign)


def _make_local_verify_pallas(interpret: bool = False):
    """Per-shard dispatch of the fully fused Pallas verify (scan +
    in-VMEM compressed-equality epilogue) — each device runs it on its
    slice; per-shard batch must be a multiple of pallas_dsm.LANE_TILE
    (the verifier's pad grid guarantees it).  ``interpret=True`` runs
    the SAME kernel through the Pallas interpreter so the exact
    production route (shard_map + Pallas + psum) gets multi-device
    parity coverage on the CPU test mesh (VERDICT r2 item 7)."""
    from ..tpu import pallas_dsm

    def local(ax, ay, az, at, s_bits, k_bits, r_y, r_sign):
        return pallas_dsm.verify_compressed(
            s_bits, k_bits, (ax, ay, az, at), r_y, r_sign,
            interpret=interpret,
        )

    return local


def make_sharded_verify(
    mesh: Mesh,
    pallas: bool = False,
    interpret: bool = False,
    donate: bool = False,
    psum_word: bool = False,
):
    """jitted [batch]-bool verification with the batch sharded over the
    mesh. Batch size must be a multiple of the mesh size (the driver pads).

    ``pallas=True`` runs the Pallas kernel per shard (TPU meshes; the
    XLA kernel remains the portable path for the CPU-mesh tests and
    dryrun).  ``interpret=True`` (tests only) drives the pallas branch
    through the interpreter on CPU meshes.

    ``donate=True`` donates the per-wave staging temporaries (args 4-7:
    s_bits, k_bits, r_y, r_sign) to the kernel, mirroring the base
    verifier's ``_verify_kernel_donated`` — the committee point rows
    (args 0-3) alias the sharded device key gather and must NOT be
    donated.

    ``psum_word=True`` additionally returns the replicated invalid-count
    scalar — the single psum word crossing ICI that the paper's scaling
    story hinges on.  The production mesh readback fetches THAT word
    first and skips the multi-shard lane gather entirely when the whole
    wave is valid (the common case)."""
    local = _make_local_verify_pallas(interpret) if pallas else _local_verify
    if psum_word:
        inner = local

        def local(ax, ay, az, at, s_bits, k_bits, r_y, r_sign):
            ok = inner(ax, ay, az, at, s_bits, k_bits, r_y, r_sign)
            bad = jax.lax.psum(
                jnp.sum(jnp.logical_not(ok).astype(jnp.int32)), DP_AXIS
            )
            return ok, bad

        out_specs = (P(DP_AXIS), P())
    else:
        out_specs = P(DP_AXIS)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=_IN_SPECS,
        out_specs=out_specs,
        # pallas_call's out_shape carries no varying-mesh-axes metadata,
        # so the vma consistency check cannot apply to the pallas branch
        check_vma=not pallas,
    )
    return jax.jit(fn, donate_argnums=(4, 5, 6, 7) if donate else ())


def make_sharded_qc_check(mesh: Mesh):
    """jitted scalar-bool "is every signature in this QC valid" with the
    batch sharded over the mesh and a single psum word crossing ICI."""

    def local_all(ax, ay, az, at, s_bits, k_bits, r_y, r_sign):
        ok = _local_verify(ax, ay, az, at, s_bits, k_bits, r_y, r_sign)
        bad = jax.lax.psum(jnp.sum(jnp.logical_not(ok).astype(jnp.int32)), DP_AXIS)
        return bad == 0

    fn = shard_map(
        local_all, mesh=mesh, in_specs=_IN_SPECS, out_specs=P()
    )
    return jax.jit(fn)


class ShardedBatchVerifier(BatchVerifier):
    """BatchVerifier whose kernel runs sharded over a device mesh.

    Host-side batch preparation (point-cache lookups, challenge hashing,
    padding) is inherited; only the device dispatch changes. Pads to a
    multiple of the mesh size on top of the power-of-4 shape grid so every
    chip gets an equal slice.
    """

    def __init__(self, mesh: Mesh | None = None, min_device_batch: int = 64):
        # use_pallas=False at the BASE-class routing level: the sharded
        # dispatch below owns kernel choice per shard instead (the base
        # class's split-kernel small-batch route assumes single-device
        # tile interleaving).
        super().__init__(min_device_batch=min_device_batch, use_pallas=False)
        self.mesh = mesh if mesh is not None else default_mesh()
        m = int(self.mesh.devices.size)
        # Per-shard Pallas on TPU meshes (each chip runs the fused
        # VMEM-resident scan on its slice — the v5e-8 path for the
        # <1 ms 256-vote QC target: 32 votes/chip in one lane tile);
        # XLA per shard on CPU meshes (tests/dryrun — Pallas has no CPU
        # lowering outside interpret mode).
        self._shard_pallas = (
            self.mesh.devices.flat[0].platform == "tpu"
        )
        mk = lambda **kw: make_sharded_verify(  # noqa: E731
            self.mesh, pallas=self._shard_pallas, **kw
        )
        # four compiled entry points, each compiled lazily per shape:
        # the plain per-item kernel keeps stage()/bench signature parity
        # with the base class; production verify_device dispatches the
        # psum-word variants (per-item lanes + the one ICI word).
        self._kernel = mk()
        self._kernel_donated = mk(donate=True)
        self._kernel_psum = mk(psum_word=True)
        self._kernel_psum_donated = mk(psum_word=True, donate=True)
        self.name = f"tpu-sharded-{m}"
        if self._shard_pallas:
            from ..tpu import pallas_dsm

            # Per-shard batches must be lane-tile multiples.  The grid
            # must include the intermediate multiples: (128, 128, 1024)
            # made a 256-vote QC pad to 1024 — 4x the work — which was
            # the whole "sharded route pays ~4x at mesh 1" anomaly
            # (VERDICT r4 weak #4; BENCH_r04 sharded_route 2.008 ms vs
            # 0.526 single-device).
            self.pad_sizes = tuple(
                m * k * pallas_dsm.LANE_TILE for k in (1, 2, 4, 8)
            )
        else:
            # equal per-device slices: powers of two from one row per
            # device up to 8192.  The old power-of-4 progression
            # (m * {1,4,16,64,...}) skipped 4096 at mesh 8 — a 4096-sig
            # train wave padded to 8192, 2x the work — and made every
            # canonical wave bucket land between grid points (bucket 64
            # at mesh 8 dispatched shape 128).  Powers of two keep each
            # bucket == its kernel shape at every mesh size.
            sizes, s = [], m
            while s <= 8192:
                sizes.append(s)
                s *= 2
            self.pad_sizes = tuple(sizes)
        # Mesh-multiple wave bucket shapes advertised to the async
        # service's fixed-shape tunnel (ISSUE 7): the canonical bucket
        # ladder (incl. the 4096 train bucket) snapped UP to this mesh's
        # pad grid, so every padded wave IS a pre-compiled kernel shape
        # with equal per-device slices.  On TPU meshes this snaps to the
        # lane-tile grid (e.g. v5e-8 -> 1024/2048/4096).
        grid = self.pad_sizes
        snapped = (
            next((p for p in grid if p >= b), grid[-1])
            for b in (16, 64, 256, 1024, 4096)
        )
        self.wave_bucket_shapes = tuple(sorted(set(snapped)))
        # Per-shard device key table (ISSUE 6): the stacked committee
        # tables replicate across the mesh once per rebuild, each wave
        # ships only its [padded] row indices sharded over dp, and the
        # gather runs device-side producing rows already laid out for
        # the shard_map in_specs — the sharded backend stops restaging
        # 4x[padded,20] coordinate rows every wave.
        self._row_sharding = NamedSharding(self.mesh, P(DP_AXIS))
        self._table_sharding = NamedSharding(self.mesh, P())
        self._sharded_gather = jax.jit(
            lambda tables, idxs: tuple(t[idxs] for t in tables),
            out_shardings=(self._row_sharding,) * 4,
        )

    # per-shard key table: the staged gather emits rows sharded to
    # match the shard_map in_specs (see _gather_device_rows), so the
    # PR 5 device key cache now applies to the mesh backend too
    device_key_cache = True

    def _device_build(self, build):
        """Replicate the stacked committee tables across the mesh once
        per rebuild (committee keys are epoch-static)."""
        if self._device_src is not build:
            tables, _ = build
            self._device_tables = tuple(
                jax.device_put(t, self._table_sharding) for t in tables
            )
            self._device_src = build
        return self._device_tables

    def _gather_device_rows(self, build, idxs):
        """Shard-aligned committee gather: [padded] indices sharded
        over dp index the replicated tables, so each device produces
        exactly its own slice of the coordinate rows."""
        tables = self._device_build(build)
        return self._sharded_gather(
            tables, jax.device_put(idxs, self._row_sharding)
        )

    def _run_kernel(
        self, ax, ay, az, at, s_bits, k_bits, r_y, r_sign, donate=False
    ):
        # donation wired through the shard_map jit (ISSUE 7): the
        # donated compilation hands the four per-wave staging
        # temporaries (bit-planes + R rows) back to XLA, exactly like
        # the base class's _verify_kernel_donated — the point rows stay
        # un-donated because they alias the sharded committee gather.
        kernel = self._kernel_donated if donate else self._kernel
        return kernel(
            jnp.asarray(ax),
            jnp.asarray(ay),
            jnp.asarray(az),
            jnp.asarray(at),
            jnp.asarray(s_bits),
            jnp.asarray(k_bits),
            jnp.asarray(r_y),
            jnp.asarray(r_sign),
        )

    def verify_device(self, messages, pubkeys, signatures):
        """Mesh dispatch with the psum-word readback: each wave returns
        the per-item lanes (sharded over dp) AND the replicated
        invalid-count scalar — the one word that crosses ICI.  The host
        blocks on compute, fetches that word, and only gathers the
        sharded lane array when something was actually invalid, so the
        common all-valid wave's readback is a single scalar transfer
        instead of a cross-shard gather.  Under the profiler the word
        fetch is its own ``mesh.psum`` span, sitting between
        device.execute and readback in the waterfall."""
        n = len(messages)
        if n == 0:
            return np.zeros(0, bool)
        if n > self._padded_sizes()[-1]:
            # oversized batches chunk through the base class, which
            # recurses back here per max-shape chunk
            return super().verify_device(messages, pubkeys, signatures)
        donate = self.donate_buffers
        kernel = self._kernel_psum_donated if donate else self._kernel_psum
        rec = _spans.recorder()
        if rec is None:
            valid_host, arrays = self.prepare(messages, pubkeys, signatures)
            ok, bad = kernel(*(jnp.asarray(a) for a in arrays))
            ok = jax.block_until_ready(ok)
            if int(np.asarray(bad)) == 0:
                # every lane valid => host validity was all-True too
                # (host-invalid rows are zeroed into failing lanes)
                return np.ones(n, bool)
            return np.asarray(ok)[:n] & valid_host
        with rec.span("prepare"):
            valid_host, arrays = self.prepare(messages, pubkeys, signatures)
        with rec.span("dispatch"):
            ok, bad = kernel(*(jnp.asarray(a) for a in arrays))
        with rec.span("device.execute"):
            ok = jax.block_until_ready(ok)
        with rec.span("mesh.psum"):
            bad_count = int(np.asarray(bad))
        if bad_count == 0:
            return np.ones(n, bool)
        with rec.span("readback"):
            return np.asarray(ok)[:n] & valid_host
