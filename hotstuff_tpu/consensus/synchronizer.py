"""Synchronizer: parent-block fetch and re-injection.

Parity target: reference ``Synchronizer`` (consensus/src/synchronizer.rs:
24-149). ``get_parent_block`` answers from the store, or — on a miss —
hands the orphan block to an inner task that (a) sends a SyncRequest to the
block's author, (b) parks a waiter on ``store.notify_read(parent)``, and
(c) re-broadcasts requests older than ``sync_retry_delay`` to the whole
committee every TIMER_ACCURACY tick (the "perfect point-to-point link"
retry, synchronizer.rs:84-105). When the parent is finally written, the
suspended child block is re-sent to the core via the loopback channel.

Beyond the reference: requests EXPIRE.  A parent digest that never
arrives (equivocating proposer, or a sender partitioned before anyone
stored the block) used to pin its waiter task, its ``_pending`` /
``_requests`` entries, and its store obligation forever, while
re-broadcasting to the whole committee every retry tick.  After
``sync_giveup`` seconds the request is abandoned: waiters are
cancelled, the suspended children are forgotten (a live chain re-sends
them via a later QC), and the store obligation is dropped.
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import Digest, PublicKey
from ..network import SimpleSender
from ..store import Store
from ..utils.clock import default_clock
from .config import Committee
from .errors import SerializationError
from .messages import Block
from .wire import encode_sync_request

log = logging.getLogger(__name__)

TIMER_ACCURACY_S = 5.0


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        tx_loopback: asyncio.Queue,
        sync_retry_delay_ms: int,
        network: SimpleSender | None = None,
        telemetry=None,
    ):
        self.name = name
        self.committee = committee
        self.store = store
        self.tx_loopback = tx_loopback
        self.sync_retry_delay = sync_retry_delay_ms / 1000.0
        self.network = network if network is not None else SimpleSender()
        self._journal = telemetry.journal if telemetry is not None else None

        self.log = logging.getLogger(f"{__name__}.{str(name)[:8]}")
        self._pending: set[Digest] = set()  # child digests being synced
        # parent digest -> (first-ask time, child round, parent round):
        # the rounds make the retry broadcast epoch-targeted
        self._requests: dict[Digest, tuple[float, int, int]] = {}
        # Epoch-aware join barrier: set (by the state-sync client) to
        # the snapshot adoption round on a certified-schedule join —
        # ancestry below it is covered by the snapshot and must never
        # be fetched, whatever floor a caller passes.
        self.join_floor = 0
        self._waiters: set[asyncio.Task] = set()
        # give-up bookkeeping: which waiters/children each parent pins
        self._by_parent: dict[Digest, list[asyncio.Task]] = {}
        self._children: dict[Digest, set[Digest]] = {}
        # generous: far past any honest delivery, but bounded (a parent
        # that never arrives must not leak tasks or spam the committee)
        self.sync_giveup = max(30.0, 20 * self.sync_retry_delay)
        self.expired = 0  # abandoned requests (telemetry gauge)
        self._retry_task: asyncio.Task | None = None

    def _ensure_retry_task(self) -> None:
        if self._retry_task is None or self._retry_task.done():
            self._retry_task = asyncio.get_running_loop().create_task(
                self._retry_loop(), name="synchronizer-retry"
            )

    async def _retry_loop(self) -> None:
        while True:
            await default_clock().sleep(TIMER_ACCURACY_S)
            now = default_clock().monotonic()
            for digest, (asked_at, child_round, parent_round) in list(
                self._requests.items()
            ):
                if asked_at + self.sync_giveup < now:
                    self._expire(digest)
                elif asked_at + self.sync_retry_delay < now:
                    self.log.debug("Requesting sync for block %s (retry)", digest)
                    addresses = self._sync_targets(child_round, parent_round)
                    message = encode_sync_request(digest, self.name)
                    await self.network.broadcast(addresses, message)

    def _sync_targets(self, child_round: int, parent_round: int) -> list:
        """Retry-broadcast targets for a missing parent: the members of
        the child's epoch plus the parent's epoch (they differ exactly
        at a reconfiguration boundary — the retiring members are the
        ones guaranteed to hold the old-epoch block).  The all-epoch
        union would instead spam every past epoch's membership on each
        retry tick."""
        seen: dict = {}
        for r in (child_round, max(1, parent_round)):
            com = self.committee.for_round(r)
            for nm, addr in com.broadcast_addresses(self.name):
                seen.setdefault(nm, addr)
        return list(seen.values())

    def _expire(self, parent: Digest) -> None:
        """Abandon a parent that never arrived: unpin everything it
        holds.  The chain self-heals if the digest was real — a later
        block certifying it re-enters via get_parent_block."""
        self.expired += 1
        self._requests.pop(parent, None)
        for task in self._by_parent.pop(parent, ()):
            task.cancel()
        for child in self._children.pop(parent, ()):
            self._pending.discard(child)
        self.store.cancel_notify(parent.to_bytes())
        if self._journal is not None:
            self._journal.record("sync.expire", 0, parent)
        self.log.warning(
            "Giving up sync for parent %s after %.0fs", parent, self.sync_giveup
        )

    async def _waiter(self, parent: Digest, child: Block) -> None:
        """Park on the store until the parent exists, then loop the child
        block back into the core (synchronizer.rs:74-83, 115-118)."""
        try:
            await self.store.notify_read(parent.to_bytes())
        except asyncio.CancelledError:
            return
        self._pending.discard(child.digest())
        self._requests.pop(parent, None)
        if self._journal is not None:
            self._journal.record("sync.done", child.round, parent)
        await self.tx_loopback.put(child)

    async def _request_parent(self, block: Block) -> None:
        if block.digest() in self._pending:
            return
        self._pending.add(block.digest())
        parent = block.parent
        task = asyncio.get_running_loop().create_task(
            self._waiter(parent, block), name=f"sync-wait-{parent}"
        )
        self._waiters.add(task)
        self._by_parent.setdefault(parent, []).append(task)
        self._children.setdefault(parent, set()).add(block.digest())

        def _cleanup(t, parent=parent):
            self._waiters.discard(t)
            tasks = self._by_parent.get(parent)
            if tasks is not None:
                try:
                    tasks.remove(t)
                except ValueError:
                    pass
                if not tasks:
                    self._by_parent.pop(parent, None)
                    self._children.pop(parent, None)

        task.add_done_callback(_cleanup)

        if parent not in self._requests:
            self.log.debug("Requesting sync for block %s", parent)
            self._requests[parent] = (
                default_clock().monotonic(), block.round, block.qc.round
            )
            if self._journal is not None:
                self._journal.record(
                    "sync.req", block.round, parent, str(block.author)[:8]
                )
            address = self.committee.address(block.author)
            if address is not None:
                await self.network.send(
                    address, encode_sync_request(parent, self.name)
                )
        self._ensure_retry_task()

    async def get_parent_block(
        self, block: Block, floor: int = -1
    ) -> Block | None:
        """The block certified by ``block.qc``; None if it must be fetched
        (in which case processing of ``block`` is suspended).

        ``floor`` is the snapshot barrier: a node that adopted a
        QC-anchored state snapshot holds no block history at or below its
        commit cursor, and that history must never be fetched — otherwise
        a snapshot rejoin degenerates into the hop-by-hop ancestry
        backfill the snapshot exists to skip (and stalls outright when an
        old proposer is unreachable).  A missing parent certified at or
        below the floor resolves to the genesis stand-in: the block's own
        verified QC vouches for it, its state effects are inside the
        snapshot, and callers only read ``.round`` from it (the 2-chain
        commit rule can never fire across the cut)."""
        if block.qc.is_genesis():
            return Block.genesis()
        data = await self.store.read(block.parent.to_bytes())
        if data is not None:
            try:
                return Block.deserialize(data)
            except Exception as e:
                raise SerializationError(f"corrupt block in store: {e}") from e
        if block.qc.round <= max(floor, self.join_floor):
            return Block.genesis()
        await self._request_parent(block)
        return None

    async def get_ancestors(
        self, block: Block, floor: int = -1
    ) -> tuple[Block, Block] | None:
        """(b0, b1) with b0 <- |qc0; b1| <- |qc1; block|, or None if the
        parent chain is not yet locally available.  ``floor`` applies the
        snapshot barrier (see get_parent_block) to both hops."""
        b1 = await self.get_parent_block(block, floor)
        if b1 is None:
            return None
        b0 = await self.get_parent_block(b1, floor)
        if b0 is None:
            # Delivered blocks have stored ancestors (synchronizer.rs:142-146)
            # except across a snapshot cut (handled by the floor above);
            # reaching here means the store lost data.
            raise SerializationError(
                f"missing ancestor of delivered block {b1.digest()}"
            )
        return b0, b1

    def shutdown(self) -> None:
        if self._retry_task is not None:
            self._retry_task.cancel()
            self._retry_task = None
        for task in list(self._waiters):
            task.cancel()
        self._waiters.clear()
        self._by_parent.clear()
        self._children.clear()
        self.network.close()
