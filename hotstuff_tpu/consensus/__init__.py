"""Consensus layer: the 2-chain HotStuff protocol engine.

Parity map (SURVEY.md §2.4): messages (Block/Vote/QC/Timeout/TC), config
(Committee/Parameters), aggregator (QCMaker/TCMaker), leader elector,
timer, core state machine, proposer, synchronizer, helper, and the
Consensus wiring — reference crate ``consensus/``.
"""

from .aggregator import Aggregator, QCMaker, TCMaker
from .config import Authority, Committee, CommitteeSchedule, Parameters
from .consensus import CHANNEL_CAPACITY, Consensus, ConsensusReceiverHandler
from .core import ConsensusState, Core, ProposerMessage
from .errors import (
    AuthorityReuse,
    ConsensusError,
    InvalidSignature,
    QCRequiresQuorum,
    SerializationError,
    TCRequiresQuorum,
    UnknownAuthority,
    WrongLeader,
)
from .helper import Helper
from .leader import LeaderElector, RoundRobinLeaderElector
from .messages import QC, TC, Block, Round, Timeout, Vote, timeout_digest
from .proposer import Proposer
from .synchronizer import Synchronizer
from .timer import Timer

__all__ = [
    "Aggregator",
    "QCMaker",
    "TCMaker",
    "Authority",
    "Committee",
    "CommitteeSchedule",
    "Parameters",
    "CHANNEL_CAPACITY",
    "Consensus",
    "ConsensusReceiverHandler",
    "ConsensusState",
    "Core",
    "ProposerMessage",
    "AuthorityReuse",
    "ConsensusError",
    "InvalidSignature",
    "QCRequiresQuorum",
    "SerializationError",
    "TCRequiresQuorum",
    "UnknownAuthority",
    "WrongLeader",
    "Helper",
    "LeaderElector",
    "RoundRobinLeaderElector",
    "QC",
    "TC",
    "Block",
    "Round",
    "Timeout",
    "Vote",
    "timeout_digest",
    "Proposer",
    "Synchronizer",
    "Timer",
]
