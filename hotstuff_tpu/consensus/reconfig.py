"""Consensus-driven committee reconfiguration: the typed epoch-change op.

BEYOND reference parity (the reference fleet is frozen at boot): a
``ReconfigOp`` carries the NEXT epoch's full committee plus an
activation margin Δ.  It is sponsored (signed) by a current member,
proposed inside a block, 2-chain committed like any other block, and
applied by every node's commit path — which splices
``(commit_round + Δ, new_committee)`` into the shared, mutable
``CommitteeSchedule``.  Certificates formed at the boundary keep
verifying under their own epoch (the ``for_round`` seam); leader
election, stake checks and wire-scheme narrowing roll forward at the
activation round.

Wire form (versioned; decode-time caps on every attacker-sized field):

    u8  version (RECONFIG_OP_VERSION)
    u64 epoch                     -- must be current epoch + 1
    var scheme (<= 16 bytes)      -- "ed25519" | "bls"
    u32 margin                    -- activation delay Δ in rounds
    u16 member count              -- capped at MAX_RECONFIG_MEMBERS
    per member:
        var pk (<= 96)  u64 stake  var host (<= 255)  u32 port
        flag pop?  [var pop (<= 96)]
    var sponsor pk (<= 96)
    var sponsor signature (<= 96)  -- over digest() of everything above

The sponsor rule is the submission-authorization gate: only a member of
the committee in effect at the proposing round may introduce an epoch
change, and every voter re-checks the sponsor signature inside
``Block.verify`` — a forged or out-of-protocol reconfiguration dies at
verification (the ``byz-reconfig`` adversary policy exercises exactly
this path).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import Digest, PublicKey, Signature, sha512_trunc
from ..utils.codec import CodecError, Decoder, Encoder
from .config import Authority, Committee, InvalidCommittee
from .errors import InvalidReconfig

#: wire version byte of the reconfiguration op
RECONFIG_OP_VERSION = 1
#: decode-time cap on the proposed committee's member count
MAX_RECONFIG_MEMBERS = 128
#: activation margin bounds: the lower bound keeps the boundary past the
#: 2-chain commit depth of the op's own block (every node must be able
#: to commit-and-splice before certificates for the new epoch arrive);
#: the upper bound rejects a margin that would park the epoch change
#: beyond any practical run.
RECONFIG_MIN_MARGIN = 2
RECONFIG_MAX_MARGIN = 1_000_000

_MAX_SCHEME = 16
_MAX_HOST = 255
_MAX_KEYSIG = 96
_KNOWN_SCHEMES = ("ed25519", "bls")


def encode_committee(enc: Encoder, committee: Committee) -> None:
    """Canonical wire form of one epoch's committee (sorted key order —
    two nodes encoding the same committee must produce identical bytes,
    the op digest depends on it)."""
    enc.u64(committee.epoch)
    enc.var_bytes(committee.scheme.encode())
    names = committee.sorted_keys()
    enc.u16(len(names))
    for name in names:
        auth = committee.authorities[name]
        enc.var_bytes(name.to_bytes())
        enc.u64(auth.stake)
        host, port = auth.address
        enc.var_bytes(host.encode())
        enc.u32(port)
        enc.flag(auth.pop is not None)
        if auth.pop is not None:
            enc.var_bytes(auth.pop)


def decode_committee(dec: Decoder) -> Committee:
    epoch = dec.u64()
    scheme_raw = dec.var_bytes(_MAX_SCHEME)
    try:
        scheme = scheme_raw.decode("ascii")
    except UnicodeDecodeError as e:
        raise CodecError(f"non-ascii committee scheme: {e}") from e
    if scheme not in _KNOWN_SCHEMES:
        raise CodecError(f"unknown committee scheme '{scheme}'")
    n = dec.u16()
    if n > MAX_RECONFIG_MEMBERS:
        raise CodecError(
            f"reconfig member count {n} exceeds cap {MAX_RECONFIG_MEMBERS}"
        )
    authorities: dict[PublicKey, Authority] = {}
    for _ in range(n):
        pk_raw = dec.var_bytes(_MAX_KEYSIG)
        try:
            pk = PublicKey(pk_raw)
        except ValueError as e:
            raise CodecError(str(e)) from e
        stake = dec.u64()
        host_raw = dec.var_bytes(_MAX_HOST)
        try:
            host = host_raw.decode("ascii")
        except UnicodeDecodeError as e:
            raise CodecError(f"non-ascii member host: {e}") from e
        port = dec.u32()
        pop = dec.var_bytes(_MAX_KEYSIG) if dec.flag() else None
        if pk in authorities:
            raise CodecError(f"duplicate member {pk} in reconfig committee")
        authorities[pk] = Authority(stake, (host, port), pop=pop)
    return Committee(authorities=authorities, epoch=epoch, scheme=scheme)


@dataclass
class ReconfigOp:
    """A sponsored epoch change: the next epoch's committee + margin Δ."""

    new_committee: Committee
    margin: int
    sponsor: PublicKey = field(default_factory=PublicKey)
    signature: Signature = field(default_factory=Signature)
    _digest: bytes | None = field(
        default=None, init=False, repr=False, compare=False
    )

    def digest(self) -> bytes:
        """Digest of the op body (everything the sponsor signs; the
        sponsor fields themselves are excluded)."""
        d = self._digest
        if d is None:
            enc = Encoder()
            self._encode_body(enc)
            d = sha512_trunc(enc.finish())
            self._digest = d
        return d

    def _encode_body(self, enc: Encoder) -> None:
        enc.u8(RECONFIG_OP_VERSION)
        encode_committee(enc, self.new_committee)
        enc.u32(self.margin)

    def encode(self, enc: Encoder) -> None:
        self._encode_body(enc)
        enc.var_bytes(self.sponsor.to_bytes())
        enc.var_bytes(self.signature.to_bytes())

    @classmethod
    def decode(cls, dec: Decoder) -> "ReconfigOp":
        version = dec.u8()
        if version != RECONFIG_OP_VERSION:
            raise CodecError(f"unknown reconfig op version {version}")
        committee = decode_committee(dec)
        margin = dec.u32()
        try:
            sponsor = PublicKey(dec.var_bytes(_MAX_KEYSIG))
            signature = Signature(dec.var_bytes(_MAX_KEYSIG))
        except ValueError as e:
            raise CodecError(str(e)) from e
        return cls(
            new_committee=committee,
            margin=margin,
            sponsor=sponsor,
            signature=signature,
        )

    def serialize(self) -> bytes:
        enc = Encoder()
        self.encode(enc)
        return enc.finish()

    @classmethod
    def deserialize(cls, data: bytes) -> "ReconfigOp":
        dec = Decoder(data)
        op = cls.decode(dec)
        dec.finish()
        return op

    def __repr__(self) -> str:
        return (
            f"ReconfigOp(epoch {self.new_committee.epoch}, "
            f"{len(self.new_committee.authorities)} members, "
            f"margin {self.margin})"
        )


def newest_epoch(committee) -> int:
    """Highest epoch number anywhere in the schedule (a bare Committee
    is its own single epoch)."""
    return max(c.epoch for c in committee.committees())


def validate_reconfig(op: ReconfigOp, committee, round_, verifier=None):
    """The verification gate every honest node applies to a reconfig op
    — at submission, at ``Block.verify`` (so a Byzantine leader's forged
    epoch change dies before any honest vote), and again defensively at
    apply.  ``committee`` is the node's committee/schedule; ``round_``
    the round the op is proposed in.  ``verifier`` (when given) also
    checks the sponsor signature.  Raises ``InvalidReconfig``.
    """
    current = committee.for_round(round_)
    new = op.new_committee
    if not (RECONFIG_MIN_MARGIN <= op.margin <= RECONFIG_MAX_MARGIN):
        raise InvalidReconfig(
            f"activation margin {op.margin} outside "
            f"[{RECONFIG_MIN_MARGIN}, {RECONFIG_MAX_MARGIN}]"
        )
    if not new.authorities:
        raise InvalidReconfig("proposed committee is empty")
    if len(new.authorities) > MAX_RECONFIG_MEMBERS:
        raise InvalidReconfig(
            f"proposed committee has {len(new.authorities)} members "
            f"(cap {MAX_RECONFIG_MEMBERS})"
        )
    if new.scheme not in _KNOWN_SCHEMES:
        raise InvalidReconfig(f"unknown scheme '{new.scheme}'")
    if any(a.stake <= 0 for a in new.authorities.values()):
        raise InvalidReconfig("proposed committee has a zero-stake member")
    if new.epoch != newest_epoch(committee) + 1:
        raise InvalidReconfig(
            f"proposed epoch {new.epoch} does not succeed newest "
            f"scheduled epoch {newest_epoch(committee)}"
        )
    # Continuity: the carried-over members must hold at least f+1 of the
    # CURRENT epoch's stake, so at least one honest current member is
    # guaranteed to survive into the new epoch (a forged committee of
    # attacker-only keys fails here even if structurally well-formed).
    overlap = sum(
        current.stake(name)
        for name in new.authorities
        if current.stake(name) > 0
    )
    if overlap < current.validity_threshold():
        raise InvalidReconfig(
            f"carried-over stake {overlap} below the current epoch's "
            f"validity threshold {current.validity_threshold()}"
        )
    if current.stake(op.sponsor) <= 0:
        raise InvalidReconfig(
            f"sponsor {op.sponsor} is not a member of the current epoch"
        )
    if verifier is not None and not verifier.verify_one(
        Digest(op.digest()), op.sponsor, op.signature
    ):
        raise InvalidReconfig("bad sponsor signature on reconfig op")
    # Rogue-key hardening carries over: a BLS successor committee must
    # prove possession per member before it can ever be spliced.
    try:
        new.verify_pops()
    except InvalidCommittee as e:
        raise InvalidReconfig(str(e)) from e


def splice_schedule_links(
    links,
    committee,
    verifier,
    qc_cache: set | None = None,
    journal=None,
    log=None,
) -> int:
    """Verified-successor acceptance (docs/RECONFIG.md): walk a certified
    ``(reconfig block bytes, certifying QC bytes)`` chain — served in a
    state-sync manifest or replayed from the local store at boot — and
    splice every epoch change not yet present into the schedule.

    Each link is self-certifying: the op is re-validated against the
    schedule *as extended so far*, and the QC must certify exactly that
    block under the committee in effect at its round.  A node that
    started from only the genesis committee file therefore ends up with
    the same schedule a live witness holds, or the chain is rejected.

    Returns the number of links spliced; raises :class:`InvalidReconfig`
    on the first link that fails verification (callers discard the whole
    chain — a partial splice is still safe, since every applied link was
    individually certified)."""
    from ..utils.codec import CodecError, Decoder
    from .errors import ConsensusError
    from .messages import QC, Block

    if not links:
        return 0
    if not hasattr(committee, "splice"):
        raise InvalidReconfig(
            "static committee cannot accept schedule links"
        )
    spliced = 0
    for raw_block, raw_qc in links:
        try:
            block = Block.deserialize(raw_block)
            dec = Decoder(raw_qc)
            qc = QC.decode(dec)
            dec.finish()
        except (CodecError, ConsensusError, ValueError) as e:
            raise InvalidReconfig(f"corrupt schedule link: {e}") from e
        op = block.reconfig
        if op is None:
            raise InvalidReconfig("schedule link carries no reconfig op")
        if op.new_committee.epoch <= newest_epoch(committee):
            continue  # already spliced (earlier chain, or live witness)
        validate_reconfig(op, committee, block.round, verifier=verifier)
        if qc.hash != block.digest() or qc.round != block.round:
            raise InvalidReconfig(
                "schedule link QC does not certify its block"
            )
        try:
            qc.verify(committee, verifier, cache=qc_cache)
        except ConsensusError as e:
            raise InvalidReconfig(
                f"schedule link QC failed to verify: {e}"
            ) from e
        activation = block.round + op.margin
        try:
            committee.splice(activation, op.new_committee)
        except InvalidCommittee as e:
            raise InvalidReconfig(str(e)) from e
        spliced += 1
        if journal is not None:
            journal.record("reconfig.link", block.round)
        if log is not None:
            log.info(
                "Verified schedule link: epoch %d activates at round %d",
                op.new_committee.epoch,
                activation,
            )
    return spliced
