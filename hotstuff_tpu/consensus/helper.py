"""Helper: replies to other authorities' sync requests.

Parity target: reference ``Helper`` (consensus/src/helper.rs:14-68): for
each (missing-digest, origin) request, read the block from the store and —
if we have it — send it back to the requester as a regular Propose
message, letting the normal proposal path store it and wake the
requester's parked synchronizer waiter.
"""

from __future__ import annotations

import asyncio
import logging

from ..network import SimpleSender
from ..store import Store
from .config import Committee
from .messages import Block
from .wire import encode_propose

log = logging.getLogger(__name__)


class Helper:
    def __init__(
        self,
        committee: Committee,
        store: Store,
        rx_requests: asyncio.Queue,
        network: SimpleSender | None = None,
        telemetry=None,
    ):
        self.committee = committee
        self.store = store
        self.rx_requests = rx_requests
        self.network = network if network is not None else SimpleSender()
        self._journal = telemetry.journal if telemetry is not None else None
        self._task: asyncio.Task | None = None

    async def run(self) -> None:
        while True:
            digest, origin = await self.rx_requests.get()
            address = self.committee.address(origin)
            if address is None:
                log.warning(
                    "Received sync request from unknown authority: %s", origin
                )
                continue
            data = await self.store.read(digest.to_bytes())
            if data is not None:
                block = Block.deserialize(data)
                if self._journal is not None:
                    self._journal.record(
                        "sync.reply", block.round, digest, str(origin)[:8]
                    )
                await self.network.send(address, encode_propose(block))

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.get_running_loop().create_task(
            self.run(), name="helper"
        )
        return self._task

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.network.close()
