"""Proposer: payload buffering, block creation, quorum-ACK back-pressure.

Parity target: reference ``Proposer`` (consensus/src/proposer.rs:17-186),
the fork's producer payload path: producer digests arriving from external
parties are buffered; on ``Make(round, qc, tc)`` one buffered digest
becomes the payload of a signed block that is reliable-broadcast to the
committee, looped back to the core, and ACK-awaited until 2f+1 stake —
the leader back-pressure control system (proposer.rs:115-131).

Redesigned buffering (round-2 fix for the burst-and-stall dynamics the
reference's scheme produces):

- The reference buffers payloads in per-round buckets keyed by the
  store's ``latest_round + 1`` *at arrival time* (proposer.rs:164-173) and
  drops whole buckets as rounds are processed.  Under load, rounds race
  ahead of payload arrival, each round discards an entire bucket after
  consuming one digest, the buffer empties, and the next leader
  "proposes nothing" (proposer.rs:74-78) — wedging the round for the
  full 5 s view-change timeout.  Measured effect in round 1: commits in
  ~5 ms bursts separated by 5 s stalls, 87 ms mean consensus latency.
  The bucket scheme also costs one store round-trip per arriving payload
  (the ``latest_round`` read), 50k queue hops/s at the target rate.
- Here: one FIFO (ordered map) with digest dedup and O(1) removal of
  committed payloads (core cleanup).  ``Make`` pops the oldest
  payload; if the buffer is empty the make is DEFERRED and fires the
  moment the next payload arrives (superseded by newer makes, dropped by
  cleanups for later rounds).  No store reads at all on the payload
  path; consensus paces itself to the payload arrival rate instead of
  spinning empty rounds into view changes.
"""

from __future__ import annotations

import asyncio
import logging
import os
from collections import OrderedDict

from ..crypto import Digest, PublicKey, SignatureService
from ..network import ReliableSender
from ..utils.clock import default_clock
from .config import Committee
from .core import ProposerMessage
from .messages import MAX_BLOCK_PAYLOADS, QC, TC, Block, Round
from .reconfig import ReconfigOp, newest_epoch
from .wire import encode_propose

log = logging.getLogger(__name__)

# Payload buffer bound: newest arrivals are dropped when full (the
# reference's bounded channel has the same drop-newest semantics).
MAX_PENDING = 100_000
# Dedup window: digests remembered (buffered or already proposed).
SEEN_CAP = 200_000
# In-flight proposal bound (rounds whose fate is undecided).  When commit
# signals stall past this many proposals, the OLDEST one's payloads are
# conservatively re-buffered (treated as orphaned).  The bound keeps
# inflight memory finite through arbitrarily long partitions; the
# eager re-buffer can duplicate a payload only if its commit signal is
# still unseen AFTER this many newer proposals resolved — and the
# committed_seen LRU (SEEN_CAP deep) still filters those on resolution.
MAX_INFLIGHT = 1_024


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


class Proposer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        rx_producer: asyncio.Queue,
        rx_message: asyncio.Queue,
        tx_loopback: asyncio.Queue,
        network: ReliableSender | None = None,
        telemetry=None,
        adversary=None,
        admission=None,
    ):
        self.name = name
        # Ingest admission controller (ingest/admission.py): fed the
        # committed-payload counts from Cleanup messages — the drain
        # signal its credit window is derived from.  None = no ingest
        # plane (component tests construct the proposer bare).
        self.admission = admission
        # Buffer bound, overridable per run (HOTSTUFF_MAX_PENDING) so
        # load tests can shrink the buffer and reach the admission
        # watermark without queuing 100k payloads first.
        self.max_pending = _env_int("HOTSTUFF_MAX_PENDING", MAX_PENDING)
        # Payloads silently dropped at the full buffer — with admission
        # control active this staying at ZERO under overload is the
        # acceptance signal (sheds happen at the ingest door instead).
        self.drop_newest = 0
        # Byzantine adversary plane (faults/adversary.py): None on
        # honest nodes; the equivocation seam in _make_block consults it
        self.adversary = adversary
        self.committee = committee
        self.signature_service = signature_service
        self.rx_producer = rx_producer
        self.rx_message = rx_message
        self.tx_loopback = tx_loopback
        # FIFO with O(1) membership/removal: committed payloads are
        # pruned by digest on every commit (Core._commit cleanup).
        self.pending: OrderedDict[Digest, None] = OrderedDict()
        self.seen: OrderedDict[Digest, None] = OrderedDict()
        # Our proposals whose fate is undecided: round -> payloads.
        # With single-homed clients (node/client.py round-robin) only WE
        # hold these digests — if the block orphans (a view change built
        # the chain past it), they must return to the buffer or they are
        # lost for good.  Resolved by commit signals (cleanup messages
        # carrying committed_round).
        self.inflight: dict[Round, tuple] = {}
        # Recently COMMITTED digests (bounded LRU): orphan recovery must
        # not re-buffer a payload that committed in an EARLIER walk via
        # another node's block (multi-homed producers) — the per-walk
        # payload set alone cannot show that.
        self.committed_seen: OrderedDict[Digest, None] = OrderedDict()
        self.deferred: ProposerMessage | None = None
        # A core-validated reconfiguration op awaiting our next leader
        # slot (docs/RECONFIG.md); dropped once its epoch is scheduled
        # (another leader's block carried it first).
        self.pending_reconfig: ReconfigOp | None = None
        # Highest round a block was actually created for: re-issued Makes
        # for the same round are dropped, so (a) the core may safely
        # re-send a Make when allow_empty conditions change, and (b) this
        # node can never produce two blocks for one round (leader
        # equivocation guard).
        self.last_made_round: Round = 0
        self.network = network if network is not None else ReliableSender()
        self._task: asyncio.Task | None = None
        self.log = logging.getLogger(f"{__name__}.{str(name)[:8]}")
        # Telemetry (optional): payload buffer dwell time + buffer
        # occupancy.  With telemetry on, `pending` values hold the
        # arrival timestamp (read at make time); off, they stay None —
        # no per-payload float allocation.
        self.telemetry = telemetry
        self._payload_wait = None
        self._deferred_makes = None
        self._journal = telemetry.journal if telemetry is not None else None
        if telemetry is not None:
            self._payload_wait = telemetry.trace.payload_wait
            self._deferred_makes = telemetry.counter(
                "proposer_deferred_makes",
                "Makes deferred for lack of buffered payloads",
            )
            telemetry.gauge(
                "proposer_pending_payloads",
                "Payload digests buffered for proposal",
                fn=lambda: len(self.pending),
            )
            telemetry.gauge(
                "proposer_inflight_proposals",
                "Own proposals whose commit/orphan fate is undecided",
                fn=lambda: len(self.inflight),
            )
            telemetry.gauge(
                "proposer_drop_newest",
                "Payloads silently dropped at the full buffer "
                "(admission control should keep this at zero)",
                fn=lambda: self.drop_newest,
            )

    def _buffer_payload(self, digest: Digest) -> None:
        if digest in self.seen:
            return  # duplicate of a buffered or recently proposed payload
        if len(self.pending) >= self.max_pending:
            self.drop_newest += 1
            return  # drop newest under overload (bounded like reference)
        self.seen[digest] = None
        while len(self.seen) > SEEN_CAP:
            self.seen.popitem(last=False)
        if self._payload_wait is not None:
            self.pending[digest] = default_clock().monotonic()
        else:
            self.pending[digest] = None

    async def _make_block(
        self, round_: Round, qc: QC, tc: TC | None, allow_empty: bool = False
    ) -> None:
        if round_ <= self.last_made_round:
            return  # already proposed for this round (equivocation guard)
        op = self.pending_reconfig
        if op is not None and newest_epoch(self.committee) >= op.new_committee.epoch:
            # the epoch change is already scheduled (committed via
            # another leader's block, or a competing op won): drop ours
            self.pending_reconfig = None
            op = None
        snipes = (
            self.adversary.wants("reconfig", round_)
            if op is None and self.adversary is not None else False
        )
        if snipes:
            # reconfig policy (forge half): attach a forged epoch change
            # — well-formed wire, hostile committee / bad sponsor — that
            # MUST die in every honest voter's Block.verify.  The
            # reconfig-sniper mounts the same forgery, but only inside
            # the epoch-activation margin (wants returns its token).
            op = self.adversary.forged_reconfig(self.committee, round_)
            if op is not None:
                self.adversary.mark_adaptive(snipes, round_, self.log)
                self.adversary.count("byz_forged_reconfigs")
                self.adversary.record("reconfig-forge", round_)
                self.log.info("byz reconfig-forge round %d", round_)
        if not self.pending and not allow_empty and op is None:
            # Defer: fire the moment the next payload arrives instead of
            # wedging the round until the view-change timer (see module
            # docstring).  A newer Make supersedes this one.
            self.deferred = ProposerMessage.make(round_, qc, tc)
            if self._deferred_makes is not None:
                self._deferred_makes.inc()
            self.log.info("Round: %d, no payloads yet - proposal deferred", round_)
            return
        # allow_empty: the core signalled that uncommitted payload blocks
        # are in flight — an empty block advances the 2-chain so they
        # commit now rather than on the producer's next burst.
        self.last_made_round = round_
        take = min(len(self.pending), MAX_BLOCK_PAYLOADS)
        if self._payload_wait is not None and take:
            now = default_clock().monotonic()
            popped = [self.pending.popitem(last=False) for _ in range(take)]
            for _, arrived in popped:
                if arrived:  # re-buffered orphans may carry None
                    self._payload_wait.observe(now - arrived)
            payloads = tuple(d for d, _ in popped)
        else:
            payloads = tuple(
                self.pending.popitem(last=False)[0] for _ in range(take)
            )
        if payloads:
            self.inflight[round_] = payloads
            while len(self.inflight) > MAX_INFLIGHT:
                self._requeue_oldest_inflight()

        if op is not None and op is self.pending_reconfig:
            self.pending_reconfig = None  # it rides in this block
        block = Block(
            qc=qc, tc=tc, author=self.name, round=round_, payloads=payloads,
            reconfig=op,
        )
        block.signature = await self.signature_service.request_signature(
            block.digest()
        )
        if op is not None:
            self.log.info(
                "Proposing reconfig in block %d: epoch %d (margin %d)",
                round_, op.new_committee.epoch, op.margin,
            )
        # NOTE: this log entry is used to compute performance — the harness
        # maps each payload -> block digest from it (benchmark/logs.py
        # contract).
        self.log.info(
            "Created block %d (payloads %s) -> %s",
            block.round,
            ",".join(str(p) for p in block.payloads),
            block.digest(),
        )
        if self._journal is not None:
            # the propose record is the timeline anchor traces.py hangs
            # every recv.propose edge off — journaled just before the
            # broadcast leaves this node
            self._journal.record("propose", block.round, block.digest())
            if block.payloads:
                # producer-channel edge (ROADMAP PR 2 follow-up): pairs
                # with the receiver's recv.producer record so traces
                # can measure payload-wait (client frame -> proposed)
                # and chaos runs can tell payload starvation from
                # consensus stall
                self._journal.record(
                    "payload.first", block.round, block.payloads[0]
                )

        # Broadcast to the union of epochs (committee.broadcast_addresses
        # is the union on a CommitteeSchedule — members of the adjacent
        # epoch need boundary blocks too); ACK stake counts only under
        # the BLOCK round's committee.
        com = self.committee.for_round(round_)
        names_addresses = self.committee.broadcast_addresses(self.name)
        message = encode_propose(block)
        # broadcast() (not a per-peer send loop) so flow accounting
        # charges ONE logical propose per proposal: the wire/logical
        # ratio is the leader amplification factor (== n-1 here).
        # ReliableSender.broadcast enqueues per address in list order,
        # so handles pair with names exactly as the loop did.
        handles = list(
            zip(
                (name for name, _ in names_addresses),
                await self.network.broadcast(
                    [address for _, address in names_addresses], message
                ),
            )
        )

        await self.tx_loopback.put(block)

        ambushes = (
            self.adversary.wants("equivocate", block.round)
            if self.adversary is not None else False
        )
        if ambushes:
            # schedule-driven equivocation, or the ambush-leader trigger
            # (faults/adaptive.py): equivocate exactly when we lead a
            # round seated by a fresh TC
            self.adversary.mark_adaptive(
                ambushes, block.round, self.log, block.digest()
            )
            await self._byz_equivocate(block, names_addresses)

        # Control system: wait for 2f+1 total stake (ours included) to ACK
        # the block before making the next one.
        total_stake = com.stake(self.name)
        threshold = com.quorum_threshold()
        # tasks is an ordered LIST (committee order), not a set:
        # cancelling a waiter propagates into its ACK handle, which the
        # reliable sender reads as "give up retransmitting this frame" —
        # id()-ordered set iteration here made the surviving retransmit
        # set depend on heap layout (caught by the deterministic sim's
        # byte-identical-journal check).
        tasks = [
            asyncio.ensure_future(self._ack_stake(handle, com.stake(name)))
            for name, handle in handles
        ]
        pending = set(tasks)
        try:
            while pending and total_stake < threshold:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    # lint: allow(no-blocking-in-async) -- t is in the
                    # done set asyncio.wait just returned: result() is
                    # an immediate read, never a block
                    total_stake += t.result()
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()

    async def _byz_equivocate(self, block: Block, names_addresses) -> None:
        """equivocate policy (adversary plane): sign the deterministic
        shadow twin of the block just proposed — same round, same QC,
        conflicting payloads — and ship it to a deterministic peer
        subset (fellow colluders when colluding, else the first half of
        the peer set).  Honest receivers vote at most once per round,
        so the main branch keeps committing; the checker attributes the
        equivocations to this authority."""
        adversary = self.adversary
        shadow = adversary.shadow_block(block)
        shadow.signature = await self.signature_service.request_signature(
            shadow.digest()
        )
        targets = adversary.equivocation_targets(names_addresses)
        message = encode_propose(shadow)
        for _, address in targets:
            await self.network.send(address, message)
        adversary.count("byz_equivocations")
        adversary.record(
            "equivocate", block.round, shadow.digest(), f"{len(targets)}p"
        )
        self.log.info(
            "byz equivocate round %d -> %s | %s (%d peers)",
            block.round, block.digest(), shadow.digest(), len(targets),
        )

    def _requeue_orphans(
        self, round_: Round, payloads: tuple, committed=frozenset(), note: str = ""
    ) -> None:
        """Re-buffer a resolved/abandoned proposal's payloads at the
        FRONT of the queue (oldest-first order preserved by callers
        iterating newest-round-first), skipping anything known
        committed or already buffered."""
        orphaned = [
            d for d in payloads
            if d not in committed
            and d not in self.committed_seen
            and d not in self.pending
        ]
        if orphaned:
            self.log.info(
                "Re-buffering %d payloads from %s block %d",
                len(orphaned),
                note or "orphaned",
                round_,
            )
        for digest in reversed(orphaned):
            self.pending[digest] = None
            self.pending.move_to_end(digest, last=False)

    def _requeue_oldest_inflight(self) -> None:
        """Inflight overflow (MAX_INFLIGHT): re-buffer the oldest
        undecided proposal's payloads as if orphaned.  Single-homed
        payloads survive the stall; the committed_seen/pending filters
        keep the duplicate window bounded (see MAX_INFLIGHT note)."""
        round_ = min(self.inflight)
        self._requeue_orphans(
            round_, self.inflight.pop(round_), note="unresolved"
        )

    def _resolve_inflight(self, message: ProposerMessage) -> None:
        """Orphan recovery: once the chain is committed through round R,
        every proposal of ours at round <= R either committed (its
        payloads are in the accumulated committed sets) or was orphaned
        by a view change — re-buffer the orphans at the FRONT of the
        queue (oldest first) so single-homed payloads are never lost."""
        if not message.committed_round:
            return
        for round_ in sorted(
            (r for r in self.inflight if r <= message.committed_round),
            reverse=True,  # re-insert newest first so oldest ends up in front
        ):
            self._requeue_orphans(
                round_, self.inflight.pop(round_), committed=message.payloads
            )

    @staticmethod
    async def _ack_stake(handle: asyncio.Future, stake: int) -> int:
        # handle resolves with the peer's ACK; deliver that peer's stake
        await handle
        return stake

    async def run(self) -> None:
        prod_task = asyncio.ensure_future(self.rx_producer.get())
        msg_task = asyncio.ensure_future(self.rx_message.get())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {prod_task, msg_task}, return_when=asyncio.FIRST_COMPLETED
                )
                if prod_task in done:
                    # lint: allow(no-blocking-in-async) -- guarded by
                    # membership in asyncio.wait's done set
                    digest = prod_task.result()
                    self._buffer_payload(digest)
                    # drain any burst backlog without extra loop passes
                    while not self.rx_producer.empty():
                        self._buffer_payload(self.rx_producer.get_nowait())
                    prod_task = asyncio.ensure_future(self.rx_producer.get())
                    if self.deferred is not None and self.pending:
                        make = self.deferred
                        self.deferred = None
                        await self._make_block(make.round, make.qc, make.tc)
                if msg_task in done:
                    # lint: allow(no-blocking-in-async) -- guarded by
                    # membership in asyncio.wait's done set
                    message: ProposerMessage = msg_task.result()
                    if message.kind == ProposerMessage.MAKE:
                        self.deferred = None  # superseded
                        await self._make_block(
                            message.round,
                            message.qc,
                            message.tc,
                            message.allow_empty,
                        )
                    elif message.kind == ProposerMessage.RECONFIG:
                        self.pending_reconfig = message.op
                        self.log.info(
                            "Reconfig op buffered for the next leader "
                            "slot: epoch %d",
                            message.op.new_committee.epoch,
                        )
                        if self.deferred is not None:
                            # an empty-buffer make was parked waiting
                            # for payloads — the op is reason enough to
                            # propose now
                            make = self.deferred
                            self.deferred = None
                            await self._make_block(make.round, make.qc, make.tc)
                    else:
                        # Cleanup(rounds): the chain advanced through these
                        # rounds — a deferred make for an older round is
                        # stale (the core will issue a fresh Make when this
                        # node next leads).
                        if (
                            self.deferred is not None
                            and message.rounds
                            and self.deferred.round <= max(message.rounds)
                        ):
                            self.deferred = None
                        # Cleanup(payloads): these digests committed (in
                        # anyone's block) — proposing them again would
                        # waste block capacity on duplicates.  They stay
                        # in `seen` so a re-delivered copy is not
                        # re-buffered either.
                        if self.admission is not None and message.payloads:
                            # drain signal for the ingest credit window
                            self.admission.on_committed(len(message.payloads))
                        for digest in message.payloads:
                            self.pending.pop(digest, None)
                            self.committed_seen[digest] = None
                        while len(self.committed_seen) > SEEN_CAP:
                            self.committed_seen.popitem(last=False)
                        self._resolve_inflight(message)
                    msg_task = asyncio.ensure_future(self.rx_message.get())
        finally:
            prod_task.cancel()
            msg_task.cancel()

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.get_running_loop().create_task(
            self.run(), name="proposer"
        )
        return self._task

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.network.close()
