"""Proposer: payload buffering, block creation, quorum-ACK back-pressure.

Parity target: reference ``Proposer`` (consensus/src/proposer.rs:17-186),
the fork's producer payload path:

- producer digests arriving from external parties are buffered per round,
  keyed by (latest stored round + 1) (proposer.rs:164-173);
- on ``Make(round, qc, tc)`` one buffered digest is chosen at random for
  the payload round; with an empty buffer nothing is proposed
  (proposer.rs:69-80);
- the signed block is reliable-broadcast to the committee, looped back to
  the core, and the proposer then BLOCKS until 2f+1 stake has ACKed — the
  leader back-pressure control system (proposer.rs:115-131).
"""

from __future__ import annotations

import asyncio
import logging
import random

from ..crypto import Digest, PublicKey, SignatureService
from ..network import ReliableSender
from ..store import Store
from .config import Committee
from .core import LATEST_ROUND_KEY, ProposerMessage
from .messages import QC, TC, Block, Round
from .wire import encode_propose

log = logging.getLogger(__name__)


class Proposer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        rx_producer: asyncio.Queue,
        rx_message: asyncio.Queue,
        tx_loopback: asyncio.Queue,
        store: Store,
        network: ReliableSender | None = None,
    ):
        self.name = name
        self.committee = committee
        self.signature_service = signature_service
        self.rx_producer = rx_producer
        self.rx_message = rx_message
        self.tx_loopback = tx_loopback
        self.store = store
        self.buffer: dict[Round, list[Digest]] = {}
        self.network = network if network is not None else ReliableSender()
        self._task: asyncio.Task | None = None
        self.log = logging.getLogger(f"{__name__}.{str(name)[:8]}")

    async def _latest_round(self) -> Round:
        raw = await self.store.read(LATEST_ROUND_KEY)
        return int.from_bytes(raw, "big") if raw else 0

    async def _make_block(self, round_: Round, qc: QC, tc: TC | None) -> None:
        payload_round = await self._latest_round() + 1
        # Liveness fix over the reference (proposer.rs:69-80): payloads are
        # buffered under latest_round+1 *at arrival time*; the reference only
        # ever proposes from the exact current bucket, so payloads whose
        # round passed unproposed (view change, lost race) are orphaned and
        # the proposer stalls. Here we fall back to the newest non-empty
        # bucket. Buckets stay separate so Cleanup keeps the reference's
        # per-round payload-dedup semantics (one bucket dropped per
        # processed round, not the whole queue).
        candidates = self.buffer.get(payload_round)
        if not candidates:
            fallback = [r for r in self.buffer if self.buffer[r]]
            if fallback:
                candidates = self.buffer[max(fallback)]
        if not candidates:
            self.log.info("Round: %d, No payloads to propose", round_)
            return
        # bound stale-bucket growth the reference leaks (aggregator-style
        # DoS TODO, proposer buffer equivalent)
        for r in [r for r in self.buffer if r < payload_round - 64]:
            del self.buffer[r]
        payload = random.choice(candidates)

        block = Block(qc=qc, tc=tc, author=self.name, round=round_, payload=payload)
        block.signature = await self.signature_service.request_signature(
            block.digest()
        )
        # NOTE: this log entry is used to compute performance — the harness
        # maps payload -> block digest from it (benchmark/logs.py contract).
        self.log.info(
            "Created block %d (payload %s) -> %s",
            block.round,
            block.payload,
            block.digest(),
        )

        names_addresses = self.committee.broadcast_addresses(self.name)
        message = encode_propose(block)
        handles = [
            (name, await self.network.send(address, message))
            for name, address in names_addresses
        ]

        await self.tx_loopback.put(block)

        # Control system: wait for 2f+1 total stake (ours included) to ACK
        # the block before making the next one.
        total_stake = self.committee.stake(self.name)
        threshold = self.committee.quorum_threshold()
        pending = {
            asyncio.ensure_future(
                self._ack_stake(handle, self.committee.stake(name))
            )
            for name, handle in handles
        }
        try:
            while pending and total_stake < threshold:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    total_stake += t.result()
        finally:
            for t in pending:
                t.cancel()

    @staticmethod
    async def _ack_stake(handle: asyncio.Future, stake: int) -> int:
        # handle resolves with the peer's ACK; deliver that peer's stake
        await handle
        return stake

    async def run(self) -> None:
        prod_task = asyncio.ensure_future(self.rx_producer.get())
        msg_task = asyncio.ensure_future(self.rx_message.get())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {prod_task, msg_task}, return_when=asyncio.FIRST_COMPLETED
                )
                if prod_task in done:
                    digest = prod_task.result()
                    self.log.debug("Received payload: %s", digest)
                    latest = await self._latest_round()
                    self.buffer.setdefault(latest + 1, []).append(digest)
                    prod_task = asyncio.ensure_future(self.rx_producer.get())
                if msg_task in done:
                    message: ProposerMessage = msg_task.result()
                    if message.kind == ProposerMessage.MAKE:
                        await self._make_block(
                            message.round, message.qc, message.tc
                        )
                    else:
                        for r in message.rounds:
                            self.buffer.pop(r, None)
                    msg_task = asyncio.ensure_future(self.rx_message.get())
        finally:
            prod_task.cancel()
            msg_task.cancel()

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.get_running_loop().create_task(
            self.run(), name="proposer"
        )
        return self._task

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.network.close()
