"""Protocol messages: Block, Vote, QC, Timeout, TC.

Parity target: reference ``consensus/src/messages.rs`` (16-324). Same
protocol objects and verification rules, restructured for the TPU crypto
backend: every ``verify`` takes a ``VerifierBackend`` so certificate
signature checks ship as *batches* (QC: one shared digest, the
``verify_shared_msg`` shape; TC: distinct digests, the ``verify_many``
shape) instead of a sequential per-signature loop — the BASELINE.json
accumulate-then-dispatch rewrite.

Digest preimages (all SHA-512 truncated to 32 bytes):
- block:   author ‖ round_le8 ‖ payload ‖ qc.hash   (messages.rs:80-87)
- vote:    block_hash ‖ round_le8                   (messages.rs:148-153)
- qc:      hash ‖ round_le8                         (messages.rs:205-210)
- timeout: round_le8 ‖ high_qc.round_le8            (messages.rs:266-271)
TC entries sign the timeout digest for (tc.round, entry.high_qc_round)
(messages.rs:305-311).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..crypto import Digest, PublicKey, Signature, sha512_trunc
from ..crypto.service import VerifierBackend
from ..utils.codec import CodecError, Decoder, Encoder
from .config import Committee
from .errors import (
    AuthorityReuse,
    InvalidSignature,
    MalformedBlock,
    QCRequiresQuorum,
    TCRequiresQuorum,
    UnknownAuthority,
)
from .reconfig import ReconfigOp, validate_reconfig

Round = int

# Wire cap for length-prefixed key/signature fields (largest scheme:
# BLS 96-byte public keys; Ed25519 is 32/64).  One committee uses one
# scheme; the length prefix lets both coexist in the one wire format.
_MAX_KEYSIG = 96

# Compact-certificate wire form (aggregated BLS committees): inside the
# certificate's wire slot the vote count carries this sentinel, followed
# by a version byte, one aggregated G1 signature and a committee signer
# bitmap — constant-size in committee membership (48 + ceil(n/8) bytes
# vs n x 144 for the vote list).  ed25519 committees never emit it and
# scheme-pinned decoders reject it (wire.SCHEME_COMPACT_SIZES sets
# ``Decoder.compact_sig_size`` to 0 = forbidden).
COMPACT_SENTINEL = 0xFFFFFFFF
COMPACT_VERSION = 1
#: decode-time cap on the signer bitmap (bytes) — committees up to 4096
MAX_SIGNER_BITMAP = 512
#: decode-time cap on compact-TC groups (distinct high_qc_rounds)
MAX_COMPACT_GROUPS = 64
#: decode-time cap on vote-list entries in a QC/TC — one vote per
#: committee member, same 4096-member ceiling the signer bitmap encodes.
#: Without it a 4-byte wire count of 2**32 drives the vote decode loop
#: (an allocation bomb the codec's truncation check does not stop,
#: because each iteration reads only a few valid bytes before failing).
MAX_CERT_VOTES = 8 * MAX_SIGNER_BITMAP

#: process-wide QC-verify memo hits/misses — the ``qc_verify_cache_hit``
#: telemetry counter reads these (co-located committees share the
#: process, so the split is per-process, not per-node)
QC_CACHE_STATS = {"hits": 0, "misses": 0}


def make_signer_bitmap(authors, ordered: list[PublicKey]) -> bytes:
    """Bitmap over ``ordered`` (the round committee's ``sorted_keys()``)
    with one bit set per author; unknown authors raise."""
    index = {pk: i for i, pk in enumerate(ordered)}
    bits = bytearray((len(ordered) + 7) // 8)
    for pk in authors:
        i = index.get(pk)
        if i is None:
            raise UnknownAuthority(pk)
        bits[i // 8] |= 1 << (i % 8)
    return bytes(bits)


def bitmap_indices(bitmap: bytes):
    """Set-bit positions of a signer bitmap, ascending."""
    for byte_idx, b in enumerate(bitmap):
        while b:
            low = b & -b
            yield byte_idx * 8 + low.bit_length() - 1
            b ^= low


def bitmap_keys(bitmap: bytes, ordered: list[PublicKey]) -> list[PublicKey]:
    """Resolve a signer bitmap against the committee key order.  Bits
    beyond the committee size take the UnknownAuthority path — the same
    rule an unknown vote author hits in the vote-list form."""
    out = []
    for i in bitmap_indices(bitmap):
        if i >= len(ordered):
            raise UnknownAuthority(f"signer bit {i} of {len(ordered)}")
        out.append(ordered[i])
    return out


def _popcount(bitmap: bytes) -> int:
    return int.from_bytes(bitmap, "little").bit_count()


def _compact_allowed(dec: Decoder) -> None:
    if dec.compact_sig_size == 0:
        raise CodecError(
            "compact certificate not valid under this committee scheme"
        )


def _decode_agg_and_bitmap(dec: Decoder) -> tuple[Signature, bytes]:
    agg = dec.var_bytes(_MAX_KEYSIG)
    want = dec.compact_sig_size
    if want is not None and len(agg) != want:
        raise CodecError(
            f"aggregate signature must be {want} bytes under the "
            f"committee scheme, got {len(agg)}"
        )
    bitmap = dec.var_bytes(dec.compact_bitmap_max or MAX_SIGNER_BITMAP)
    try:
        return Signature(agg), bitmap
    except ValueError as e:
        raise CodecError(str(e)) from e


def _decode_compact_version(dec: Decoder) -> None:
    _compact_allowed(dec)
    version = dec.u8()
    if version != COMPACT_VERSION:
        raise CodecError(f"unknown compact-certificate version {version}")


# Precompiled struct layouts for the two hottest wire shapes (per-scheme
# pk/sig sizes).  When the decoder carries the committee's sizes
# (wire.decode_message sets them from the scheme), QC and Vote decoding
# collapses ~10 generic codec calls into one-or-two struct unpacks —
# byte-identical format, just fewer interpreter frames.  The generic
# Encoder/Decoder path remains for unpinned decoders (loopback, store
# deserialize, mixed-size tests).
def _qc_structs(ps: int, ss: int):
    key = (ps, ss)
    cached = _QC_STRUCTS.get(key)
    if cached is None:
        cached = (
            struct.Struct("<32sQI"),
            struct.Struct(f"<I{ps}sI{ss}s"),
        )
        _QC_STRUCTS[key] = cached
    return cached


def _vote_struct(ps: int, ss: int):
    key = (ps, ss)
    cached = _VOTE_STRUCTS.get(key)
    if cached is None:
        cached = struct.Struct(f"<32sQI{ps}sI{ss}s")
        _VOTE_STRUCTS[key] = cached
    return cached


_QC_STRUCTS: dict = {}
_VOTE_STRUCTS: dict = {}


def _round_le(r: Round) -> bytes:
    return struct.pack("<Q", r)


def encode_pk(enc: Encoder, pk: PublicKey) -> None:
    enc.var_bytes(pk.to_bytes())


def decode_pk(dec: Decoder) -> PublicKey:
    data = dec.var_bytes(_MAX_KEYSIG)
    if dec.pk_size is not None and len(data) != dec.pk_size:
        raise CodecError(
            f"public key must be {dec.pk_size} bytes under the "
            f"committee scheme, got {len(data)}"
        )
    try:
        return PublicKey(data)
    except ValueError as e:
        raise CodecError(str(e)) from e


def encode_sig(enc: Encoder, sig: Signature) -> None:
    enc.var_bytes(sig.to_bytes())


def decode_sig(dec: Decoder) -> Signature:
    data = dec.var_bytes(_MAX_KEYSIG)
    if dec.sig_size is not None and len(data) != dec.sig_size:
        raise CodecError(
            f"signature must be {dec.sig_size} bytes under the "
            f"committee scheme, got {len(data)}"
        )
    try:
        return Signature(data)
    except ValueError as e:
        raise CodecError(str(e)) from e


def _check_certificate_weight(
    votes_authors: list[PublicKey], committee: Committee, quorum_error
) -> None:
    """Shared QC/TC stake rule: no authority reuse, all known, 2f+1 stake."""
    weight = 0
    used: set[PublicKey] = set()
    for name in votes_authors:
        if name in used:
            raise AuthorityReuse(name)
        stake = committee.stake(name)
        if stake <= 0:
            raise UnknownAuthority(name)
        used.add(name)
        weight += stake
    if weight < committee.quorum_threshold():
        raise quorum_error()


@dataclass
class QC:
    """Quorum certificate: 2f+1 vote signatures over one block digest."""

    hash: Digest = field(default_factory=Digest)
    round: Round = 0
    votes: list[tuple[PublicKey, Signature]] = field(default_factory=list)
    # compact (aggregated) form: one G1 aggregate over the shared vote
    # digest plus a signer bitmap over the round committee's
    # sorted_keys() order.  ``votes`` is empty in this form; either form
    # proves the same 2f+1 statement and both coexist on the wire
    # (versioned sentinel encoding below).
    agg_sig: Signature | None = None
    signers: bytes | None = None
    # memoized wire encoding (same contract as Block._wire): the
    # committee's current high_qc is re-encoded on every ConsensusState
    # persist (once-plus per round per node) and in every block carrying
    # it; certificates never mutate after construction/decode.
    _wire: bytes | None = field(
        default=None, init=False, repr=False, compare=False
    )

    @classmethod
    def genesis(cls) -> "QC":
        return cls()

    def is_genesis(self) -> bool:
        return (
            self.hash == Digest()
            and self.round == 0
            and not self.votes
            and self.agg_sig is None
        )

    @property
    def is_compact(self) -> bool:
        return self.agg_sig is not None

    def signer_keys(self, committee: Committee) -> list[PublicKey]:
        """The compact form's signers, resolved against the round
        committee's canonical key order."""
        return bitmap_keys(
            self.signers, committee.for_round(self.round).sorted_keys()
        )

    def wire_size(self) -> int:
        """Encoded certificate size in bytes (the qc_bytes metric)."""
        enc = Encoder()
        self.encode(enc)
        return len(enc.finish())

    def timeout(self) -> bool:
        return self.hash == Digest() and self.round != 0

    def digest(self) -> Digest:
        return Digest(sha512_trunc(self.hash.to_bytes() + _round_le(self.round)))

    def _cache_key(self) -> bytes:
        """Identity of this certificate's full contents (hash, round and
        every vote) — two QCs with the same key are byte-identical, so a
        successful verification of one covers the other.

        The hashed material must be INJECTIVE in the vote list, not just
        the concatenated bytes: pk/sig accept multiple wire sizes (32/96
        and 64/48 for ed25519/BLS), so an unframed concatenation lets a
        different partitioning of the same byte stream (e.g. two 96+48
        votes vs three 32+64 chunks, both 288 bytes) collide with a
        verified QC's key and skip verification for a crafted
        certificate.  Hence the vote count and a u32 length prefix per
        field.  The compact form gets its own discriminator byte so an
        aggregate certificate can never collide with a vote-list one."""
        if self.is_compact:
            agg = self.agg_sig.to_bytes()
            parts = [
                b"\x01",
                self.hash.to_bytes(),
                _round_le(self.round),
                len(agg).to_bytes(4, "little"),
                agg,
                len(self.signers).to_bytes(4, "little"),
                self.signers,
            ]
            return sha512_trunc(b"".join(parts))
        parts = [
            b"\x00",
            self.hash.to_bytes(),
            _round_le(self.round),
            len(self.votes).to_bytes(4, "little"),
        ]
        for pk, sig in self.votes:
            parts.append(len(pk.data).to_bytes(4, "little"))
            parts.append(pk.data)
            parts.append(len(sig.data).to_bytes(4, "little"))
            parts.append(sig.data)
        return sha512_trunc(b"".join(parts))

    def check_weight(self, committee: Committee) -> None:
        """The stake/structure rules alone (no signatures): authority
        reuse, unknown authorities, 2f+1 stake — under this
        certificate's own round's committee.  The compact form resolves
        its bitmap first: a bit per member makes reuse structurally
        impossible, but sub-quorum bitmaps and out-of-range bits fail
        here exactly like their vote-list counterparts."""
        committee = committee.for_round(self.round)  # epoch seam
        if self.is_compact:
            _check_certificate_weight(
                bitmap_keys(self.signers, committee.sorted_keys()),
                committee,
                QCRequiresQuorum,
            )
            return
        _check_certificate_weight(
            [pk for pk, _ in self.votes], committee, QCRequiresQuorum
        )

    def claims(
        self, cache: set | None = None, committee: Committee | None = None
    ) -> list:
        """The signature claims an async preverifier must discharge for
        this certificate (crypto/async_service.py): one shared-message
        claim (vote-list form) or one aggregate claim (compact form —
        needs ``committee`` to resolve the signer bitmap), or none when
        genesis / already memoized in ``cache``.

        SAFETY: a successful claim verdict proves only the SIGNATURES.
        A caller that memoizes this certificate as verified (the core's
        qc_cache — ``verify`` early-returns on a hit) must check
        ``check_weight`` FIRST, or a sub-quorum certificate with one
        valid self-signature would enter the cache and bypass the
        quorum rule forever."""
        if self.is_genesis():
            return []
        if cache is not None and self._cache_key() in cache:
            QC_CACHE_STATS["hits"] += 1
            return []
        if self.is_compact:
            if committee is None:
                raise ValueError(
                    "compact QC claims need the committee to resolve "
                    "the signer bitmap"
                )
            return [
                (
                    "agg",
                    self.digest().to_bytes(),
                    self.agg_sig.to_bytes(),
                    tuple(
                        pk.to_bytes() for pk in self.signer_keys(committee)
                    ),
                )
            ]
        return [
            (
                "shared",
                self.digest().to_bytes(),
                tuple((pk.to_bytes(), sig.to_bytes()) for pk, sig in self.votes),
            )
        ]

    def verify(
        self,
        committee: Committee,
        verifier: VerifierBackend,
        cache: set | None = None,
        sigs_verified: bool = False,
    ) -> None:
        """``cache`` (per-core, optional) memoizes certificates that
        already verified against THIS committee: under a view-change
        storm every one of n timeouts carries the same high_qc, and
        without the memo the node re-runs the identical batch
        verification n times (n x the most expensive check in the
        protocol).  Only successes are cached; the set is bounded by the
        owner (core.py).

        ``sigs_verified=True``: the caller already discharged this
        certificate's signature ``claims()`` through the async
        preverifier — only the stake/structure rules run here."""
        key = None
        if cache is not None:
            key = self._cache_key()
            if key in cache:
                QC_CACHE_STATS["hits"] += 1
                return
            QC_CACHE_STATS["misses"] += 1
        self.check_weight(committee)
        if not sigs_verified:
            if self.is_compact:
                # Bitmap-selected public keys summed + ONE pairing,
                # regardless of committee size (verify_aggregate_msg —
                # BLS backends only; a backend without it cannot accept
                # an aggregate certificate).
                fn = getattr(verifier, "verify_aggregate_msg", None)
                pks = [pk.to_bytes() for pk in self.signer_keys(committee)]
                if fn is None or not fn(
                    self.digest(), pks, self.agg_sig.to_bytes()
                ):
                    raise InvalidSignature(
                        f"bad aggregate signature in QC for {self.hash}"
                    )
            # One batched verification over the shared vote digest — the
            # hot kernel (reference messages.rs:195 → crypto verify_batch).
            elif not verifier.verify_shared_msg(self.digest(), self.votes):
                raise InvalidSignature(f"bad signature in QC for {self.hash}")
        if cache is not None:
            cache.add(key)

    # equality on (hash, round) only, like the reference (messages.rs:213-217)
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, QC)
            and self.hash == other.hash
            and self.round == other.round
        )

    def __hash__(self) -> int:
        return hash((self.hash, self.round))

    def encode(self, enc: Encoder) -> None:
        w = self._wire
        if w is None:
            e = Encoder()
            e.raw(self.hash.to_bytes()).u64(self.round)
            if self.is_compact:
                e.u32(COMPACT_SENTINEL).u8(COMPACT_VERSION)
                e.var_bytes(self.agg_sig.to_bytes())
                e.var_bytes(self.signers)
            else:
                e.u32(len(self.votes))
                for pk, sig in self.votes:
                    encode_pk(e, pk)
                    encode_sig(e, sig)
            w = e.finish()
            self._wire = w
        enc.raw(w)

    @classmethod
    def decode(cls, dec: Decoder) -> "QC":
        ps, ss = dec.pk_size, dec.sig_size
        if ps is not None and ss is not None:
            return cls._decode_fast(dec, ps, ss)
        start = dec.mark()
        h = Digest(dec.raw(Digest.SIZE))
        rnd = dec.u64()
        n = dec.u32()
        if n == COMPACT_SENTINEL:
            _decode_compact_version(dec)
            agg, signers = _decode_agg_and_bitmap(dec)
            qc = cls(hash=h, round=rnd, agg_sig=agg, signers=signers)
            qc._wire = dec.since(start)
            return qc
        if n > MAX_CERT_VOTES:
            raise CodecError(f"QC vote count {n} exceeds cap {MAX_CERT_VOTES}")
        votes = [(decode_pk(dec), decode_sig(dec)) for _ in range(n)]
        qc = cls(hash=h, round=rnd, votes=votes)
        qc._wire = dec.since(start)
        return qc

    @classmethod
    def _decode_fast(cls, dec: Decoder, ps: int, ss: int) -> "QC":
        # struct fast path for a scheme-pinned decoder; byte-identical
        # wire layout to the generic path above (incl. the per-field
        # u32 length prefixes), same CodecError semantics
        head, entry = _qc_structs(ps, ss)
        data, start = dec._data, dec._pos
        try:
            h, rnd, n = head.unpack_from(data, start)
        except struct.error as e:
            raise CodecError(f"truncated QC header: {e}") from e
        if n == COMPACT_SENTINEL:
            # compact certificate under a scheme-pinned decoder: hand
            # the tail back to the generic codec (scheme gating and
            # size narrowing live in _decode_agg_and_bitmap)
            dec._pos = start + head.size
            _decode_compact_version(dec)
            agg, signers = _decode_agg_and_bitmap(dec)
            qc = cls(hash=Digest(h), round=rnd, agg_sig=agg, signers=signers)
            qc._wire = data[start : dec._pos]
            return qc
        pos = start + head.size
        end = pos + n * entry.size
        if end > len(data):
            raise CodecError(
                f"truncated: QC claims {n} votes past end of input"
            )
        votes = []
        for off in range(pos, end, entry.size):
            lp, pkb, ls, sgb = entry.unpack_from(data, off)
            if lp != ps or ls != ss:
                raise CodecError(
                    f"key/signature sizes ({lp}/{ls}) do not match the "
                    f"committee scheme ({ps}/{ss})"
                )
            votes.append((PublicKey(pkb), Signature(sgb)))
        qc = cls(hash=Digest(h), round=rnd, votes=votes)
        dec._pos = end
        qc._wire = data[start:end]
        return qc

    def __repr__(self) -> str:
        return f"QC({self.hash}, {self.round})"


@dataclass
class TC:
    """Timeout certificate: 2f+1 timeout signatures for one round."""

    round: Round = 0
    # (author, signature, author's high_qc round)
    votes: list[tuple[PublicKey, Signature, Round]] = field(default_factory=list)
    # compact (aggregated) form: per distinct high_qc_round, one G1
    # aggregate over timeout_digest(round, hq_round) plus a signer
    # bitmap; ``votes`` is empty in this form
    groups: list[tuple[Round, Signature, bytes]] | None = None

    @property
    def is_compact(self) -> bool:
        return self.groups is not None

    def high_qc_rounds(self) -> list[Round]:
        if self.is_compact:
            out: list[Round] = []
            for hq, _, bitmap in self.groups:
                out.extend([hq] * _popcount(bitmap))
            return out
        return [r for _, _, r in self.votes]

    def claims(self, committee: Committee | None = None) -> list:
        """Signature claims for the async preverifier: entries signing
        the SAME timeout digest (same high_qc_round — the common storm
        shape) group into shared claims so aggregate-preferring backends
        (BLS) pay one check per group; distinct rounds become single
        claims.  The compact form emits one aggregate claim per group
        (needs ``committee`` to resolve the signer bitmaps)."""
        if self.is_compact:
            if committee is None:
                raise ValueError(
                    "compact TC claims need the committee to resolve "
                    "the signer bitmaps"
                )
            ordered = committee.for_round(self.round).sorted_keys()
            return [
                (
                    "agg",
                    timeout_digest(self.round, hq).to_bytes(),
                    agg.to_bytes(),
                    tuple(
                        pk.to_bytes() for pk in bitmap_keys(bitmap, ordered)
                    ),
                )
                for hq, agg, bitmap in self.groups
            ]
        groups: dict[Round, list] = {}
        for pk, sig, hq_round in self.votes:
            groups.setdefault(hq_round, []).append((pk, sig))
        out = []
        for hq_round, members in groups.items():
            digest = timeout_digest(self.round, hq_round).to_bytes()
            if len(members) == 1:
                pk, sig = members[0]
                out.append(("one", digest, pk.to_bytes(), sig.to_bytes()))
            else:
                out.append(
                    (
                        "shared",
                        digest,
                        tuple(
                            (pk.to_bytes(), sig.to_bytes())
                            for pk, sig in members
                        ),
                    )
                )
        return out

    def verify(
        self,
        committee: Committee,
        verifier: VerifierBackend,
        sigs_verified: bool = False,
    ) -> None:
        committee = committee.for_round(self.round)  # epoch seam
        if self.is_compact:
            ordered = committee.sorted_keys()
            authors: list[PublicKey] = []
            for _, _, bitmap in self.groups:
                authors.extend(bitmap_keys(bitmap, ordered))
            # a node in two groups is authority reuse, caught here
            _check_certificate_weight(authors, committee, TCRequiresQuorum)
            if sigs_verified:
                return
            fn = getattr(verifier, "verify_aggregate_msg", None)
            for hq, agg, bitmap in self.groups:
                pks = [pk.to_bytes() for pk in bitmap_keys(bitmap, ordered)]
                if fn is None or not fn(
                    timeout_digest(self.round, hq), pks, agg.to_bytes()
                ):
                    raise InvalidSignature(
                        f"bad aggregate signature in TC for round {self.round}"
                    )
            return
        _check_certificate_weight(
            [pk for pk, _, _ in self.votes], committee, TCRequiresQuorum
        )
        if sigs_verified:
            return  # claims() discharged by the async preverifier
        # Each entry signs a different digest (its own high_qc_round), so
        # this is the distinct-message batch shape (reference verifies these
        # sequentially, messages.rs:305-311 — here one dispatched batch).
        digests = [
            timeout_digest(self.round, hq_round).to_bytes()
            for _, _, hq_round in self.votes
        ]
        ok = verifier.verify_many(
            digests,
            [pk.to_bytes() for pk, _, _ in self.votes],
            [sig.to_bytes() for _, sig, _ in self.votes],
            aggregate_ok=True,
        )
        if not all(ok):
            raise InvalidSignature(f"bad signature in TC for round {self.round}")

    def encode(self, enc: Encoder) -> None:
        if self.is_compact:
            enc.u64(self.round).u32(COMPACT_SENTINEL).u8(COMPACT_VERSION)
            enc.u8(len(self.groups))
            for hq, agg, bitmap in self.groups:
                enc.u64(hq)
                enc.var_bytes(agg.to_bytes())
                enc.var_bytes(bitmap)
            return
        enc.u64(self.round).u32(len(self.votes))
        for pk, sig, hq in self.votes:
            encode_pk(enc, pk)
            encode_sig(enc, sig)
            enc.u64(hq)

    @classmethod
    def decode(cls, dec: Decoder) -> "TC":
        rnd = dec.u64()
        n = dec.u32()
        if n == COMPACT_SENTINEL:
            _decode_compact_version(dec)
            count = dec.u8()
            if count > MAX_COMPACT_GROUPS:
                raise CodecError(
                    f"compact TC groups {count} exceed cap "
                    f"{MAX_COMPACT_GROUPS}"
                )
            groups = []
            for _ in range(count):
                hq = dec.u64()
                agg, bitmap = _decode_agg_and_bitmap(dec)
                groups.append((hq, agg, bitmap))
            return cls(round=rnd, groups=groups)
        if n > MAX_CERT_VOTES:
            raise CodecError(f"TC vote count {n} exceeds cap {MAX_CERT_VOTES}")
        votes = [
            (decode_pk(dec), decode_sig(dec), dec.u64()) for _ in range(n)
        ]
        return cls(round=rnd, votes=votes)

    def __repr__(self) -> str:
        return f"TC({self.round}, {self.high_qc_rounds()})"


def timeout_digest(round_: Round, high_qc_round: Round) -> Digest:
    """The digest a Timeout (and thus each TC entry) signs."""
    return Digest(sha512_trunc(_round_le(round_) + _round_le(high_qc_round)))


# Protocol-level cap on payload digests per block, enforced on RECEIVED
# blocks in Block.verify (a Byzantine leader must not be able to ship a
# frame-limit-sized block and wedge every node's store path).  The honest
# proposer uses the same constant when draining its buffer.
MAX_BLOCK_PAYLOADS = 512


@dataclass
class Block:
    """A proposal: extends the block certified by ``qc`` with a list of
    payload digests.

    The reference fork narrowed upstream's ``Vec<Digest>`` payload to a
    single digest (reference messages.rs:16-23); this build restores the
    vector form — one round can drain the whole producer backlog, so
    committed-payload throughput is round-rate x batch-size instead of
    being capped at one payload per round."""

    qc: QC = field(default_factory=QC)
    tc: TC | None = None
    author: PublicKey = field(default_factory=PublicKey)
    round: Round = 0
    payloads: tuple[Digest, ...] = ()
    signature: Signature = field(default_factory=Signature)
    # Typed epoch-change payload (consensus/reconfig.py): at most one
    # per block; covered by the block digest (votes certify the op),
    # validated in ``verify`` so a forged epoch change never earns an
    # honest vote, and applied by the commit path via
    # ``CommitteeSchedule.splice``.
    reconfig: ReconfigOp | None = None
    # memoized digest — blocks are immutable after construction and the
    # digest is recomputed on the hot path (signature check, store key,
    # commit walk, log lines): ~20 us of SHA-512 + joins per call
    _digest: Digest | None = field(
        default=None, init=False, repr=False, compare=False
    )
    # memoized wire encoding — a received block is decoded from wire
    # bytes and then re-serialized for the store write (core store_block
    # path); capturing the decode slice makes serialize() a cached
    # return.  Safe for the same reason _digest is: blocks never mutate
    # after construction.
    _wire: bytes | None = field(
        default=None, init=False, repr=False, compare=False
    )

    @classmethod
    def genesis(cls) -> "Block":
        return cls()

    @property
    def parent(self) -> Digest:
        return self.qc.hash

    def digest(self) -> Digest:
        d = self._digest
        if d is None:
            # The reconfig op digest is appended only when present, so
            # every reconfig-free block keeps the pre-reconfiguration
            # preimage byte-for-byte.
            d = Digest(
                sha512_trunc(
                    self.author.to_bytes()
                    + _round_le(self.round)
                    + b"".join(p.to_bytes() for p in self.payloads)
                    + self.qc.hash.to_bytes()
                    + (
                        self.reconfig.digest()
                        if self.reconfig is not None
                        else b""
                    )
                )
            )
            self._digest = d
        return d

    def claims(
        self,
        qc_cache: set | None = None,
        committee: Committee | None = None,
    ) -> list:
        """Signature claims for the async preverifier: the author
        signature, the embedded QC (unless memoized), and the embedded
        TC's entries.  ``committee`` is required when the embedded
        certificates are compact (signer-bitmap resolution)."""
        out = [
            (
                "one",
                self.digest().to_bytes(),
                self.author.to_bytes(),
                self.signature.to_bytes(),
            )
        ]
        out.extend(self.qc.claims(cache=qc_cache, committee=committee))
        if self.tc is not None:
            out.extend(self.tc.claims(committee=committee))
        return out

    def verify(
        self,
        committee: Committee,
        verifier: VerifierBackend,
        qc_cache: set | None = None,
        sigs_verified: bool = False,
    ) -> None:
        # Epoch seam: the author is judged by the block round's
        # committee; each embedded certificate routes ITSELF to its own
        # round's committee inside QC.verify/TC.verify (at an epoch
        # boundary the first new-epoch block carries a QC formed by the
        # previous epoch's validators).  for_round is the identity on a
        # bare Committee.
        com = committee.for_round(self.round)
        if com.stake(self.author) <= 0:
            raise UnknownAuthority(self.author)
        if len(self.payloads) > MAX_BLOCK_PAYLOADS:
            raise MalformedBlock(self.digest())
        if self.reconfig is not None:
            # Raises InvalidReconfig: a block carrying a forged or
            # unauthorized epoch change never earns an honest vote.
            validate_reconfig(
                self.reconfig, committee, self.round, verifier=verifier
            )
        if not sigs_verified and not verifier.verify_one(
            self.digest(), self.author, self.signature
        ):
            raise InvalidSignature(f"bad author signature on block {self}")
        if not self.qc.is_genesis():
            self.qc.verify(
                committee, verifier, cache=qc_cache, sigs_verified=sigs_verified
            )
        if self.tc is not None:
            self.tc.verify(committee, verifier, sigs_verified=sigs_verified)

    def encode(self, enc: Encoder) -> None:
        self.qc.encode(enc)
        enc.flag(self.tc is not None)
        if self.tc is not None:
            self.tc.encode(enc)
        encode_pk(enc, self.author)
        enc.u64(self.round)
        enc.u32(len(self.payloads))
        for p in self.payloads:
            enc.raw(p.to_bytes())
        enc.flag(self.reconfig is not None)
        if self.reconfig is not None:
            self.reconfig.encode(enc)
        encode_sig(enc, self.signature)

    @classmethod
    def decode(cls, dec: Decoder) -> "Block":
        start = dec.mark()
        qc = QC.decode(dec)
        tc = TC.decode(dec) if dec.flag() else None
        author = decode_pk(dec)
        rnd = dec.u64()
        n = dec.u32()
        if n > MAX_BLOCK_PAYLOADS:
            # Block.verify re-checks this for protocol attribution, but
            # the decode-time cap stops a forged count from sizing the
            # digest-vector read at all
            raise CodecError(
                f"block payload count {n} exceeds cap {MAX_BLOCK_PAYLOADS}"
            )
        # one bounds-checked read for the whole digest vector (a block
        # carries up to 512 payload digests — the per-digest raw() call
        # was the hottest decode loop in the profile)
        raw = dec.raw(Digest.SIZE * n)
        payloads = tuple(
            Digest(raw[i : i + Digest.SIZE])
            for i in range(0, Digest.SIZE * n, Digest.SIZE)
        )
        reconfig = ReconfigOp.decode(dec) if dec.flag() else None
        sig = decode_sig(dec)
        block = cls(
            qc=qc,
            tc=tc,
            author=author,
            round=rnd,
            payloads=payloads,
            signature=sig,
            reconfig=reconfig,
        )
        block._wire = dec.since(start)
        return block

    def serialize(self) -> bytes:
        w = self._wire
        if w is None:
            enc = Encoder()
            self.encode(enc)
            w = enc.finish()
            self._wire = w
        return w

    @classmethod
    def deserialize(cls, data: bytes) -> "Block":
        dec = Decoder(data)
        block = cls.decode(dec)
        dec.finish()
        return block

    def __repr__(self) -> str:
        return (
            f"{self.digest()}: B({self.author}, {self.round}, "
            f"{self.qc!r}, {len(self.payloads)} payloads)"
        )

    def __str__(self) -> str:
        return f"B{self.round}"


@dataclass
class Vote:
    """A vote over a block digest, addressed to the next leader."""

    hash: Digest
    round: Round
    author: PublicKey
    signature: Signature = field(default_factory=Signature)
    _digest: Digest | None = field(
        default=None, init=False, repr=False, compare=False
    )

    @classmethod
    def for_block(cls, block: Block, author: PublicKey) -> "Vote":
        """Unsigned vote; the caller signs ``digest()`` via SignatureService."""
        return cls(hash=block.digest(), round=block.round, author=author)

    def digest(self) -> Digest:
        d = self._digest
        if d is None:
            d = Digest(
                sha512_trunc(self.hash.to_bytes() + _round_le(self.round))
            )
            self._digest = d
        return d

    def claim(self) -> tuple:
        """This vote's signature claim for the async preverifier."""
        return (
            "one",
            self.digest().to_bytes(),
            self.author.to_bytes(),
            self.signature.to_bytes(),
        )

    def verify(self, committee: Committee, verifier: VerifierBackend) -> None:
        if committee.for_round(self.round).stake(self.author) <= 0:
            raise UnknownAuthority(self.author)
        if not verifier.verify_one(self.digest(), self.author, self.signature):
            raise InvalidSignature(f"bad signature on vote {self}")

    def encode(self, enc: Encoder) -> None:
        enc.raw(self.hash.to_bytes()).u64(self.round)
        encode_pk(enc, self.author)
        encode_sig(enc, self.signature)

    @classmethod
    def decode(cls, dec: Decoder) -> "Vote":
        ps, ss = dec.pk_size, dec.sig_size
        if ps is not None and ss is not None:
            # struct fast path (scheme-pinned decoder) — same layout and
            # CodecError semantics as the generic path below
            s = _vote_struct(ps, ss)
            try:
                h, rnd, lp, pkb, ls, sgb = s.unpack_from(
                    dec._data, dec._pos
                )
            except struct.error as e:
                raise CodecError(f"truncated vote: {e}") from e
            if lp != ps or ls != ss:
                raise CodecError(
                    f"key/signature sizes ({lp}/{ls}) do not match the "
                    f"committee scheme ({ps}/{ss})"
                )
            dec._pos += s.size
            return cls(
                hash=Digest(h),
                round=rnd,
                author=PublicKey(pkb),
                signature=Signature(sgb),
            )
        return cls(
            hash=Digest(dec.raw(Digest.SIZE)),
            round=dec.u64(),
            author=decode_pk(dec),
            signature=decode_sig(dec),
        )

    def __repr__(self) -> str:
        return f"V({self.author}, {self.round}, {self.hash})"


@dataclass
class Timeout:
    """A round-timeout complaint carrying the sender's highest QC."""

    high_qc: QC
    round: Round
    author: PublicKey
    signature: Signature = field(default_factory=Signature)

    def digest(self) -> Digest:
        return timeout_digest(self.round, self.high_qc.round)

    def verify(
        self,
        committee: Committee,
        verifier: VerifierBackend,
        qc_cache: set | None = None,
        sig_verified: bool = False,
    ) -> None:
        """``sig_verified=True`` skips only the author-signature check —
        for callers that already verified it as part of a burst
        aggregate (Core's timeout-flood batching); the authority/stake
        check and the embedded-QC verification always run."""
        if committee.for_round(self.round).stake(self.author) <= 0:
            raise UnknownAuthority(self.author)
        if not sig_verified and not verifier.verify_one(
            self.digest(), self.author, self.signature
        ):
            raise InvalidSignature(f"bad signature on timeout {self}")
        if not self.high_qc.is_genesis():
            # QC.verify routes itself to its own round's committee
            self.high_qc.verify(committee, verifier, cache=qc_cache)

    def encode(self, enc: Encoder) -> None:
        self.high_qc.encode(enc)
        enc.u64(self.round)
        encode_pk(enc, self.author)
        encode_sig(enc, self.signature)

    @classmethod
    def decode(cls, dec: Decoder) -> "Timeout":
        return cls(
            high_qc=QC.decode(dec),
            round=dec.u64(),
            author=decode_pk(dec),
            signature=decode_sig(dec),
        )

    def __repr__(self) -> str:
        return f"TV({self.author}, {self.round}, {self.high_qc!r})"
