"""Leader election.

Parity target: ``RRLeaderElector`` (reference consensus/src/leader.rs:5-21):
round-robin over the sorted committee public keys. The sorted key list is
computed once (the reference re-sorts per call; the committee is immutable
within an epoch).
"""

from __future__ import annotations

from ..crypto import PublicKey
from .config import Committee
from .messages import Round


class RoundRobinLeaderElector:
    def __init__(self, committee: Committee):
        self._keys: list[PublicKey] = committee.sorted_keys()

    def get_leader(self, round_: Round) -> PublicKey:
        return self._keys[round_ % len(self._keys)]


LeaderElector = RoundRobinLeaderElector
