"""Leader election.

Parity target: ``RRLeaderElector`` (reference consensus/src/leader.rs:5-21):
round-robin over the sorted committee public keys.  Epoch-aware: the
election asks ``for_round`` so a ``CommitteeSchedule`` rotates the
validator set at its boundaries; sorted key lists are cached per epoch
committee (the reference re-sorts per call; a committee is immutable
within an epoch).
"""

from __future__ import annotations

from ..crypto import PublicKey
from .config import Committee
from .messages import Round


class RoundRobinLeaderElector:
    def __init__(self, committee: Committee):
        self._committee = committee
        self._keys_cache: dict[int, list[PublicKey]] = {}

    def get_leader(self, round_: Round) -> PublicKey:
        com = self._committee.for_round(round_)
        keys = self._keys_cache.get(id(com))
        if keys is None:
            keys = com.sorted_keys()
            self._keys_cache[id(com)] = keys
        return keys[round_ % len(keys)]


LeaderElector = RoundRobinLeaderElector
