"""Leader election.

Parity target: ``RRLeaderElector`` (reference consensus/src/leader.rs:5-21):
round-robin over the sorted committee public keys.  Epoch-aware: the
election asks ``for_round`` so a ``CommitteeSchedule`` rotates the
validator set at its boundaries; sorted key lists are cached per epoch
committee (the reference re-sorts per call; a committee is immutable
within an epoch).
"""

from __future__ import annotations

from ..crypto import PublicKey
from .config import Committee
from .messages import Round


class RoundRobinLeaderElector:
    def __init__(self, committee: Committee):
        self._committee = committee
        # id(com) -> (com, sorted keys).  The cache holds a STRONG
        # reference to each committee it has served, which is what makes
        # the id() key sound: a cached committee can never be collected,
        # so its id can never be reused by a different object (ADVICE r3
        # flagged the bare-id() variant's reliance on the schedule's own
        # lifetime for this).
        self._keys_cache: dict[int, tuple[Committee, list[PublicKey]]] = {}

    def get_leader(self, round_: Round) -> PublicKey:
        com = self._committee.for_round(round_)
        hit = self._keys_cache.get(id(com))
        if hit is None:
            hit = (com, com.sorted_keys())
            self._keys_cache[id(com)] = hit
        return hit[1][round_ % len(hit[1])]


LeaderElector = RoundRobinLeaderElector
