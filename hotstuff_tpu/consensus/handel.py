"""Handel-style multi-level vote aggregation (arXiv:1906.05132).

The compact certificate (messages.py, ISSUE 9) makes the QC O(1) on the
wire, but the LEADER still receives n individual votes.  Handel removes
that last O(n): validators are arranged into log2(n) levels over a
seeded permutation, every node exchanges *partial aggregates* (one
aggregate G1 signature + a signer bitmap — exactly the compact-QC
payload) with its mirror block at each level, and the top of the tree
holds a full-coverage aggregate after each node merged O(log n)
partials.  Merging is one G1 point add plus a bitmap OR; disjointness
of the operand bitmaps is checked structurally (bit i set in both =
the same signature counted twice = an invalid aggregate), so a
Byzantine peer cannot inflate weight by replaying coverage.

This module is the protocol plane: deterministic topology, partial
merge rules, and an in-process driver (``simulate``) used by the bench
(`bench.py` agg_qc), the sweep harness (`scripts/agg_check.py`) and the
tests.  Network dissemination of partials rides the existing vote
channels unchanged — a partial is just (agg sig, bitmap), the same
material a compact QC carries, and the leader's QCMaker accepts the
final aggregate exactly as it accepts its own running sum.

Trust base: identical to compact-QC verification — bitmaps resolve
against the committee's sorted key order, aggregation is only over
PoP-checked members, and every receiver re-verifies the final aggregate
with one pairing (``BlsVerifier.verify_aggregate_msg``).
"""

from __future__ import annotations

import hashlib

from .errors import ConsensusError
from .messages import _popcount, bitmap_indices

__all__ = [
    "HandelTopology",
    "PartialAggregate",
    "PartialOverlap",
    "simulate",
]


class PartialOverlap(ConsensusError):
    """Two partials claim the same signer bit — merging would double-
    count a signature (weight inflation)."""

    def __init__(self):
        super().__init__("overlapping signer bitmaps in partial aggregates")


class PartialAggregate:
    """A Handel partial: Σ sig over the signers named by ``bitmap``.

    ``point`` is the running G1 sum (None = empty).  Wire form is
    (48-byte compressed aggregate, bitmap) — the compact-QC payload.
    """

    __slots__ = ("point", "bitmap")

    def __init__(self, point, bitmap: bytes):
        self.point = point
        self.bitmap = bitmap

    @classmethod
    def empty(cls, nbytes: int) -> "PartialAggregate":
        return cls(None, bytes(nbytes))

    @classmethod
    def single(
        cls, sig_bytes: bytes, index: int, nbytes: int
    ) -> "PartialAggregate":
        """One validator's own signature as a level-0 partial."""
        from ..crypto.bls.curve import G1Point

        pt = G1Point.from_bytes(sig_bytes, subgroup_check=False)
        if pt is None:
            raise ConsensusError("undecodable signature in Handel partial")
        bm = bytearray(nbytes)
        bm[index // 8] |= 1 << (index % 8)
        return cls(pt, bytes(bm))

    @property
    def weight(self) -> int:
        return _popcount(self.bitmap)

    def signers(self) -> list[int]:
        return list(bitmap_indices(self.bitmap))

    def merge(self, other: "PartialAggregate") -> "PartialAggregate":
        """Disjoint union: one point add + bitmap OR.  Raises
        ``PartialOverlap`` when any signer bit appears in both."""
        a = int.from_bytes(self.bitmap, "little")
        b = int.from_bytes(other.bitmap, "little")
        if a & b:
            raise PartialOverlap()
        if self.point is None:
            point = other.point
        elif other.point is None:
            point = self.point
        else:
            point = self.point + other.point
        n = max(len(self.bitmap), len(other.bitmap))
        return PartialAggregate(point, (a | b).to_bytes(n, "little"))

    def to_wire(self) -> tuple[bytes, bytes]:
        """(aggregate signature bytes, signer bitmap) — the compact-
        certificate payload.  Raises on the empty partial."""
        if self.point is None:
            raise ConsensusError("empty Handel partial has no aggregate")
        return self.point.to_bytes(), self.bitmap


class HandelTopology:
    """Seeded level structure over n validators.

    A seeded Fisher-Yates permutation maps validator index (committee
    sorted-key order) -> tree position; the permutation reshuffles every
    round (seed = H(domain ‖ round)), so a fixed Byzantine coalition
    cannot permanently occupy one subtree.  At level l (1-based), the
    tree positions split into blocks of 2^l; a node's PARTNER BLOCK is
    the sibling half of its own block — the positions whose partial it
    must obtain to double its coverage.  ceil(log2 n) levels take every
    node from its own signature to full coverage, so a leader merges
    O(log n) partials instead of touching n votes.
    """

    def __init__(self, n: int, seed: bytes):
        if n <= 0:
            raise ValueError("topology needs at least one validator")
        self.n = n
        self.seed = seed
        self.levels = max(1, (n - 1).bit_length())
        # Fisher-Yates driven by a hash counter — deterministic across
        # nodes given (n, seed), no RNG state to share
        perm = list(range(n))
        for i in range(n - 1, 0, -1):
            h = hashlib.blake2b(
                seed + i.to_bytes(4, "little"), digest_size=8
            ).digest()
            j = int.from_bytes(h, "little") % (i + 1)
            perm[i], perm[j] = perm[j], perm[i]
        # validator index -> position, and the inverse
        self.position = {v: p for p, v in enumerate(perm)}
        self.validator_at = perm

    @classmethod
    def for_round(
        cls, n: int, round_: int, domain: bytes = b"hotstuff-handel"
    ) -> "HandelTopology":
        seed = hashlib.blake2b(
            domain + round_.to_bytes(8, "little"), digest_size=16
        ).digest()
        return cls(n, seed)

    def block(self, index: int, level: int) -> range:
        """Tree positions of ``index``'s own block at ``level`` (size
        2^level, clipped to n)."""
        pos = self.position[index]
        size = 1 << level
        start = (pos // size) * size
        return range(start, min(start + size, self.n))

    def partner_block(self, index: int, level: int) -> range:
        """Tree positions whose partial ``index`` needs at ``level``:
        the sibling half of its level block (possibly empty at the
        ragged top of a non-power-of-two committee)."""
        pos = self.position[index]
        size = 1 << level
        half = size >> 1
        start = (pos // size) * size
        if (pos - start) < half:
            lo, hi = start + half, start + size
        else:
            lo, hi = start, start + half
        return range(min(lo, self.n), min(hi, self.n))

    def validators_in(self, positions: range) -> list[int]:
        return [self.validator_at[p] for p in positions]


def simulate(
    topology: HandelTopology,
    signatures: dict[int, bytes],
    nbytes: int | None = None,
) -> tuple[PartialAggregate, int, int]:
    """In-process Handel run: every contributing validator (index ->
    48-byte signature) builds its level-0 partial, partials combine up
    the levels, and the aggregate covering position 0's top block is
    returned — (final partial, merges the top node performed, total
    merges network-wide).  Missing validators simply leave their bits
    clear; the caller checks ``weight`` against its quorum rule.

    The per-node merge count is the headline: it is <= topology.levels
    — O(log n) — however large the committee.
    """
    n = topology.n
    if nbytes is None:
        nbytes = (n + 7) // 8
    # per-position level-0 partials (skip non-contributors)
    partials: dict[int, PartialAggregate | None] = {}
    for pos in range(n):
        v = topology.validator_at[pos]
        sig = signatures.get(v)
        partials[pos] = (
            None
            if sig is None
            else PartialAggregate.single(sig, v, nbytes)
        )
    total_merges = 0
    top_merges = 0
    # combine block pairs bottom-up: after level l every surviving
    # partial covers one 2^l block — exactly the exchange each node
    # performs with its partner block at that level
    for level in range(1, topology.levels + 1):
        size = 1 << level
        half = size >> 1
        nxt: dict[int, PartialAggregate | None] = {}
        for start in range(0, n, size):
            left = partials.get(start)
            right = partials.get(start + half)
            if left is None:
                merged = right
            elif right is None:
                merged = left
            else:
                merged = left.merge(right)
                total_merges += 1
                if start == 0:
                    top_merges += 1
            nxt[start] = merged
        partials = nxt
    final = partials.get(0)
    if final is None:
        raise ConsensusError("no contributions reached the Handel root")
    return final, top_merges, total_merges
