"""The consensus core: the 2-chain HotStuff state machine.

Parity target: reference ``Core`` (consensus/src/core.rs:31-495) — one
actor selecting over {network messages, loopback blocks, round timer},
holding {round, last_voted_round, last_committed_round, high_qc}, with:

- the Jolteon voting rule (safety_rule_1: round > last_voted_round;
  safety_rule_2: extends the previous round's QC, or extends a TC for the
  previous round while qc.round >= max(tc.high_qc_rounds)) — core.rs:160-177;
- the 2-chain commit rule: committing b0 when b0 <- b1 <- block and
  b0.round + 1 == b1.round — core.rs:384-386;
- view change via Timeout/TC aggregation — core.rs:220-318;
- crash-recovery persistence of ConsensusState after every state-changing
  iteration (the fork's addition, core.rs:52-58, 484-492);
- the per-round payload index + latest-round bookkeeping the fork's
  proposer feeds on (core.rs:112-148).

Verification is accumulate-then-dispatch (BASELINE.json): votes/timeouts
enter the aggregator unverified and each certificate's signature set is
batch-verified once at quorum, on the pluggable VerifierBackend (CPU or
TPU kernel).
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..crypto import Digest, PublicKey, SignatureService
from ..crypto.async_service import AsyncVerifyService
from ..crypto.service import VerifierBackend
from ..network import SimpleSender
from ..store import Store
from ..utils.clock import default_clock
from ..utils.codec import Decoder, Encoder
from .aggregator import ROUND_LOOKAHEAD, Aggregator
from .config import Committee, InvalidCommittee
from .errors import ConsensusError, SerializationError, WrongLeader
from .leader import LeaderElector
from .messages import MAX_BLOCK_PAYLOADS, QC, TC, Block, Round, Timeout, Vote
from .reconfig import ReconfigOp, validate_reconfig
from .synchronizer import Synchronizer
from .timer import Timer
from .wire import (
    MAX_SCHEDULE_LINKS,
    TAG_PROPOSE,
    TAG_RECONFIG,
    TAG_TC,
    TAG_TIMEOUT,
    TAG_VOTE,
    decode_schedule_links,
    encode_schedule_links,
    encode_tc,
    encode_timeout,
    encode_vote,
)

log = logging.getLogger(__name__)

CONSENSUS_STATE_KEY = b"consensus_state"
LATEST_ROUND_KEY = b"latest_round"
#: certified schedule links: one (committed reconfig block, certifying
#: QC) pair per applied epoch change — replayed into the schedule at
#: boot and served to joiners via the state-sync manifest
SCHEDULE_LINKS_KEY = b"schedule_links"

# Core event-queue kinds.  The reference selects over three channels
# (core.rs:466-477); this build merges them into ONE queue of tagged
# events: a ready item then costs a plain ``await queue.get()`` (no
# waiter future, no Task) instead of an ``asyncio.wait`` over three
# branch tasks with per-iteration callback add/remove — measured ~1 ms
# of loop machinery per committed block at 4 nodes.  Arrival order
# across kinds is preserved (one FIFO).
EV_MSG = 0  # network message: (tag, payload) from the receiver handler
EV_LOOP = 1  # loopback Block from the proposer/synchronizer
EV_TIMER = 2  # round-timer expiry (from the core's own pump task)


class TaggedEventQueue:
    """Facade presenting one kind-tagged view of the core's merged
    event queue — producers keep the plain ``put`` interface the
    reference's channel topology gives them."""

    __slots__ = ("_inner", "_kind")

    def __init__(self, inner: asyncio.Queue, kind: int):
        self._inner = inner
        self._kind = kind

    async def put(self, item) -> None:
        await self._inner.put((self._kind, item))

    def put_nowait(self, item) -> None:
        self._inner.put_nowait((self._kind, item))

    def qsize(self) -> int:
        return self._inner.qsize()


class LoopbackChannel:
    """The proposer/synchronizer -> core loopback: its OWN bounded
    queue, drained at the top of every core iteration, plus a
    non-blocking wake token into the merged queue for the idle case.

    Why not a tagged slot in the merged FIFO: a message flood would put
    the node's own proposed block (and sync-resumed orphans) behind the
    whole attacker backlog, and the producer would block awaiting a
    slot on a queue shared with hostile traffic — the reference's
    select loop services the loopback branch every wake-up regardless
    of message pressure, and this preserves that bound (<= one batch)."""

    __slots__ = ("_q", "_events")

    def __init__(self, events: asyncio.Queue, capacity: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=capacity)
        self._events = events

    async def put(self, block) -> None:
        await self._q.put(block)
        self._wake()

    def put_nowait(self, block) -> None:
        self._q.put_nowait(block)
        self._wake()

    def _wake(self) -> None:
        # wake an idle core; droppable when the merged queue is full —
        # an actively-iterating core drains us every iteration anyway
        try:
            self._events.put_nowait((EV_LOOP, None))
        except asyncio.QueueFull:
            pass

    def get_nowait(self):
        return self._q.get_nowait()

    def qsize(self) -> int:
        return self._q.qsize()


def make_event_channels(
    capacity: int,
) -> tuple[asyncio.Queue, TaggedEventQueue, LoopbackChannel]:
    """(rx_events, tx_consensus, tx_loopback): the merged core queue,
    the network-message facade, and the priority loopback channel."""
    rx_events: asyncio.Queue = asyncio.Queue(maxsize=capacity)
    return (
        rx_events,
        TaggedEventQueue(rx_events, EV_MSG),
        LoopbackChannel(rx_events, capacity),
    )


def round_key(round_: Round) -> bytes:
    """Store key of the per-round payload-digest index (big-endian, like
    the reference's ``to_be_bytes`` keys, core.rs:117-146)."""
    return round_.to_bytes(8, "big")


def encode_payload_index(digests: list) -> bytes:
    enc = Encoder().u32(len(digests))
    for d in digests:
        enc.raw(d.to_bytes())
    return enc.finish()


def decode_payload_index(data: bytes) -> list:
    from ..crypto import Digest

    dec = Decoder(data)
    n = dec.u32()
    out = [Digest(dec.raw(Digest.SIZE)) for _ in range(n)]
    dec.finish()
    return out


class ConsensusState:
    """The persisted crash-recovery snapshot (core.rs:52-58)."""

    __slots__ = ("round", "last_voted_round", "last_committed_round", "high_qc")

    def __init__(
        self,
        round_: Round = 1,
        last_voted_round: Round = 0,
        last_committed_round: Round = 0,
        high_qc: QC | None = None,
    ):
        self.round = round_
        self.last_voted_round = last_voted_round
        self.last_committed_round = last_committed_round
        self.high_qc = high_qc if high_qc is not None else QC.genesis()

    def serialize(self) -> bytes:
        enc = (
            Encoder()
            .u64(self.round)
            .u64(self.last_voted_round)
            .u64(self.last_committed_round)
        )
        self.high_qc.encode(enc)
        return enc.finish()

    @classmethod
    def deserialize(cls, data: bytes) -> "ConsensusState":
        dec = Decoder(data)
        state = cls(dec.u64(), dec.u64(), dec.u64(), QC.decode(dec))
        dec.finish()
        return state


class ProposerMessage:
    """Core -> Proposer commands (reference proposer.rs:17-21).

    ``allow_empty`` (this build's addition): the core sets it when the
    commit pipeline still holds uncommitted payload-carrying blocks — a
    leader with an empty payload buffer may then propose an EMPTY block
    so the 2-chain rule can commit the in-flight payloads within two
    fast rounds, instead of parking their commit until the producer's
    next burst arrives (bursty clients otherwise couple commit latency
    to their burst interval)."""

    __slots__ = (
        "kind", "round", "qc", "tc", "rounds", "allow_empty", "payloads",
        "committed_round", "op",
    )

    MAKE = "make"
    CLEANUP = "cleanup"
    RECONFIG = "reconfig"

    def __init__(
        self,
        kind,
        round_=0,
        qc=None,
        tc=None,
        rounds=(),
        allow_empty=False,
        payloads=frozenset(),
        committed_round=0,
        op=None,
    ):
        self.kind = kind
        self.round = round_
        self.qc = qc
        self.tc = tc
        self.rounds = list(rounds)
        self.allow_empty = allow_empty
        # a validated ReconfigOp awaiting our next leader slot (RECONFIG)
        self.op = op
        # committed payload digests the proposer must drop from its
        # buffer, and the round the chain is committed through — any of
        # our in-flight proposals at <= committed_round whose payloads
        # are not in the set are orphaned for good and get re-buffered
        # (see Core._commit / Proposer orphan recovery)
        self.payloads = payloads
        self.committed_round = committed_round

    @classmethod
    def make(
        cls, round_: Round, qc: QC, tc: TC | None, allow_empty: bool = False
    ) -> "ProposerMessage":
        return cls(cls.MAKE, round_=round_, qc=qc, tc=tc, allow_empty=allow_empty)

    @classmethod
    def cleanup(
        cls, rounds: list[Round], payloads=frozenset(), committed_round=0
    ) -> "ProposerMessage":
        return cls(
            cls.CLEANUP,
            rounds=rounds,
            payloads=payloads,
            committed_round=committed_round,
        )

    @classmethod
    def reconfig(cls, op: ReconfigOp) -> "ProposerMessage":
        return cls(cls.RECONFIG, op=op)


class Core:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        verifier: VerifierBackend,
        store: Store,
        leader_elector: LeaderElector,
        synchronizer: Synchronizer,
        timeout_delay_ms: int,
        rx_events: asyncio.Queue,
        rx_loopback: "LoopbackChannel",
        tx_proposer: asyncio.Queue,
        tx_commit: asyncio.Queue,
        network: SimpleSender | None = None,
        timeout_backoff: float = 2.0,
        timeout_cap_ms: int = 60_000,
        payload_bodies=None,
        telemetry=None,
        adversary=None,
        state_machine=None,
    ):
        self.name = name
        self.committee = committee
        self.signature_service = signature_service
        self.verifier = verifier
        self.store = store
        self.leader_elector = leader_elector
        self.synchronizer = synchronizer
        self.rx_events = rx_events
        self.rx_loopback = rx_loopback
        self._timer_ack = asyncio.Event()
        self.tx_proposer = tx_proposer
        self.tx_commit = tx_commit
        # consensus.PayloadBodies: committed payload bodies leave the
        # receiver's eviction budget (they became history)
        self.payload_bodies = payload_bodies
        self.round: Round = 1
        self.last_voted_round: Round = 0
        self.last_committed_round: Round = 0
        # Highest payload-carrying block round seen (in-memory latency
        # hint for allow_empty proposals; resets to 0 on crash recovery,
        # which merely restores the reference's defer-until-payload
        # behavior until the next payload block flows through).
        self.last_payload_round: Round = 0
        self.high_qc: QC = QC.genesis()
        self.timer = Timer(timeout_delay_ms)
        # Exponential view-change backoff (config.Parameters docstring):
        # consecutive local timeouts grow the round timer geometrically;
        # observing a NEWER QC (real progress) snaps it back to base.
        self._timeout_base_ms = timeout_delay_ms
        self._timeout_backoff = timeout_backoff
        self._timeout_cap_ms = timeout_cap_ms
        self._timeout_exponent = 0
        # TC advances since the last QC advance (see _advance_round)
        self._consecutive_tcs = 0
        # The round most recently advanced past via a TC — the adaptive
        # adversary's ambush-leader trigger reads this through the
        # state view (faults/adaptive.py); None until the first TC.
        self._last_tc_round: Round | None = None
        # Did the current round show any sign of life (a proposal for
        # it)?  An IDLE timeout — no proposal seen and no uncommitted
        # payload block in flight — is the committee waiting for
        # payloads (the proposer defers empty makes), NOT a liveness
        # failure: growing the view-change backoff there compounds into
        # multi-second timers before the first transaction arrives
        # (measured: a WAN f=3 committee wedged to zero commits because
        # boot-time idle rounds pushed the timer to 16 s+).
        self._saw_proposal = False
        # Reconfiguration (docs/RECONFIG.md): the epoch the node is
        # operating under.  None until run() sets it AFTER crash
        # recovery and the state-sync bootstrap — initializing earlier
        # would make a restarted node re-log old epoch activations at
        # wrong rounds, breaking the epoch-agreement invariant.
        self._active_epoch: int | None = None
        # Retirement: once an activated epoch excludes this node, it
        # keeps serving (Helper / state-sync / boundary certificates)
        # for a grace window of rounds, then flips ``retired`` — the
        # run loop drains events without processing and node/main.py
        # shuts the process down cleanly.
        self._retire_after: Round | None = None
        self._grace_rounds = int(
            os.environ.get("HOTSTUFF_RECONFIG_GRACE_ROUNDS", "16")
        )
        self.retired = False
        # Byzantine adversary plane (faults/adversary.py): None on
        # honest nodes; on attacking nodes the vote/timeout/commit
        # seams below consult it for the active policy windows.
        self.adversary = adversary
        # Replicated execution layer (store/state.py): committed blocks
        # are applied in commit order and summarized by a state root.
        self.state = state_machine
        # Boot-time snapshot catch-up (statesync.StateSyncClient), set
        # by Consensus.spawn on recovering nodes; run() consults it
        # once, right after load_state.
        self.state_sync = None
        self.aggregator = Aggregator(committee, verifier, self_key=name)
        # Async claim preverifier (crypto/async_service.py): device
        # backends get a coalescing off-loop dispatch service (shared
        # across in-process cores); CPU backends evaluate inline.
        self.averifier = AsyncVerifyService.for_backend(verifier)
        self.network = network if network is not None else SimpleSender()
        # Memo of QC cache-keys that already verified against this
        # committee (messages.QC.verify): under a view-change storm all
        # n timeouts carry the SAME high_qc — without the memo the most
        # expensive check in the protocol runs n times per storm.
        # Bounded: cleared when full (worst case = one re-verification).
        self._verified_qcs: set[bytes] = set()
        self.state_changed = False
        self._task: asyncio.Task | None = None
        # per-node logger so multi-node (in-process) runs are attributable
        self.log = logging.getLogger(f"{__name__}.{str(name)[:8]}")
        # telemetry (telemetry/__init__.py): every hook below is guarded
        # by `if self._trace is not None` — with telemetry off the hot
        # path pays one attribute test and nothing else
        self.telemetry = telemetry
        self._trace = telemetry.trace if telemetry is not None else None
        # flight recorder (telemetry/journal.py): same guard discipline —
        # journaling off means one attribute test per site and no writes
        self._journal = telemetry.journal if telemetry is not None else None
        if telemetry is not None:
            telemetry.gauge(
                "core_round", "Current consensus round", fn=lambda: self.round
            )
            telemetry.gauge(
                "core_epoch",
                "Active committee epoch at the current round",
                fn=lambda: self.committee.for_round(self.round).epoch,
            )
            telemetry.gauge(
                "core_event_queue_depth",
                "Merged core event queue occupancy",
                fn=rx_events.qsize,
            )
            telemetry.gauge(
                "core_loopback_depth",
                "Priority loopback channel occupancy",
                fn=rx_loopback.qsize,
            )
            telemetry.gauge(
                "core_timer_resets",
                "Round timer re-arms (rounds entered + backoff restarts)",
                fn=lambda: self.timer.resets,
            )
            from .messages import QC_CACHE_STATS

            # process-wide (module-level) by design: co-located nodes
            # share the dedup the counter is meant to surface
            telemetry.gauge(
                "qc_verify_cache_hit",
                "QC verifications skipped via the per-digest verify "
                "memo (same QC via Propose / sync reply / TC high-QC)",
                fn=lambda: QC_CACHE_STATS["hits"],
            )
            telemetry.add_section("aggregator", self.aggregator.stats)

    # ---- persistence (fork additions, core.rs:76-86, 112-153) --------------

    async def load_state(self) -> None:
        data = await self.store.read(CONSENSUS_STATE_KEY)
        if data is None:
            return
        state = ConsensusState.deserialize(data)
        self.round = state.round
        self.last_voted_round = state.last_voted_round
        self.last_committed_round = state.last_committed_round
        self.high_qc = state.high_qc
        self.log.info("Recovered consensus state at round %d", self.round)

    async def persist_state(self) -> None:
        state = ConsensusState(
            self.round,
            self.last_voted_round,
            self.last_committed_round,
            self.high_qc,
        )
        await self.store.write(CONSENSUS_STATE_KEY, state.serialize())

    async def store_block(self, block: Block) -> None:
        await self.store.write(block.digest().to_bytes(), block.serialize())

        # Maintain the per-round payload index + latest-round key the
        # proposer's payload buffering feeds on (core.rs:117-148).
        latest_raw = await self.store.read(LATEST_ROUND_KEY)
        latest = int.from_bytes(latest_raw, "big") if latest_raw else 0
        if latest == block.round:
            raw = await self.store.read(round_key(block.round))
            payloads = decode_payload_index(raw) if raw else []
            known = set(payloads)
            for p in block.payloads:
                if p not in known:
                    known.add(p)
                    payloads.append(p)
        elif latest < block.round:
            payloads = list(block.payloads)
        else:
            self.log.warning("The block round is less than the last round")
            return
        await self.store.write(round_key(block.round), encode_payload_index(payloads))
        await self.store.write(LATEST_ROUND_KEY, round_key(block.round))

    # ---- voting and committing ---------------------------------------------

    def _increase_last_voted_round(self, target: Round) -> None:
        self.last_voted_round = max(self.last_voted_round, target)
        self.state_changed = True

    async def _make_vote(self, block: Block) -> Vote | None:
        safety_rule_1 = block.round > self.last_voted_round
        safety_rule_2 = block.qc.round + 1 == block.round
        if block.tc is not None:
            can_extend = block.tc.round + 1 == block.round
            can_extend &= block.qc.round >= max(block.tc.high_qc_rounds())
            safety_rule_2 |= can_extend
        if not (safety_rule_1 and safety_rule_2):
            return None

        # Ensure we won't vote for contradicting blocks.  last_voted_round
        # MUST be durable before the vote can leave this node: a crash
        # between send and persist would recover a stale value and allow
        # an equivocating re-vote for these rounds (a BFT safety
        # violation).  The end-of-loop persist is only a catch-all for
        # non-safety-critical state; this is the safety-critical write.
        self._increase_last_voted_round(block.round)
        await self.persist_state()
        self.state_changed = False
        vote = Vote.for_block(block, self.name)
        vote.signature = await self.signature_service.request_signature(
            vote.digest()
        )
        return vote

    async def _commit(self, block: Block, cert_qc: QC) -> None:
        """Commit ``block`` and its uncommitted ancestors.  ``cert_qc``
        is the QC certifying ``block`` itself (the 2-chain rule's b1.qc)
        — committed reconfig blocks persist it as the certified schedule
        link a joiner verifies the epoch change with."""
        if self.last_committed_round >= block.round:
            return

        # Commit the entire chain up to `block` (needed after view-change),
        # oldest first.
        to_commit = [block]
        parent = block
        while self.last_committed_round + 1 < parent.round:
            ancestor = await self.synchronizer.get_parent_block(
                parent, floor=self.last_committed_round
            )
            if ancestor is None:
                raise SerializationError(
                    "missing ancestor while committing a delivered chain"
                )
            if ancestor.round <= self.last_committed_round:
                # snapshot barrier (genesis stand-in) or an ancestor the
                # cursor already covers: nothing below this point needs
                # (re-)committing
                break
            to_commit.append(ancestor)
            parent = ancestor

        self.last_committed_round = block.round
        self.state_changed = True

        # certifying QC per chain position: to_commit[0] (the head) is
        # certified by the caller's cert_qc; every deeper ancestor by
        # its child's embedded qc (child.qc.hash == parent.digest())
        cert_qcs = [cert_qc] + [b.qc for b in to_commit[:-1]]

        committed_payloads: set = set()
        for b, cqc in zip(reversed(to_commit), reversed(cert_qcs)):
            await self.tx_commit.put(b)
            committed_payloads.update(b.payloads)
            if self._trace is not None:
                self._trace.mark_committed(b.digest().to_bytes(), b.round)
            if self._journal is not None:
                self._journal.record("commit", b.round, b.digest())
            # NOTE: this log entry is used to compute performance.
            # One info line per block in the chain walk — a DELIBERATE
            # divergence from the reference, which info-logs only the
            # head and debug-logs the rest (core.rs:204-209): head-only
            # logging hides the other blocks' payloads from the harness
            # and undercounts TPS after every view change.
            reported = b.digest()
            shadow = None
            adversary = self.adversary
            if (
                adversary is not None
                and adversary.is_shadow_committer
                and adversary.active("collude")
                and b.author in adversary.colluder_names
            ):
                # collude policy: the designated shadow committer
                # reports the shadow branch for colluder-led rounds —
                # a REAL divergent history the safety checker must
                # catch and attribute to the colluding authorities
                shadow = adversary.shadow_block(b).digest()
                reported = shadow
                adversary.count("byz_shadow_commits")
                adversary.record("shadow-commit", b.round, reported)
                self.log.info(
                    "byz shadow-commit round %d -> %s", b.round, reported
                )
            self.log.info("Committed block %d -> %s", b.round, reported)
            if self.state is not None:
                # execution layer: apply in commit order; the REPORTED
                # root chains over the reported (possibly shadow)
                # digests, so a colluder's claimed state diverges
                # exactly where its claimed digest log does
                root = self.state.apply_block(b, reported_digest=shadow)
                if root is not None:
                    if self._journal is not None:
                        self._journal.record("state.apply", b.round, b.digest())
                    # NOTE: this log entry is used to compute performance.
                    self.log.info(
                        "State root %d -> %s (round %d)",
                        self.state.version,
                        Digest(root),
                        b.round,
                    )
            if b.reconfig is not None:
                await self._apply_reconfig(b, cqc)
        # Tell the proposer what committed: (a) it prunes those digests
        # from its buffer — with single-homed clients (node/client.py)
        # queues are disjoint so this is defense-in-depth against
        # producers that DO multi-home a payload (each would otherwise
        # be re-proposed by every node that buffered it); (b) the
        # committed_round lets it resolve its in-flight proposals —
        # payloads of orphaned blocks return to the buffer (orphan
        # recovery; the reference instead drops whole per-round buckets
        # on cleanup, proposer.rs:164-173, losing them entirely).
        if self.payload_bodies is not None:
            self.payload_bodies.mark_committed(committed_payloads)
        await self.tx_proposer.put(
            ProposerMessage.cleanup(
                [],
                payloads=committed_payloads,
                committed_round=self.last_committed_round,
            )
        )

    def _update_high_qc(self, qc: QC) -> None:
        if qc.round > self.high_qc.round:
            self.high_qc = qc
            self.state_changed = True

    # ---- reconfiguration (docs/RECONFIG.md) --------------------------------

    async def _apply_reconfig(self, block: Block, cert_qc: QC) -> None:
        """A committed block carries an epoch change: splice the new
        committee into the shared schedule at ``block.round + margin``
        — deterministic across nodes, so every honest node activates
        the same epoch at the same round — and persist the certified
        link for crash recovery and joiners."""
        op = block.reconfig
        if not hasattr(self.committee, "splice"):
            # a bare (non-schedule) committee cannot rotate — tests
            # spawning Core directly on a plain Committee stay valid
            self.log.warning(
                "Reconfig committed at round %d but the committee is "
                "not a schedule; ignoring", block.round,
            )
            return
        activation = block.round + op.margin
        try:
            spliced = self.committee.splice(activation, op.new_committee)
        except InvalidCommittee as e:
            # defense in depth: Block.verify already ran the full gate,
            # so only a replayed/conflicting splice can land here
            self.log.warning(
                "Reconfig committed at round %d not applied: %s",
                block.round, e,
            )
            return
        if not spliced:
            return  # exact replay (crash-recovery re-commit)
        # NOTE: this log entry is used by the reconfiguration harness.
        self.log.info(
            "Reconfig committed at round %d: epoch %d activates at "
            "round %d (margin %d)",
            block.round, op.new_committee.epoch, activation, op.margin,
        )
        if self._journal is not None:
            self._journal.record("reconfig.commit", block.round, block.digest())
            self._journal.flush()
        # pre-warm native verifier key tables for the incoming epoch so
        # the first boundary certificate pays no key-parsing latency
        pre = getattr(self.verifier, "precompute", None)
        if pre is not None:
            try:
                pre([k.to_bytes() for k in op.new_committee.sorted_keys()])
            except Exception as e:  # noqa: BLE001 — warm-up only
                self.log.debug("verifier precompute failed: %s", e)
        await self._persist_schedule_link(block, cert_qc)

    async def _persist_schedule_link(
        self, block: Block, cert_qc: QC
    ) -> None:
        raw = await self.store.read(SCHEDULE_LINKS_KEY)
        links = decode_schedule_links(raw) if raw else []
        enc = Encoder()
        cert_qc.encode(enc)
        links.append((block.serialize(), enc.finish()))
        if len(links) > MAX_SCHEDULE_LINKS:
            # beyond the wire cap a joiner can no longer verify from
            # genesis — drop the oldest link and say so (joiners must
            # then boot from a committee file of a later epoch)
            self.log.warning(
                "Schedule link list exceeds %d; dropping the oldest "
                "(joiners need a post-genesis committee file)",
                MAX_SCHEDULE_LINKS,
            )
            links = links[-MAX_SCHEDULE_LINKS:]
        await self.store.write(SCHEDULE_LINKS_KEY, encode_schedule_links(links))

    def _maybe_activate_epoch(self) -> None:
        """Epoch-boundary detection at the CURRENT round, run on every
        round advance.  Crossing a boundary also snaps the view-change
        backoff: the backed-off timer measured the OLD committee's
        liveness trouble, and carrying it into a fresh validator set
        costs several idle multi-second views right when the handoff
        gap is being measured (the exponent was previously never reset
        on activation — epoch-boundary bugfix)."""
        if self._active_epoch is None:
            return
        epoch = self.committee.for_round(self.round).epoch
        if epoch == self._active_epoch:
            return
        self._consecutive_tcs = 0
        if self._timeout_exponent:
            self._timeout_exponent = 0
            self.timer.set_duration_ms(self._timeout_base_ms)
            self.timer.reset()
        self._activate_epoch(epoch)

    def _activate_epoch(self, epoch: int) -> None:
        self._active_epoch = epoch
        # Report the SCHEDULE's activation round, not wherever this node
        # happens to be: a joiner (or a state-synced straggler) crosses
        # the boundary mid-catch-up at some later round, and the
        # epoch-agreement invariant compares the activation POINT — the
        # deterministic commit_round + margin every honest node shares.
        reported_round = self.round
        for from_round, com in getattr(self.committee, "entries", ()):
            if com.epoch == epoch:
                reported_round = from_round
                break
        adversary = self.adversary
        snipes = (
            adversary.wants("reconfig", self.round)
            if adversary is not None else False
        )
        if snipes:
            # reconfig policy (shadow half): claim the activation at a
            # skewed round — a divergent epoch history the
            # epoch-agreement invariant must catch and attribute.  The
            # reconfig-sniper fires the same attack, but only inside
            # the epoch-activation margin (wants returns its token).
            adversary.mark_adaptive(snipes, self.round, self.log)
            reported_round = reported_round + 1 + (epoch % 3)
            adversary.count("byz_shadow_epochs")
            adversary.record("reconfig-shadow", self.round)
            self.log.info(
                "byz reconfig-shadow epoch %d round %d -> %d",
                epoch, self.round, reported_round,
            )
        # NOTE: this log entry is used by the epoch-agreement invariant.
        self.log.info("Epoch %d activated at round %d", epoch, reported_round)
        if self._journal is not None:
            self._journal.record("reconfig.activate", self.round)
            self._journal.flush()
        if (
            self._retire_after is None
            and self.committee.for_round(self.round).stake(self.name) <= 0
        ):
            self._retire_after = self.round + self._grace_rounds
            self.log.info(
                "Retiring: epoch %d excludes this node; serving a grace "
                "window through round %d", epoch, self._retire_after,
            )

    # ---- round advancement and proposals -----------------------------------

    def _advance_round(self, round_: Round, *, via_tc: bool = False) -> None:
        if round_ < self.round:
            return
        # View-change backoff policy:
        # - QC advance = real progress: snap timer and TC streak to base.
        # - FIRST TC after progress: retry at base once — with
        #   round-robin leaders a single crashed node deterministically
        #   costs TWO view changes per lap (the preceding round's QC
        #   dies with it: votes route to the dead collector; then its
        #   own round stalls), and paying base + backed-off for a
        #   structural event halves fault throughput for nothing.
        # - CONSECUTIVE TCs (no QC in between): keep the backed-off
        #   timer — under a uniformly slow but live network TCs keep
        #   forming, and resetting on every TC would pin the timer at
        #   base forever (endless view changes, zero commits).  Growth
        #   is delayed by one view change but remains geometric, so
        #   convergence under asynchrony is preserved.
        if via_tc:
            self._consecutive_tcs += 1
            self._last_tc_round = round_
            snap = self._consecutive_tcs == 1
            if self._trace is not None:
                self._trace.mark_tc_advance()
            if self._journal is not None:
                # view change: force-flush so the record survives even if
                # the node wedges in the new view
                self._journal.record("tc", round_)
                self._journal.flush()
        else:
            self._consecutive_tcs = 0
            snap = True
        if snap and self._timeout_exponent:
            self._timeout_exponent = 0
            self.timer.set_duration_ms(self._timeout_base_ms)
        self.timer.reset()
        self.round = round_ + 1
        self._saw_proposal = False
        self._maybe_activate_epoch()
        self.state_changed = True
        if self._journal is not None:
            self._journal.record("round.enter", self.round)
        self.log.debug("Moved to round %d", self.round)
        self.aggregator.cleanup(self.round)
        # Tell the proposer the chain moved on, so a make deferred while
        # the payload buffer was empty can't later fire for a dead round
        # (best effort — a full queue just means the signal is late).
        try:
            self.tx_proposer.put_nowait(
                ProposerMessage.cleanup([self.round - 1])
            )
        except asyncio.QueueFull:
            pass

    async def _generate_proposal(self, tc: TC | None) -> None:
        await self.tx_proposer.put(
            ProposerMessage.make(
                self.round,
                self.high_qc,
                tc,
                allow_empty=self.last_payload_round > self.last_committed_round,
            )
        )

    async def _cleanup_proposer(self, b0: Block, b1: Block, block: Block) -> None:
        await self.tx_proposer.put(
            ProposerMessage.cleanup([b0.round, b1.round, block.round])
        )

    def _process_qc(self, qc: QC) -> None:
        if self._trace is not None and not qc.is_genesis():
            self._trace.mark_qc_formed(qc.hash.to_bytes())
        # journal only NEW high QCs: every proposal/timeout re-carries
        # older QCs and re-recording them would swamp the timeline
        if (
            self._journal is not None
            and not qc.is_genesis()
            and qc.round > self.high_qc.round
        ):
            self._journal.record("qc", qc.round, qc.hash)
        self._advance_round(qc.round)
        self._update_high_qc(qc)

    # ---- message handlers ---------------------------------------------------

    async def _handle_vote(self, vote: Vote, sig_verified: bool = False) -> None:
        self.log.debug("Processing %r", vote)
        if vote.round < self.round:
            return
        # Accumulate-then-dispatch: authority/stake checks happen on entry;
        # signatures were either pre-verified by the burst preverifier
        # (sig_verified) or batch-verified at quorum inside the aggregator.
        qc = self.aggregator.add_vote(vote, self.round, sig_verified=sig_verified)
        if qc is not None:
            self.log.debug("Assembled %r", qc)
            # qc.form marks the FORMATION moment at the assembling node
            # (quorum-th vote folded in), distinct from the ``qc`` edge
            # which marks high-QC adoption — the critical-path engine
            # (telemetry/critpath.py) attributes agg.form from it
            if self._journal is not None and not qc.is_genesis():
                self._journal.record("qc.form", qc.round, qc.hash)
            self._process_qc(qc)
            if self.name == self.leader_elector.get_leader(self.round):
                await self._generate_proposal(None)

    def _qc_cache(self) -> set:
        if len(self._verified_qcs) > 4_096:
            self._verified_qcs.clear()
        return self._verified_qcs

    async def _handle_timeout(
        self, timeout: Timeout, sig_verified: bool = False
    ) -> None:
        self.log.debug("Processing %r", timeout)
        if timeout.round < self.round:
            return
        # Verify on entry like the reference (core.rs:288): the author's
        # single signature is checked FIRST (cheap), so a spoofed timeout
        # cannot force the expensive embedded-QC batch verify — and the
        # TCMaker can then emit TCs from pre-verified entries.
        # ``sig_verified``: the burst drain already aggregate-verified
        # this timeout's author signature (_preverify_timeout_burst).
        try:
            timeout.verify(
                self.committee,
                self.verifier,
                qc_cache=self._qc_cache(),
                sig_verified=sig_verified,
            )
        except ConsensusError:
            # honest defense seam: a timeout whose author signature or
            # embedded certificate fails verification (forged QCs from
            # the adversary plane land here after the burst preverifier
            # refuses their claims)
            self.aggregator.qc_rejects += 1
            self.log.info(
                "qc reject: invalid certificate in timeout from %s "
                "round %d", str(timeout.author)[:8], timeout.round,
            )
            raise
        self._process_qc(timeout.high_qc)

        tc = self.aggregator.add_timeout(timeout, self.round)
        if tc is not None:
            self.log.debug("Assembled %r", tc)
            self._advance_round(tc.round, via_tc=True)

            addresses = [
                addr for _, addr in self.committee.broadcast_addresses(self.name)
            ]
            await self.network.broadcast(addresses, encode_tc(tc))

            if self.name == self.leader_elector.get_leader(self.round):
                await self._generate_proposal(tc)
        elif (
            timeout.round > self.round
            and self.aggregator.timeout_weight(timeout.round)
            >= self.committee.for_round(timeout.round).validity_threshold()
        ):
            # Round synchronization (timeout-join): f+1 stake — at least
            # one honest authority — is provably timing out a round
            # AHEAD of ours, so that round is legitimate; join it and
            # emit our own timeout so the TC can complete.  Without
            # this, a node that missed a one-shot TC broadcast (e.g. it
            # was inside its state-sync bootstrap when the round
            # turned) wedges one round behind a committee whose TC
            # needs this node's timeout — mutual starvation where every
            # node re-broadcasts timeouts for a round no one else is
            # in.  A snapshot rejoin under partition makes that window
            # routine rather than exotic.
            self.log.info(
                "Joining timeout round %d (round sync, own round %d)",
                timeout.round,
                self.round,
            )
            self.round = timeout.round
            self._saw_proposal = False
            self._maybe_activate_epoch()
            self.state_changed = True
            self.aggregator.cleanup(self.round)
            await self._local_timeout_round()

    async def _local_timeout_round(self) -> None:
        if self.committee.for_round(self.round).stake(self.name) <= 0:
            # not a member of the round's epoch (a joiner before its
            # activation round, a retiree after): our timeout carries
            # no stake and honest receivers would reject it — keep
            # observing, just re-arm the timer
            self.timer.reset()
            return
        self.log.warning("Timeout reached for round %d", self.round)
        if self._trace is not None:
            self._trace.mark_timeout()
        if self._journal is not None:
            # timeout: a force-flush point (the whole point of a flight
            # recorder is surviving the interesting failures)
            self._journal.record("timeout", self.round)
            self._journal.flush()
        self._increase_last_voted_round(self.round)
        # durable before the Timeout broadcast, same safety argument as
        # in _make_vote
        await self.persist_state()
        self.state_changed = False
        timeout = Timeout(high_qc=self.high_qc, round=self.round, author=self.name)
        timeout.signature = await self.signature_service.request_signature(
            timeout.digest()
        )
        self.log.debug("Created %r", timeout)
        # one more consecutive view change -> stretch the next round's
        # timer (a dead-leader round costs ~one base delay; a genuinely
        # slow network backs off geometrically instead of storming).
        # IDLE timeouts — no proposal seen for the round and nothing
        # uncommitted in flight — keep the base timer: that's the
        # committee pacing itself to payload arrival (deferred makes),
        # not a liveness failure (see _saw_proposal).
        active = (
            self._saw_proposal
            or self.last_payload_round > self.last_committed_round
        )
        if active:
            self._timeout_exponent += 1
            self.timer.set_duration_ms(
                min(
                    self._timeout_base_ms
                    * self._timeout_backoff**self._timeout_exponent,
                    self._timeout_cap_ms,
                )
            )
        self.timer.reset()

        addresses = [
            addr for _, addr in self.committee.broadcast_addresses(self.name)
        ]
        await self.network.broadcast(addresses, encode_timeout(timeout))
        # own timeout: we just signed it; the embedded high_qc is ours
        # (already verified when it was adopted)
        await self._handle_timeout(timeout, sig_verified=True)

    async def _process_block(self, block: Block) -> None:
        self.log.debug("Processing %r", block)
        if block.round >= self.round:
            # a (verified or self-made) proposal for the current round:
            # the committee is live — timeouts from here on are real
            # liveness signals, not idle pacing (_saw_proposal)
            self._saw_proposal = True
        if self._trace is not None:
            self._trace.mark_proposed(block.digest().to_bytes(), block.round)

        # b0 <- |qc0; b1| <- |qc1; block|: suspend if ancestors are missing
        # (the synchronizer will re-inject the block via loopback).  The
        # floor is the snapshot barrier: after a QC-anchored snapshot
        # adoption, ancestry at or below the commit cursor is certified
        # by the block's own verified QC and already covered by the
        # snapshot — it resolves to the genesis stand-in instead of a
        # fetch, so the node can vote (and restore quorum) immediately.
        ancestors = await self.synchronizer.get_ancestors(
            block, floor=self.last_committed_round
        )
        if ancestors is None:
            self.log.debug("Processing of %s suspended: missing parent", block.digest())
            return
        b0, b1 = ancestors

        await self.store_block(block)
        if block.payloads and block.round > self.last_payload_round:
            self.last_payload_round = block.round
            # If we lead the current round and our Make went out before
            # this payload block was processed (votes can overtake the
            # proposal), the proposer may be sitting on a deferred Make
            # with a stale allow_empty=False — with an idle producer the
            # commit would then wait out the full view-change timeout.
            # Re-issue; the proposer drops it if a block for this round
            # was already made.  Skip the TC edge (high_qc not adjacent):
            # re-issuing without the original TC would propose a block
            # followers refuse to vote for.
            if (
                self.name == self.leader_elector.get_leader(self.round)
                and self.high_qc.round + 1 == self.round
                and self.last_payload_round > self.last_committed_round
            ):
                await self._generate_proposal(None)
        await self._cleanup_proposer(b0, b1, block)

        # 2-chain commit rule.
        if b0.round + 1 == b1.round:
            await self._commit(b0, b1.qc)

        # Prevents bad leaders from proposing blocks far in the future.
        if block.round != self.round:
            return

        adversary = self.adversary
        withholds = (
            adversary.wants("withhold", block.round)
            if adversary is not None else False
        )
        if withholds:
            # withhold policy: receive, never vote — the committee must
            # reach quorum without us (timeouts), and recover liveness
            # once the window closes.  Also the reconfig-sniper's
            # withhold half (wants returns its token near an epoch
            # activation boundary).
            adversary.mark_adaptive(withholds, block.round, self.log)
            adversary.count("byz_votes_withheld")
            adversary.record("withhold", block.round, block.digest())
            self.log.info(
                "byz withhold vote round %d -> %s",
                block.round, block.digest(),
            )
            return

        if self.committee.for_round(block.round).stake(self.name) <= 0:
            # not a member of this block's epoch: observe the chain
            # (commits above still ran), never vote
            return

        vote = await self._make_vote(block)
        if vote is not None:
            self.log.debug("Created %r", vote)
            if self._trace is not None:
                self._trace.mark_first_vote(block.digest().to_bytes())
            next_leader = self.leader_elector.get_leader(self.round + 1)
            if self._journal is not None:
                self._journal.record(
                    "vote.send",
                    block.round,
                    block.digest(),
                    str(next_leader)[:8],
                )
            if next_leader == self.name:
                # own vote: we just signed it — no verification needed
                await self._handle_vote(vote, sig_verified=True)
            else:
                surfs = (
                    adversary.wants("vote-delay", block.round)
                    if adversary is not None else False
                )
                if surfs:
                    # timeout-surfer (faults/adaptive.py): hold the vote
                    # to a fraction of the OBSERVED view timer — the
                    # collector reaches quorum just inside the timeout,
                    # stretching every view without firing a TC
                    delay = adversary.surf_delay_s(self.timer.duration)
                    adversary.mark_adaptive(
                        surfs, block.round, self.log, block.digest()
                    )
                    self.log.info(
                        "byz vote-delay round %d: holding %.0f ms of "
                        "%.0f ms timer", block.round, delay * 1e3,
                        self.timer.duration * 1e3,
                    )
                    await default_clock().sleep(delay)
                address = self.committee.address(next_leader)
                await self.network.send(address, encode_vote(vote))
            if adversary is not None and adversary.active("double-vote"):
                await self._byz_double_vote(block, next_leader)
        if adversary is not None and adversary.active("forge-qc"):
            await self._byz_forge_qc()

    # ---- adversary seams (faults/adversary.py) -----------------------------

    async def _byz_double_vote(self, block: Block, next_leader) -> None:
        """double-vote policy: also sign a vote for the deterministic
        shadow twin of ``block`` and ship it to the same next leader —
        a well-formed conflicting vote the honest aggregator must park
        (second digest cell for one payer)."""
        adversary = self.adversary
        shadow = adversary.shadow_block(block)
        vote = Vote(hash=shadow.digest(), round=block.round, author=self.name)
        vote.signature = await self.signature_service.request_signature(
            vote.digest()
        )
        adversary.count("byz_double_votes")
        adversary.record(
            "double-vote", block.round, shadow.digest(), str(next_leader)[:8]
        )
        self.log.info(
            "byz double-vote round %d -> %s", block.round, shadow.digest()
        )
        if next_leader == self.name:
            try:
                await self._handle_vote(vote, sig_verified=True)
            except ConsensusError as e:
                self.log.debug("own conflicting vote rejected: %s", e)
        else:
            address = self.committee.address(next_leader)
            await self.network.send(address, encode_vote(vote))

    async def _byz_forge_qc(self) -> None:
        """forge-qc policy: broadcast a properly-signed timeout whose
        high_qc names real committee authors with quorum-many garbage
        signatures — it passes every structural check (stake, quorum,
        no reuse) and MUST die in honest signature verification.  One
        seeded draw gates each opportunity so the attack volume is
        replayable."""
        adversary = self.adversary
        if adversary.rng.random() >= 0.5:
            return
        qc = adversary.forged_qc(self.committee, max(self.round - 1, 1))
        timeout = Timeout(high_qc=qc, round=self.round, author=self.name)
        timeout.signature = await self.signature_service.request_signature(
            timeout.digest()
        )
        adversary.count("byz_forged_qcs")
        adversary.record("forge-qc", self.round, qc.hash)
        self.log.info(
            "byz forge-qc round %d (authors %d)", self.round, len(qc.votes)
        )
        addresses = [
            addr for _, addr in self.committee.broadcast_addresses(self.name)
        ]
        await self.network.broadcast(addresses, encode_timeout(timeout))

    async def _handle_proposal(
        self, block: Block, sigs_verified: bool = False
    ) -> None:
        digest = block.digest()
        expected = self.leader_elector.get_leader(block.round)
        if block.author != expected:
            raise WrongLeader(digest, block.author, block.round)
        block.verify(
            self.committee,
            self.verifier,
            qc_cache=self._qc_cache(),
            sigs_verified=sigs_verified,
        )
        self._process_qc(block.qc)
        if block.tc is not None:
            self._advance_round(block.tc.round, via_tc=True)
        await self._process_block(block)

    async def _handle_tc(self, tc: TC, sigs_verified: bool = False) -> None:
        # staleness check first: every node broadcasts assembled TCs, so
        # stale copies are routine — drop them before paying the 2f+1
        # batch verify
        if tc.round < self.round:
            return
        tc.verify(self.committee, self.verifier, sigs_verified=sigs_verified)
        self._advance_round(tc.round, via_tc=True)
        if self.name == self.leader_elector.get_leader(self.round):
            await self._generate_proposal(tc)

    async def _handle_reconfig(self, op: ReconfigOp) -> None:
        """An operator-submitted epoch change (wire.encode_reconfig).
        The full verification gate runs at admission — margin bounds,
        epoch succession, carried-over stake, sponsor membership and
        signature (byz-reconfig's forged ops die HERE on honest nodes)
        — then the op waits in the proposer for our next leader slot."""
        validate_reconfig(op, self.committee, self.round, verifier=self.verifier)
        self.log.info(
            "Reconfig op admitted: epoch %d (%d members, margin %d)",
            op.new_committee.epoch,
            len(op.new_committee.authorities),
            op.margin,
        )
        if self._journal is not None:
            self._journal.record("reconfig.submit", self.round)
        await self.tx_proposer.put(ProposerMessage.reconfig(op))

    # ---- the select loop -----------------------------------------------------

    async def _preverify_burst(self, burst: list) -> set[int]:
        """Burst-level accumulate-then-dispatch: collect every signature
        check the burst's messages need as CLAIMS, discharge them in ONE
        awaited call on the async verify service, and return the indices
        of fully-preverified messages.  Messages not in the returned set
        (structurally implausible, or a claim failed) fall back to the
        handler's own synchronous, hardened verification path — a
        garbage message costs the attacker the old per-item price, never
        an amplification.

        Why this exists (VERDICT r3 item 1): on the device backend the
        await runs the whole burst's crypto as one coalesced off-loop
        dispatch — measured 56% of the event loop at a 32-node committee
        moves to the TPU, and the dispatch latency overlaps the other
        nodes' protocol work instead of serializing with it.  On the CPU
        backend the service evaluates inline (one flattened batch call),
        so behavior and timing match the old eager path.

        Trust base for the timeout grouping (shared-digest aggregate):
        identical to TC.verify's grouped path — aggregation is ONLY over
        authors holding stake in their round's committee (PoP-checked
        under BLS; a rogue key pk_E = x*G2 - pk_B that would let an
        attacker forge an honest member's entry inside the aggregate
        cannot carry a valid proof of possession, and non-members never
        enter the sum at all — they fall back to per-item verification,
        where the stake check rejects them).  A certificate formed from
        collectively-certified entries is re-verified by every receiver
        under the same semantics.
        """
        cache = self._qc_cache()
        claims: dict = {}  # claim tuple (hashable) -> position, dedup
        qc_memo: dict = {}  # claim -> QC cache key to memoize on success
        per_msg: list[tuple[int, list]] = []  # (burst idx, [claims])

        def add_qc_claims(qc) -> list:
            # SAFETY: the stake/quorum rules must hold BEFORE this QC
            # can become memoizable — a successful signature claim alone
            # must never put a sub-quorum certificate into the verified
            # cache (QC.verify early-returns on a cache hit, skipping
            # the weight check; see QC.claims docstring).  Raises
            # ConsensusError, which skips this message's claims — the
            # handler then runs the full sync verify and rejects it
            # with the proper error.
            if qc.is_genesis():
                return []
            qc.check_weight(self.committee)
            out = []
            # committee= resolves a compact QC's signer bitmap into the
            # member keys its "agg" claim carries
            for c in qc.claims(cache=cache, committee=self.committee):
                claims.setdefault(c, None)
                qc_memo[c] = qc._cache_key()
                out.append(c)
            return out

        def collect_propose(idx, payload) -> None:
            com = self.committee.for_round(payload.round)
            if (
                com.stake(payload.author) <= 0
                or len(payload.payloads) > MAX_BLOCK_PAYLOADS
            ):
                return  # handler raises the proper error
            keys = [
                (
                    "one",
                    payload.digest().to_bytes(),
                    payload.author.to_bytes(),
                    payload.signature.to_bytes(),
                )
            ]
            claims.setdefault(keys[0], None)
            keys += add_qc_claims(payload.qc)
            if payload.tc is not None:
                for c in payload.tc.claims(committee=self.committee):
                    claims.setdefault(c, None)
                    keys.append(c)
            per_msg.append((idx, keys))

        def collect_vote(idx, payload) -> None:
            if (
                # mirror Aggregator.add_vote's bounds: a far-future vote
                # is rejected there with ZERO crypto (AggregationBounds)
                # — collecting its claim here would convert that free
                # rejection into attacker-priced signature work
                self.round
                <= payload.round
                <= self.round + ROUND_LOOKAHEAD
                and self.committee.for_round(payload.round).stake(
                    payload.author
                )
                > 0
            ):
                c = payload.claim()
                claims.setdefault(c, None)
                per_msg.append((idx, [c]))

        def collect_tc(idx, payload) -> None:
            if payload.round >= self.round:
                keys = []
                for c in payload.claims(committee=self.committee):
                    claims.setdefault(c, None)
                    keys.append(c)
                per_msg.append((idx, keys))

        # timeouts sharing one digest verify as one aggregate claim
        timeout_groups: dict = {}  # Digest -> [(idx, timeout)]
        collectors = {
            TAG_PROPOSE: collect_propose,
            TAG_TC: collect_tc,
        }
        if self.averifier.device:
            # Device backends: fold vote claims into the coalesced wave
            # — marginal signatures in a device dispatch are ~free, and
            # the off-loop await overlaps other nodes' work.  On the CPU
            # inline path votes are deliberately NOT preverified: the
            # aggregator accumulates them unverified and batch-verifies
            # the whole set ONCE at quorum (QCMaker.emit), so eager
            # per-burst checks — typically 1-2 signatures each — would
            # run ~3 small batch equations where quorum time runs one.
            collectors[TAG_VOTE] = collect_vote
        for idx, (tag, payload) in enumerate(burst):
            if tag == TAG_TIMEOUT:
                if (
                    # same lookahead bound as add_timeout: far-future
                    # timeouts are a free rejection, not crypto work
                    self.round
                    <= payload.round
                    <= self.round + ROUND_LOOKAHEAD
                    # committee membership BEFORE aggregation — the
                    # soundness precondition above
                    and self.committee.for_round(payload.round).stake(
                        payload.author
                    )
                    > 0
                ):
                    timeout_groups.setdefault(payload.digest(), []).append(
                        (idx, payload)
                    )
            elif tag in collectors:
                try:
                    collectors[tag](idx, payload)
                except ConsensusError:
                    # a structural rule failed (e.g. a sub-quorum
                    # embedded QC): collect nothing — the handler's
                    # full sync verify rejects it with the proper error
                    continue

        for digest, members in timeout_groups.items():
            if len(members) == 1:
                idx0, t = members[0]
                author_claim = (
                    "one",
                    digest.to_bytes(),
                    t.author.to_bytes(),
                    t.signature.to_bytes(),
                )
            else:
                author_claim = (
                    "shared",
                    digest.to_bytes(),
                    tuple(
                        (t.author.to_bytes(), t.signature.to_bytes())
                        for _, t in members
                    ),
                )
            claims.setdefault(author_claim, None)
            for idx, t in members:
                try:
                    keys = [author_claim] + add_qc_claims(t.high_qc)
                except ConsensusError:
                    continue  # sub-quorum high_qc: leave to the handler
                per_msg.append((idx, keys))

        if not claims:
            return set()
        ordered = list(claims.keys())
        try:
            results = await self.averifier.verify_claims(ordered)
        except Exception as e:  # noqa: BLE001 — any backend failure must
            # degrade to per-item verification, never crash the core; but
            # silently losing the fast path forever is a debugging trap,
            # so say so
            self.log.warning(
                "burst claim preverification failed (%s); falling back to "
                "per-item verification",
                e,
            )
            return set()
        verdict = dict(zip(ordered, results))
        for claim, key in qc_memo.items():
            if verdict.get(claim):
                cache.add(key)
        return {
            idx for idx, keys in per_msg if all(verdict[k] for k in keys)
        }

    async def _dispatch(self, tagged, sig_verified: bool = False) -> None:
        """``sig_verified=True``: every signature claim this message
        carries was discharged by the burst preverifier
        (_preverify_burst) — handlers run structural checks only."""
        tag, payload = tagged
        if tag == TAG_PROPOSE:
            await self._handle_proposal(payload, sigs_verified=sig_verified)
        elif tag == TAG_VOTE:
            await self._handle_vote(payload, sig_verified=sig_verified)
        elif tag == TAG_TIMEOUT:
            await self._handle_timeout(payload, sig_verified=sig_verified)
        elif tag == TAG_TC:
            await self._handle_tc(payload, sigs_verified=sig_verified)
        elif tag == TAG_RECONFIG:
            await self._handle_reconfig(payload)
        else:
            self.log.error("Unexpected protocol message tag %s in core", tag)

    async def _timer_pump(self) -> None:
        """Feeds round-timer expiries into the merged event queue.  The
        ack handshake keeps the pump from re-firing before the core has
        HANDLED the event (the handler resets the deadline — or a
        message did, making the fire stale; either way the next wait()
        sleeps)."""
        while True:
            await self.timer.wait()
            self._timer_ack.clear()
            await self.rx_events.put((EV_TIMER, None))
            await self._timer_ack.wait()

    async def run(self) -> None:
        await self.load_state()

        # Snapshot catch-up BEFORE entering the protocol: adopt a
        # QC-anchored peer snapshot and jump the commit cursor past the
        # missed history, so the first post-rejoin commit's ancestor
        # walk spans only the sync window — never the outage (the
        # "no history replay" half of state-sync; statesync.py).
        if self.state_sync is not None:
            try:
                adopted = await self.state_sync.bootstrap(
                    self.last_committed_round
                )
            except Exception as e:  # noqa: BLE001 — catch-up is an
                # optimization; any failure degrades to normal replay
                self.log.warning("State-sync bootstrap failed: %s", e)
                adopted = 0
            if adopted > self.last_committed_round:
                self.log.info(
                    "State sync advanced commit cursor %d -> %d "
                    "(history replay skipped)",
                    self.last_committed_round,
                    adopted,
                )
                self.last_committed_round = adopted
                self.state_changed = True

        # Epoch tracking starts at the CURRENT round's committee — only
        # now, after recovery and any state-sync schedule splices, so a
        # restart inside a later epoch does not replay old activations.
        com_now = self.committee.for_round(self.round)
        self._active_epoch = com_now.epoch
        if com_now.stake(self.name) <= 0:
            # restarted AFTER a boundary that excluded us (the live
            # crossing in _activate_epoch never fired): retire unless a
            # later scheduled epoch re-admits us (then we are a joiner)
            epochs = self.committee.committees()
            rejoins = any(
                c.stake(self.name) > 0 and c.epoch > com_now.epoch
                for c in epochs
            )
            was_member = any(c.stake(self.name) > 0 for c in epochs)
            if was_member and not rejoins and self._retire_after is None:
                self._retire_after = self.round + self._grace_rounds
                self.log.info(
                    "Retiring: epoch %d excludes this node; serving a "
                    "grace window through round %d",
                    com_now.epoch, self._retire_after,
                )

        # Bootstrap: propose if we lead the (possibly recovered) round.
        self.timer.reset()
        if self.name == self.leader_elector.get_leader(self.round):
            await self._generate_proposal(None)

        timer_pump = asyncio.ensure_future(self._timer_pump())
        try:
            while True:
                event = await self.rx_events.get()
                if self.retired:
                    # retired member: drain events without processing so
                    # the receiver never backpressures, while the Helper
                    # and state-sync server keep serving boundary
                    # certificates (node/main.py watches ``retired`` and
                    # shuts the process down after a linger window)
                    while True:
                        try:
                            self.rx_loopback.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                    continue
                # Burst drain: everything already queued is handled in
                # this wake-up.  Network messages are collected FIRST so
                # the whole wave's signature checks discharge as ONE
                # coalesced claim batch (_preverify_burst) — off-loop on
                # the device backend.  Bounded so a flood cannot starve
                # the timer.
                burst: list = []
                timer_fired = False
                while True:
                    kind, payload = event
                    if kind == EV_MSG:
                        burst.append(payload)
                    elif kind == EV_TIMER:
                        timer_fired = True
                    # EV_LOOP events are bare wake tokens — the blocks
                    # live in the priority loopback queue drained below
                    if len(burst) >= 64:
                        break
                    try:
                        event = self.rx_events.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                # Priority drain of the loopback channel EVERY iteration
                # (own proposals, sync-resumed orphans): never behind
                # the network backlog — the reference's select services
                # this branch on every wake-up.
                loops: list = []
                for _ in range(64):
                    try:
                        loops.append(self.rx_loopback.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                else:
                    # capped drain left blocks queued whose wake tokens
                    # this iteration may already have consumed — re-arm
                    # one so an otherwise-idle loop cannot strand them
                    # until the round timer (review finding, r5)
                    if self.rx_loopback.qsize() > 0:
                        try:
                            self.rx_events.put_nowait((EV_LOOP, None))
                        except asyncio.QueueFull:
                            pass
                if burst:
                    preverified = await self._preverify_burst(burst)
                    for idx, message in enumerate(burst):
                        try:
                            await self._dispatch(
                                message, sig_verified=idx in preverified
                            )
                        except ConsensusError as e:
                            self.log.warning("%s", e)
                for block in loops:
                    try:
                        await self._process_block(block)
                    except ConsensusError as e:
                        self.log.warning("%s", e)
                # Timeout check runs EVERY iteration, not only when the
                # pump's EV_TIMER event drains: a message flood filling
                # the merged queue must delay the local timeout by at
                # most one <=64-message batch (the old select loop's
                # bound), never by the whole backlog the pump's event
                # would sit behind.  The pump exists to wake an IDLE
                # loop; expiry detection does not depend on it.
                if self.timer.expired():
                    try:
                        await self._local_timeout_round()
                    except ConsensusError as e:
                        self.log.warning("%s", e)
                if timer_fired:
                    self._timer_ack.set()
                if (
                    self._retire_after is not None
                    and not self.retired
                    and self.round >= self._retire_after
                ):
                    self.retired = True
                    # NOTE: this log entry is used by the reconfig harness.
                    self.log.info(
                        "Retired at round %d (grace window complete)",
                        self.round,
                    )
                    if self._journal is not None:
                        self._journal.record("reconfig.retire", self.round)
                        self._journal.flush()
                if self.state_changed:
                    await self.persist_state()
                    self.state_changed = False
        finally:
            timer_pump.cancel()

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.get_running_loop().create_task(
            self.run(), name="consensus-core"
        )
        return self._task

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.network.close()
