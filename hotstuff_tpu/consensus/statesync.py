"""State-sync actors: snapshot serving and boot-time catch-up.

The replicated execution layer (store/state.py) gives every node a
versioned, root-summarized state.  This module is the protocol on top:

- ``StateSyncServer`` — a Helper-style actor answering StateRequest
  frames from peers: a manifest (full or delta, anchored by this node's
  current high QC) or one snapshot chunk.

- ``StateSyncClient`` — the boot-time catch-up path.  A crash-recovered
  (or explicitly opted-in fresh) node broadcasts a manifest request,
  adopts the best QC-verified offer that is meaningfully ahead of its
  own cursor, fetches the chunks from that peer, and installs them.
  The core then advances ``last_committed_round`` to the snapshot
  round, so the commit-time ancestor walk never replays the missed
  history — rejoin cost is the snapshot transfer, not the outage
  length.

Trust model: a chained state root summarizes history the snapshot
deliberately omits, so it cannot be recomputed from snapshot content.
The client trusts a manifest only when its embedded QC verifies against
the client's own committee AND ``qc.round >= manifest.last_round`` —
i.e. some quorum certified progress at least as far as the offered
cursor.  A lying peer can still under-report (harmless: the delta apply
path re-derives everything deterministically) but cannot fabricate a
certified future.

Snapshot cuts are best-effort under concurrent commits: entries that
race a commit between manifest and chunk serving may shift chunks or
arrive from a newer version.  Duplicates are idempotent puts; anything
missed at rounds beyond the manifest cursor is re-materialized by the
normal apply path.
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..crypto import PublicKey
from ..utils.clock import default_clock
from ..network import SimpleSender
from ..store.state import SnapshotManifest, StateMachine
from ..utils.codec import CodecError
from .config import Committee
from .errors import ConsensusError, InvalidReconfig
from .reconfig import splice_schedule_links
from .wire import (
    STATE_REQ_CHUNK,
    STATE_REQ_DELTA,
    STATE_REQ_MANIFEST,
    TAG_STATE_CHUNK,
    TAG_STATE_MANIFEST,
    StateRequest,
    decode_schedule_links,
    encode_schedule_links,
    encode_state_chunk,
    encode_state_manifest,
    encode_state_request,
)

log = logging.getLogger(__name__)

#: a manifest must be at least this many rounds ahead of the local
#: commit cursor to be worth adopting — below it, the ordinary commit
#: path catches up faster than a snapshot round-trip
SYNC_MIN_LAG_ROUNDS = 8
#: manifest collection window and chunk-transfer deadline (seconds)
SYNC_MANIFEST_WAIT_S = 1.0
SYNC_CHUNK_WAIT_S = 5.0
#: re-ask cadence for chunks still missing inside the transfer window —
#: a chunk request is a single frame, so one drop on a faulty link must
#: not wedge the whole sync until the deadline
SYNC_CHUNK_RETRY_S = 1.0


class StateSyncServer:
    """Answers peers' StateRequest frames from the local state machine,
    anchoring every manifest with this node's current high QC."""

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        state: StateMachine,
        rx_requests: asyncio.Queue,
        high_qc,
        network: SimpleSender | None = None,
        telemetry=None,
        store=None,
        adversary=None,
    ):
        self.name = name
        self.committee = committee
        self.state = state
        self.rx_requests = rx_requests
        self.high_qc = high_qc  # () -> the core's current high QC
        self.network = network if network is not None else SimpleSender()
        # consensus store (optional): source of the certified schedule
        # links served in the manifest so a joiner can verify epoch
        # changes it never witnessed (docs/RECONFIG.md)
        self.store = store
        # Byzantine adversary plane (faults/adversary.py): None on
        # honest nodes; the chunk-serving path below is the
        # sync-predator's attack seam (faults/adaptive.py)
        self.adversary = adversary
        self._journal = telemetry.journal if telemetry is not None else None
        self._task: asyncio.Task | None = None
        # per-node logger suffix: multi-node harnesses (sim, local
        # bench) route records to the right node-*.log by logger name
        self.log = logging.getLogger(f"{__name__}.{str(name)[:8]}")

    async def _schedule_links(self) -> tuple:
        if self.store is None:
            return ()
        from .core import SCHEDULE_LINKS_KEY

        raw = await self.store.read(SCHEDULE_LINKS_KEY)
        if not raw:
            return ()
        try:
            return tuple(decode_schedule_links(raw))
        except CodecError as e:
            log.warning("Corrupt schedule links in store: %s", e)
            return ()

    async def run(self) -> None:
        while True:
            req: StateRequest = await self.rx_requests.get()
            address = self.committee.address(req.origin)
            if address is None or req.origin == self.name:
                log.warning(
                    "Dropping state request from unknown origin %s",
                    req.origin,
                )
                continue
            if req.kind == STATE_REQ_CHUNK:
                adversary = self.adversary
                preys = (
                    adversary.wants("sync-withhold")
                    if adversary is not None else False
                )
                if preys:
                    # sync-predator (faults/adaptive.py): withhold
                    # exactly the chunks this bootstrapping peer needs —
                    # manifests still flow, so the victim commits to a
                    # sync it cannot finish until the window closes
                    adversary.mark_adaptive(
                        preys, req.from_round, self.log,
                    )
                    adversary.record(
                        "sync-withhold", req.from_round, None,
                        str(req.origin)[:8],
                    )
                    self.log.info(
                        "byz sync-withhold chunk %d from %s",
                        req.index, str(req.origin)[:8],
                    )
                    continue
                entries = self.state.chunk(req.index, req.from_round)
                reply = encode_state_chunk(
                    self.state.version, req.index, req.from_round, entries
                )
            else:
                from_round = (
                    req.from_round if req.kind == STATE_REQ_DELTA else 0
                )
                m = self.state.manifest(from_round)
                reply = encode_state_manifest(
                    m.version,
                    m.root,
                    m.last_round,
                    m.applied_payloads,
                    m.chunk_count,
                    from_round,
                    self.high_qc(),
                    self.name,
                    links=await self._schedule_links(),
                )
                self.state.snapshots_served += 1
                if self.adversary is not None:
                    # sync-predator prey sensing: this peer just began a
                    # snapshot bootstrap against us
                    self.adversary.note_syncing(req.origin)
                if self._journal is not None:
                    self._journal.record(
                        "sync.serve", m.last_round, None, str(req.origin)[:8]
                    )
            await self.network.send(address, reply)

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.get_running_loop().create_task(
            self.run(), name="state-sync-server"
        )
        return self._task

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.network.close()


class StateSyncClient:
    """One-shot boot-time catch-up.  ``bootstrap`` returns the adopted
    snapshot round (0 when nothing was adopted); the caller advances
    the consensus commit cursor past the snapshotted history."""

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        state: StateMachine,
        verifier,
        rx_replies: asyncio.Queue,
        network: SimpleSender | None = None,
        min_lag: int | None = None,
        manifest_wait_s: float | None = None,
        chunk_wait_s: float = SYNC_CHUNK_WAIT_S,
        telemetry=None,
        store=None,
        synchronizer=None,
    ):
        self.name = name
        self.committee = committee
        self.state = state
        self.verifier = verifier
        self.rx_replies = rx_replies
        self.network = network if network is not None else SimpleSender()
        # optional reconfiguration wiring (docs/RECONFIG.md): ``store``
        # persists verified schedule links so a restart re-derives the
        # epoch schedule without re-syncing; ``synchronizer`` gets its
        # join barrier raised to the adopted snapshot round
        self.store = store
        self.synchronizer = synchronizer
        if min_lag is None:
            min_lag = int(
                os.environ.get("HOTSTUFF_STATE_SYNC_LAG", SYNC_MIN_LAG_ROUNDS)
            )
        if manifest_wait_s is None:
            manifest_wait_s = (
                int(os.environ.get("HOTSTUFF_STATE_SYNC_WAIT_MS", 0)) / 1000
                or SYNC_MANIFEST_WAIT_S
            )
        self.min_lag = min_lag
        self.manifest_wait_s = manifest_wait_s
        self.chunk_wait_s = chunk_wait_s
        self._journal = telemetry.journal if telemetry is not None else None
        self._qc_cache: set = set()
        self.log = logging.getLogger(f"{__name__}.{str(name)[:8]}")

    async def _collect(self, deadline: float):
        while True:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                return None
            try:
                return await asyncio.wait_for(
                    self.rx_replies.get(), timeout=remaining
                )
            except asyncio.TimeoutError:
                return None

    async def _apply_schedule_links(self, links) -> bool:
        """Verified-successor acceptance (docs/RECONFIG.md): walk the
        certified ``(reconfig block, certifying QC)`` chain served in a
        manifest and splice every epoch change we have not seen yet into
        the local schedule.  Each link is self-certifying — the op is
        re-validated against the schedule *as extended so far* and the
        QC must certify exactly that block under the committee in effect
        at its round — so a joiner that booted with only the genesis
        committee file ends up with the same schedule a live witness
        holds, or rejects the manifest outright.  Returns False when any
        link fails verification (the offer is then discarded whole)."""
        if not links:
            return True
        if not hasattr(self.committee, "splice"):
            self.log.warning(
                "Ignoring %d schedule links: static committee", len(links)
            )
            return True
        try:
            splice_schedule_links(
                links,
                self.committee,
                self.verifier,
                qc_cache=self._qc_cache,
                journal=self._journal,
                log=self.log,
            )
        except InvalidReconfig as e:
            self.log.warning("Rejecting schedule links: %s", e)
            return False
        if self.store is not None:
            from .core import SCHEDULE_LINKS_KEY

            raw = await self.store.read(SCHEDULE_LINKS_KEY)
            have_n = 0
            if raw:
                try:
                    have_n = len(decode_schedule_links(raw))
                except CodecError:
                    have_n = 0
            if len(links) > have_n:
                await self.store.write(
                    SCHEDULE_LINKS_KEY, encode_schedule_links(list(links))
                )
        return True

    def _acceptable(self, m, from_round: int, floor: int) -> bool:
        if m.from_round != from_round or m.version <= self.state.version:
            return False
        if m.last_round <= floor + self.min_lag:
            return False
        if m.qc.is_genesis() or m.qc.round < m.last_round:
            return False
        if self.committee.address(m.origin) is None:
            return False
        try:
            m.qc.verify(self.committee, self.verifier, cache=self._qc_cache)
        except ConsensusError as e:
            self.log.warning("Rejecting state manifest with bad QC: %s", e)
            return False
        return True

    async def bootstrap(self, last_committed_round: int) -> int:
        peers = [
            addr for _, addr in self.committee.broadcast_addresses(self.name)
        ]
        if not peers:
            return 0
        started = default_clock().monotonic()
        floor = max(last_committed_round, self.state.last_round)
        # delta when local state survived the crash; full otherwise
        from_round = self.state.last_round
        kind = STATE_REQ_DELTA if from_round else STATE_REQ_MANIFEST
        await self.network.broadcast(
            peers, encode_state_request(kind, self.name, from_round=from_round)
        )
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.manifest_wait_s
        best = None
        seen = 0
        while seen < len(peers):
            msg = await self._collect(deadline)
            if msg is None:
                break
            tag, payload = msg
            if tag != TAG_STATE_MANIFEST:
                continue  # stray chunk from a previous attempt
            seen += 1
            if self._journal is not None:
                self._journal.record(
                    "sync.manifest",
                    payload.last_round,
                    None,
                    str(payload.origin)[:8],
                )
            # schedule links first: _acceptable resolves the origin and
            # verifies the anchoring QC against the (possibly extended)
            # schedule, so a joiner must splice before judging the offer
            if not await self._apply_schedule_links(payload.links):
                continue
            if self._acceptable(payload, from_round, floor) and (
                best is None or payload.version > best.version
            ):
                best = payload
        if best is None:
            self.log.info(
                "State sync: no snapshot ahead of round %d (%d offers)",
                floor,
                seen,
            )
            return 0

        address = self.committee.address(best.origin)
        pending = set(range(best.chunk_count))
        for index in pending:
            await self.network.send(
                address,
                encode_state_request(
                    STATE_REQ_CHUNK,
                    self.name,
                    index=index,
                    from_round=from_round,
                ),
            )
        entries: list = []
        deadline = loop.time() + self.chunk_wait_s
        retry_at = loop.time() + SYNC_CHUNK_RETRY_S
        while pending:
            msg = await self._collect(min(deadline, retry_at))
            if msg is None:
                now = loop.time()
                if now >= deadline:
                    break
                # a chunk ask is a single frame: when a faulty link eats
                # it, only a re-ask gets the transfer moving again
                for index in sorted(pending):
                    await self.network.send(
                        address,
                        encode_state_request(
                            STATE_REQ_CHUNK,
                            self.name,
                            index=index,
                            from_round=from_round,
                        ),
                    )
                retry_at = now + SYNC_CHUNK_RETRY_S
                continue
            tag, payload = msg
            if tag != TAG_STATE_CHUNK:
                continue
            if (
                payload.version < best.version
                or payload.from_round != from_round
                or payload.index not in pending
            ):
                continue
            pending.discard(payload.index)
            entries.extend(payload.entries)
            if self._journal is not None:
                self._journal.record("sync.chunk", payload.index)
        if pending:
            self.log.warning(
                "State sync abandoned: %d/%d chunks missing from %s",
                len(pending),
                best.chunk_count,
                str(best.origin)[:8],
            )
            return 0

        manifest = SnapshotManifest(
            best.version,
            best.root,
            best.last_round,
            best.applied_payloads,
            best.chunk_count,
        )
        self.state.adopt(manifest, entries)
        if self.synchronizer is not None:
            # ancestry at or below the snapshot is covered by the adopted
            # state; never walk it (critical on a join: the pre-snapshot
            # chain may predate this node's first reachable epoch)
            self.synchronizer.join_floor = max(
                self.synchronizer.join_floor, best.last_round
            )
        elapsed = default_clock().monotonic() - started
        if self._journal is not None:
            self._journal.record("sync.adopt", best.last_round)
        # NOTE: this log entry is used to compute performance.
        self.log.info(
            "Adopted state snapshot version %d at round %d from %s "
            "(%d entries, %.3f s)",
            best.version,
            best.last_round,
            str(best.origin)[:8],
            len(entries),
            elapsed,
        )
        return best.last_round
