"""Resettable round timer.

Parity target: reference ``Timer`` (consensus/src/timer.rs:10-34): a
future that completes ``duration`` ms after the last ``reset()``. Here the
deadline is re-checked after every sleep, so a ``reset()`` while a
``wait()`` is outstanding simply extends the sleep instead of requiring
task cancellation — the core's select loop keeps one wait task alive
across resets.
"""

from __future__ import annotations

import asyncio

from ..utils.clock import default_clock


class Timer:
    def __init__(self, duration_ms: int):
        self.duration = duration_ms / 1000.0
        self._deadline: float | None = None
        # observability (free int stores, read by pull gauges / the
        # flight recorder): how often the timer re-armed and when
        self.resets = 0
        self.armed_at_ns = 0

    def set_duration_ms(self, duration_ms: float) -> None:
        """Change the duration used by subsequent resets (the core's
        exponential view-change backoff drives this); the current
        deadline is unaffected."""
        self.duration = duration_ms / 1000.0

    def reset(self) -> None:
        self._deadline = asyncio.get_running_loop().time() + self.duration
        self.resets += 1
        self.armed_at_ns = default_clock().monotonic_ns()

    def expired(self) -> bool:
        """Is the *current* deadline in the past? A ``wait()`` that completed
        before a subsequent ``reset()`` is stale — the reference's tokio
        ``Sleep`` un-readies itself on reset (timer.rs:21-26); callers
        re-check this to get the same semantics."""
        return (
            self._deadline is not None
            and asyncio.get_running_loop().time() >= self._deadline
        )

    async def wait(self) -> None:
        loop = asyncio.get_running_loop()
        if self._deadline is None:
            self._deadline = loop.time() + self.duration
        while True:
            remaining = self._deadline - loop.time()
            if remaining <= 0:
                return
            await default_clock().sleep(remaining)
