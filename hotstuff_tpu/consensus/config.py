"""Committee and protocol parameters.

Parity target: reference ``consensus/src/config.rs:10-85`` — ``Parameters``
{timeout_delay: 5000 ms, sync_retry_delay: 10000 ms}, ``Committee`` mapping
public keys to {stake, address} with epoch number and the BFT quorum rule
``2N/3 + 1`` (= N - f for N = 3f + 1 + k).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..crypto import PublicKey

log = logging.getLogger(__name__)

Address = tuple[str, int]


def parse_address(s: str) -> Address:
    host, _, port = s.rpartition(":")
    return host, int(port)


def format_address(a: Address) -> str:
    return f"{a[0]}:{a[1]}"


@dataclass
class Parameters:
    """Protocol timing knobs (milliseconds), JSON round-trippable."""

    timeout_delay: int = 5_000
    sync_retry_delay: int = 10_000

    def log(self) -> None:
        # NOTE: these log entries are used to compute performance
        # (reference config.rs:26-30 — the harness scrapes them).
        log.info("Timeout delay set to %s ms", self.timeout_delay)
        log.info("Sync retry delay set to %s ms", self.sync_retry_delay)

    def to_json(self) -> dict:
        return {
            "timeout_delay": self.timeout_delay,
            "sync_retry_delay": self.sync_retry_delay,
        }

    @classmethod
    def from_json(cls, data: dict) -> "Parameters":
        default = cls()
        return cls(
            timeout_delay=int(data.get("timeout_delay", default.timeout_delay)),
            sync_retry_delay=int(
                data.get("sync_retry_delay", default.sync_retry_delay)
            ),
        )


@dataclass
class Authority:
    stake: int
    address: Address


@dataclass
class Committee:
    """The validator set: voting power and network address per authority."""

    authorities: dict[PublicKey, Authority] = field(default_factory=dict)
    epoch: int = 1

    @classmethod
    def new(
        cls, info: list[tuple[PublicKey, int, Address]], epoch: int = 1
    ) -> "Committee":
        return cls(
            authorities={
                name: Authority(stake, address) for name, stake, address in info
            },
            epoch=epoch,
        )

    def size(self) -> int:
        return len(self.authorities)

    def stake(self, name: PublicKey) -> int:
        auth = self.authorities.get(name)
        return auth.stake if auth is not None else 0

    def total_votes(self) -> int:
        return sum(a.stake for a in self.authorities.values())

    def quorum_threshold(self) -> int:
        # If N = 3f + 1 + k (0 <= k < 3) then 2N/3 + 1 = 2f + 1 + k = N - f
        # (reference config.rs:67-72).
        return 2 * self.total_votes() // 3 + 1

    def address(self, name: PublicKey) -> Address | None:
        auth = self.authorities.get(name)
        return auth.address if auth is not None else None

    def broadcast_addresses(
        self, myself: PublicKey
    ) -> list[tuple[PublicKey, Address]]:
        """Every authority's (key, address) except our own."""
        return [
            (name, auth.address)
            for name, auth in self.authorities.items()
            if name != myself
        ]

    def sorted_keys(self) -> list[PublicKey]:
        return sorted(self.authorities.keys())

    def to_json(self) -> dict:
        return {
            "authorities": {
                pk.encode_base64(): {
                    "stake": a.stake,
                    "address": format_address(a.address),
                }
                for pk, a in self.authorities.items()
            },
            "epoch": self.epoch,
        }

    @classmethod
    def from_json(cls, data: dict) -> "Committee":
        return cls(
            authorities={
                PublicKey.decode_base64(pk): Authority(
                    stake=int(entry["stake"]),
                    address=parse_address(entry["address"]),
                )
                for pk, entry in data["authorities"].items()
            },
            epoch=int(data.get("epoch", 1)),
        )
