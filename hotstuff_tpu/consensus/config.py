"""Committee and protocol parameters.

Parity target: reference ``consensus/src/config.rs:10-85`` — ``Parameters``
{timeout_delay: 5000 ms, sync_retry_delay: 10000 ms}, ``Committee`` mapping
public keys to {stake, address} with epoch number and the BFT quorum rule
``2N/3 + 1`` (= N - f for N = 3f + 1 + k).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..crypto import PublicKey

log = logging.getLogger(__name__)

Address = tuple[str, int]


def parse_address(s: str) -> Address:
    host, _, port = s.rpartition(":")
    return host, int(port)


def format_address(a: Address) -> str:
    return f"{a[0]}:{a[1]}"


@dataclass
class Parameters:
    """Protocol timing knobs (milliseconds), JSON round-trippable.

    ``timeout_backoff``/``timeout_cap_ms`` drive the core's exponential
    view-change backoff (beyond reference parity — its timeout is fixed,
    config.rs:16-23): after k CONSECUTIVE local timeouts the round timer
    runs at ``timeout_delay * timeout_backoff^k`` (capped), snapping back
    to the base on progress (a newer QC).  This makes a small base delay
    safe — crash-faulted committees recover dead-leader rounds in ~one
    base delay while a genuinely slow network still converges.
    ``timeout_backoff = 1.0`` restores the reference's fixed timer."""

    timeout_delay: int = 5_000
    sync_retry_delay: int = 10_000
    timeout_backoff: float = 2.0
    # None = derived: max(60 s, timeout_delay) — so a large base delay
    # never collides with the fixed default cap.
    timeout_cap_ms: int | None = None
    # Byte budget for UNCOMMITTED producer payload bodies persisted by
    # the receiver (advisor r4): without it, any peer reaching the open
    # consensus port could fill the disk with unique content-addressed
    # bodies.  Oldest uncommitted bodies are evicted when the budget
    # overflows; committed bodies are history and never evicted.
    payload_body_budget: int = 256 * 1024 * 1024

    def __post_init__(self) -> None:
        # A backoff below 1 would make consecutive timeouts geometrically
        # SHRINK the round timer toward zero — a self-inflicted
        # view-change storm from a mistyped config.  A cap below the base
        # delay is equally incoherent (the cap would override the base).
        if self.timeout_backoff < 1.0:
            raise InvalidParameters(
                f"timeout_backoff must be >= 1.0, got {self.timeout_backoff}"
            )
        if self.timeout_cap_ms is None:
            self.timeout_cap_ms = max(60_000, self.timeout_delay)
        if self.timeout_cap_ms < self.timeout_delay:
            raise InvalidParameters(
                f"timeout_cap_ms ({self.timeout_cap_ms}) must be >= "
                f"timeout_delay ({self.timeout_delay})"
            )
        # must admit at least one maximum-size body or every producer
        # submission with a body would be silently rejected
        from .wire import MAX_PAYLOAD_BODY  # noqa: PLC0415 — cycle guard

        if self.payload_body_budget < MAX_PAYLOAD_BODY:
            raise InvalidParameters(
                f"payload_body_budget ({self.payload_body_budget}) must "
                f"be >= one maximum body ({MAX_PAYLOAD_BODY})"
            )

    def log(self) -> None:
        # NOTE: these log entries are used to compute performance
        # (reference config.rs:26-30 — the harness scrapes them).
        log.info("Timeout delay set to %s ms", self.timeout_delay)
        log.info("Sync retry delay set to %s ms", self.sync_retry_delay)
        # echoed so result files record which backoff configuration
        # produced a (fault) run — without this, runs at backoff 1.0
        # (reference-parity fixed timer) vs 2.0 are indistinguishable
        log.info(
            "Timeout backoff set to %s (cap %s ms)",
            self.timeout_backoff,
            self.timeout_cap_ms,
        )

    def to_json(self) -> dict:
        return {
            "timeout_delay": self.timeout_delay,
            "sync_retry_delay": self.sync_retry_delay,
            "timeout_backoff": self.timeout_backoff,
            "timeout_cap_ms": self.timeout_cap_ms,
            "payload_body_budget": self.payload_body_budget,
        }

    @classmethod
    def from_json(cls, data: dict) -> "Parameters":
        default = cls()
        return cls(
            timeout_delay=int(data.get("timeout_delay", default.timeout_delay)),
            sync_retry_delay=int(
                data.get("sync_retry_delay", default.sync_retry_delay)
            ),
            timeout_backoff=float(
                data.get("timeout_backoff", default.timeout_backoff)
            ),
            timeout_cap_ms=(
                int(data["timeout_cap_ms"])
                if data.get("timeout_cap_ms") is not None
                else None
            ),
            payload_body_budget=int(
                data.get("payload_body_budget", default.payload_body_budget)
            ),
        )


class InvalidParameters(ValueError):
    """A parameters file that must not be allowed to run (incoherent
    timing knobs that would destroy liveness)."""


class InvalidCommittee(ValueError):
    """A committee file that must not be allowed to run (missing/bad
    BLS proofs of possession)."""


@dataclass
class Authority:
    stake: int
    address: Address
    # BLS proof of possession (48-byte G1, scheme="bls" only).  REQUIRED
    # for BLS committees: aggregate (sum-of-public-keys) QC verification
    # is forgeable by an adversarially chosen "rogue" key otherwise —
    # pk_m = a·G2 − Σ pk_honest lets one member fabricate a QC carrying
    # honest authorities' names.  A PoP proves knowledge of the secret,
    # which rules the construction out.  Enforced at Consensus.spawn via
    # ``Committee.verify_pops``.
    pop: bytes | None = None


@dataclass
class Committee:
    """The validator set: voting power and network address per authority.

    ``scheme`` is the committee-wide signature scheme ("ed25519" default,
    "bls" for the BLS12-381 aggregate-signature variant) — a committee
    never mixes schemes; nodes dispatch signing/verification on it
    (crypto/scheme.py)."""

    authorities: dict[PublicKey, Authority] = field(default_factory=dict)
    epoch: int = 1
    scheme: str = "ed25519"
    #: membership-change counter (CommitteeSchedule interface): a bare
    #: Committee never mutates, so this is the constant 0 — consumers
    #: that cache derived views (wire-scheme narrowing, peer sets) key
    #: their cache on it and revalidate when it moves.
    generation: int = 0

    @classmethod
    def new(
        cls,
        info: list[tuple[PublicKey, int, Address]],
        epoch: int = 1,
        scheme: str = "ed25519",
        pops: dict[PublicKey, bytes] | None = None,
    ) -> "Committee":
        pops = pops or {}
        return cls(
            authorities={
                name: Authority(stake, address, pop=pops.get(name))
                for name, stake, address in info
            },
            epoch=epoch,
            scheme=scheme,
        )

    def verify_pops(self) -> None:
        """BLS committees: require a valid proof of possession per
        authority (see ``Authority.pop``); no-op for ed25519 (per-vote
        signatures there already prove key possession).  Raises
        ``InvalidCommittee``.  Cost: one pairing equality (~40 ms) per
        member, paid once at spawn."""
        if self.scheme != "bls":
            return
        from ..crypto.bls import BlsPublicKey, BlsSignature, verify_possession

        for pk, auth in self.authorities.items():
            if auth.pop is None:
                raise InvalidCommittee(
                    f"BLS committee member {pk} has no proof of possession"
                )
            pub = BlsPublicKey.from_bytes(pk.to_bytes())
            proof = BlsSignature.from_bytes(auth.pop)
            if pub is None or proof is None or not verify_possession(pub, proof):
                raise InvalidCommittee(
                    f"invalid BLS proof of possession for {pk}"
                )

    def for_round(self, round_: int) -> "Committee":
        """Committee in effect for ``round_``.  A bare Committee is a
        one-epoch schedule: every round maps to itself.  This is the
        seam that makes every verification/election call site epoch-
        aware for free — ``CommitteeSchedule`` implements the same
        method with a real lookup."""
        return self

    # one-epoch-schedule views (the CommitteeSchedule interface; call
    # sites must never need hasattr checks to handle either type)
    def committees(self) -> list["Committee"]:
        return [self]

    def wire_scheme(self) -> str | None:
        return self.scheme

    def size(self) -> int:
        return len(self.authorities)

    def stake(self, name: PublicKey) -> int:
        auth = self.authorities.get(name)
        return auth.stake if auth is not None else 0

    def total_votes(self) -> int:
        return sum(a.stake for a in self.authorities.values())

    def quorum_threshold(self) -> int:
        # If N = 3f + 1 + k (0 <= k < 3) then 2N/3 + 1 = 2f + 1 + k = N - f
        # (reference config.rs:67-72).
        return 2 * self.total_votes() // 3 + 1

    def validity_threshold(self) -> int:
        # f + 1: the smallest stake that must contain at least one honest
        # authority.  If N = 3f + 1 + k (0 <= k < 3) then
        # ceil(N/3) = f + 1.
        return (self.total_votes() + 2) // 3

    def address(self, name: PublicKey) -> Address | None:
        auth = self.authorities.get(name)
        return auth.address if auth is not None else None

    def broadcast_addresses(
        self, myself: PublicKey
    ) -> list[tuple[PublicKey, Address]]:
        """Every authority's (key, address) except our own."""
        return [
            (name, auth.address)
            for name, auth in self.authorities.items()
            if name != myself
        ]

    def sorted_keys(self) -> list[PublicKey]:
        return sorted(self.authorities.keys())

    def to_json(self) -> dict:
        import base64

        return {
            "authorities": {
                pk.encode_base64(): {
                    "stake": a.stake,
                    "address": format_address(a.address),
                    **(
                        {"pop": base64.b64encode(a.pop).decode()}
                        if a.pop is not None
                        else {}
                    ),
                }
                for pk, a in self.authorities.items()
            },
            "epoch": self.epoch,
            "scheme": self.scheme,
        }

    @classmethod
    def from_json(cls, data: dict) -> "Committee":
        import base64

        return cls(
            authorities={
                PublicKey.decode_base64(pk): Authority(
                    stake=int(entry["stake"]),
                    address=parse_address(entry["address"]),
                    pop=(
                        base64.b64decode(entry["pop"])
                        if "pop" in entry
                        else None
                    ),
                )
                for pk, entry in data["authorities"].items()
            },
            epoch=int(data.get("epoch", 1)),
            scheme=data.get("scheme", "ed25519"),
        )


class CommitteeSchedule:
    """Epoch reconfiguration: committees keyed by activation round.

    BEYOND reference parity (the reference has no reconfiguration at
    all): a schedule maps round ranges to committees — rounds in
    [from_round_i, from_round_{i+1}) run under committee i.  Everything
    that verifies a certificate, elects a leader, or checks stake asks
    ``for_round(r)``, so certificates formed under epoch e verify under
    epoch e's committee forever (a block at the boundary carries a QC
    from the previous epoch — each is checked against its own round's
    validator set).  A bare ``Committee`` implements the same
    ``for_round`` as a one-epoch schedule, so all single-epoch call
    sites are unchanged.

    The handoff itself needs no extra protocol: votes for the last
    round of epoch e route to the leader of round+1 — an epoch-e+1
    member — exactly like any other round; it assembles the QC and
    proposes.  Members only of older epochs simply stop being elected
    or counted.
    """

    def __init__(self, entries: list[tuple[int, Committee]]):
        if not entries:
            raise InvalidCommittee("empty committee schedule")
        entries = sorted(entries, key=lambda e: e[0])
        if entries[0][0] > 1:
            raise InvalidCommittee(
                "schedule must cover round 1 (first from_round > 1)"
            )
        froms = [f for f, _ in entries]
        if len(set(froms)) != len(froms):
            raise InvalidCommittee("duplicate from_round in schedule")
        self.entries: list[tuple[int, Committee]] = entries
        #: bumped on every successful ``splice`` — consumers caching
        #: schedule-derived views (wire-scheme narrowing, peer sets)
        #: key their cache on it
        self.generation: int = 0

    # ---- the epoch seam ----------------------------------------------------

    def splice(self, from_round: int, committee: Committee) -> bool:
        """Append a committed epoch change: rounds >= ``from_round`` run
        under ``committee``.  The ONE mutation a schedule supports — the
        commit path applies it atomically (a single list append; every
        actor shares this object, so leader election, stake checks and
        certificate routing all roll forward together while older
        entries keep verifying boundary certificates).

        Returns False for an exact replay (same activation round and
        epoch — crash-recovery re-applies committed reconfig ops
        idempotently); raises ``InvalidCommittee`` for a genuinely
        conflicting splice (non-monotonic activation or epoch)."""
        last_from, last_com = self.entries[-1]
        for f, c in self.entries:
            if f == from_round and c.epoch == committee.epoch:
                return False  # idempotent re-apply
        if from_round <= last_from or committee.epoch <= last_com.epoch:
            raise InvalidCommittee(
                f"splice (round {from_round}, epoch {committee.epoch}) "
                f"does not extend the schedule (newest: round "
                f"{last_from}, epoch {last_com.epoch})"
            )
        self.entries.append((from_round, committee))
        self.generation += 1
        return True

    def for_round(self, round_: int) -> Committee:
        current = self.entries[0][1]
        for from_round, committee in self.entries:
            if round_ >= from_round:
                current = committee
            else:
                break
        return current

    # ---- union views (round-less call sites) -------------------------------

    def committees(self) -> list[Committee]:
        return [c for _, c in self.entries]

    def address(self, name: PublicKey) -> Address | None:
        """A member's address, from the NEWEST epoch that knows it
        (members keep one address across epochs in practice; newest wins
        if they move)."""
        for _, committee in reversed(self.entries):
            addr = committee.address(name)
            if addr is not None:
                return addr
        return None

    def broadcast_addresses(
        self, myself: PublicKey
    ) -> list[tuple[PublicKey, Address]]:
        """Union of every epoch's members except us (sync retries and
        boundary-crossing certificates must be able to reach members of
        adjacent epochs), deduplicated by key."""
        seen: dict[PublicKey, Address] = {}
        for _, committee in self.entries:
            for name, auth in committee.authorities.items():
                if name != myself:
                    seen[name] = auth.address
        return list(seen.items())

    def stake(self, name: PublicKey) -> int:
        """Round-less stake checks should not exist for schedules —
        kept for duck-type compatibility: the stake in the newest epoch
        that knows the member."""
        for _, committee in reversed(self.entries):
            if name in committee.authorities:
                return committee.stake(name)
        return 0

    # Round-less threshold/size views (duck-type compatibility with a
    # bare Committee): delegated to the NEWEST epoch.  Protocol call
    # sites must use ``for_round(r)`` — these exist for diagnostics and
    # boot-time sizing only.
    def size(self) -> int:
        return self.entries[-1][1].size()

    def total_votes(self) -> int:
        return self.entries[-1][1].total_votes()

    def quorum_threshold(self) -> int:
        return self.entries[-1][1].quorum_threshold()

    def validity_threshold(self) -> int:
        return self.entries[-1][1].validity_threshold()

    def sorted_keys(self) -> list[PublicKey]:
        return self.entries[-1][1].sorted_keys()

    @property
    def authorities(self) -> dict[PublicKey, Authority]:
        """Union membership across epochs (newest epoch wins per key) —
        round-less duck-type surface for kernel warmup, clients feeding
        the committee, and diagnostics."""
        merged: dict[PublicKey, Authority] = {}
        for _, committee in self.entries:
            merged.update(committee.authorities)
        return merged

    @property
    def scheme(self) -> str:
        """The committee-wide signature scheme when it is uniform across
        every epoch; mixed schedules raise — per-round dispatch must use
        ``for_round(r).scheme`` and the wire decode must accept the
        union (wire_scheme())."""
        schemes = {c.scheme for c in self.committees()}
        if len(schemes) == 1:
            return next(iter(schemes))
        raise InvalidCommittee(
            "schedule mixes signature schemes; use for_round(r).scheme"
        )

    def wire_scheme(self) -> str | None:
        """The scheme to narrow wire decode to: the uniform scheme, or
        None (accept the union) for mixed-scheme schedules."""
        schemes = {c.scheme for c in self.committees()}
        return next(iter(schemes)) if len(schemes) == 1 else None

    def verify_pops(self) -> None:
        for _, committee in self.entries:
            committee.verify_pops()

    # ---- JSON --------------------------------------------------------------

    def to_json(self) -> dict:
        return {
            "schedule": [
                {"from_round": from_round, **committee.to_json()}
                for from_round, committee in self.entries
            ]
        }

    @classmethod
    def from_json(cls, data: dict) -> "CommitteeSchedule":
        return cls(
            [
                (int(entry["from_round"]), Committee.from_json(entry))
                for entry in data["schedule"]
            ]
        )


def committee_from_json(data: dict):
    """Polymorphic committee-file payload: a plain Committee or a
    CommitteeSchedule (``schedule`` key)."""
    if "schedule" in data:
        return CommitteeSchedule.from_json(data)
    return Committee.from_json(data)
