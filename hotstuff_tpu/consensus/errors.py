"""Consensus error taxonomy.

Parity target: reference ``ConsensusError`` (consensus/src/error.rs:6-65).
Errors raised by message verification / protocol handlers are caught by the
core's run loop and logged, never fatal — mirroring the reference's
per-iteration ``match result`` (core.rs:478-483).
"""

from __future__ import annotations


class ConsensusError(Exception):
    """Base class for all protocol-level failures."""


class SerializationError(ConsensusError):
    pass


class StoreError(ConsensusError):
    pass


class NotInCommittee(ConsensusError):
    def __init__(self, name):
        super().__init__(f"Node {name} is not in the committee")
        self.name = name


class InvalidSignature(ConsensusError):
    pass


class AuthorityReuse(ConsensusError):
    def __init__(self, name):
        super().__init__(f"Received more than one vote from {name}")
        self.name = name


class UnknownAuthority(ConsensusError):
    def __init__(self, name):
        super().__init__(f"Received vote from unknown authority {name}")
        self.name = name


class QCRequiresQuorum(ConsensusError):
    def __init__(self):
        super().__init__("Received QC without a quorum")


class TCRequiresQuorum(ConsensusError):
    def __init__(self):
        super().__init__("Received TC without a quorum")


class MalformedBlock(ConsensusError):
    def __init__(self, digest):
        super().__init__(f"Malformed block {digest}")
        self.digest = digest


class WrongLeader(ConsensusError):
    def __init__(self, digest, leader, round_):
        super().__init__(
            f"Received block {digest} from leader {leader} at round {round_}"
        )
        self.digest = digest
        self.leader = leader
        self.round = round_


class InvalidPayload(ConsensusError):
    def __init__(self):
        super().__init__("Invalid payload")


class InvalidReconfig(ConsensusError):
    """A reconfiguration op that must die at verification: bad epoch
    succession, out-of-bounds margin, insufficient carried-over stake,
    unauthorized sponsor, or a bad sponsor signature."""

    def __init__(self, reason: str):
        super().__init__(f"Invalid reconfiguration op: {reason}")
