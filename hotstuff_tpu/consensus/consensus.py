"""Consensus wiring: builds the channel topology and spawns all actors.

Parity target: reference ``Consensus::spawn`` + ``ConsensusReceiverHandler``
(consensus/src/consensus.rs:42-169). Topology:

    NetworkReceiver -> {core, helper, producer->proposer}
    Core <-> Proposer (Make/Cleanup, loopback)
    Synchronizer -> Core (loopback)
    Core -> tx_commit (application layer)

Dispatch rules (consensus.rs:133-168): SyncRequest -> helper;
Propose -> ACK on the same socket, then core; Producer -> ACK, then
proposer; Vote/Timeout/TC -> core, no ACK.
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import PublicKey, SignatureService
from ..crypto.service import CpuVerifier, VerifierBackend
from ..network import Receiver as NetworkReceiver
from ..network import Writer
from ..store import Store
from .config import Committee, Parameters
from .core import CONSENSUS_STATE_KEY, Core, make_event_channels
from .errors import SerializationError
from .helper import Helper
from .leader import LeaderElector
from .proposer import Proposer
from .statesync import StateSyncClient, StateSyncServer
from .synchronizer import Synchronizer
from .wire import (
    ACK,
    SCHEME_WIRE_SIZES,
    STATE_READ_LEDGER,
    TAG_PRODUCER,
    TAG_PRODUCER_V2,
    TAG_PROPOSE,
    TAG_RECONFIG,
    TAG_STATE_CHUNK,
    TAG_STATE_MANIFEST,
    TAG_STATE_READ,
    TAG_STATE_REQUEST,
    TAG_SYNC_REQUEST,
    TAG_TC,
    TAG_TIMEOUT,
    TAG_VOTE,
    decode_message,
    encode_ingest_ack,
    encode_state_value,
)

log = logging.getLogger(__name__)

CHANNEL_CAPACITY = 1_000


PAYLOAD_KEY_PREFIX = b"p"  # store namespace for payload bodies


def payload_key(digest) -> bytes:
    """Store key of a payload body (33 bytes — disjoint from the
    32-byte block-digest key space)."""
    return PAYLOAD_KEY_PREFIX + digest.to_bytes()


class PayloadBodies:
    """Budgeted store-backed cache of producer payload bodies.

    Advisor finding (r4): the receiver persisted arbitrary
    unauthenticated bodies with no quota — any peer reaching the open
    consensus port could fill the disk with unique content-addressed
    bodies.  Bodies are now admitted against a byte budget
    (``Parameters.payload_body_budget``); while a body's digest is
    uncommitted it stays evictable (oldest first, FIFO — the shape an
    honest backlog drains in), and once the digest appears in a
    committed block the body is history and leaves the evictable set.
    A restarted node starts with an empty evictable set: bodies
    persisted by a previous process are treated as history (the budget
    bounds what one process lifetime can be tricked into writing).
    """

    def __init__(self, store: Store, budget: int):
        self.store = store
        self.budget = budget
        self._pending: dict[bytes, int] = {}  # digest bytes -> body size
        self._pending_bytes = 0
        self.evicted = 0

    async def admit(self, digest, body: bytes) -> None:
        key = digest.to_bytes()
        if key in self._pending:
            return  # same content, already stored and accounted
        # A body already in the store is history (committed earlier, or
        # persisted by a previous process lifetime): a replayed producer
        # frame must NOT re-enter it into the evictable set — that would
        # let an attacker replay a committed payload and then flood the
        # budget until its committed body was deleted.
        if await self.store.read(payload_key(digest)) is not None:
            return
        if key in self._pending:
            return  # re-check: a concurrent admit won the race
        # Reserve before mutating the store so accounting can never
        # double-count.  (Store operations complete without yielding to
        # the event loop today — the awaits above/below are synchronous
        # — but this ordering stays correct if the store ever parks.)
        self._pending[key] = len(body)
        self._pending_bytes += len(body)
        while self._pending_bytes > self.budget and len(self._pending) > 1:
            oldest = next(iter(self._pending))
            if oldest == key:
                # never evict the body being admitted: the budget floor
                # (>= one maximum body, config validation) makes a sole
                # pending entry always fit
                break
            self._pending_bytes -= self._pending.pop(oldest)
            await self.store.delete(PAYLOAD_KEY_PREFIX + oldest)
            self.evicted += 1
        await self.store.write(payload_key(digest), body)

    def mark_committed(self, digests) -> None:
        """Bodies of committed payloads stop counting against (and being
        evictable under) the budget."""
        for d in digests:
            size = self._pending.pop(d.to_bytes(), None)
            if size is not None:
                self._pending_bytes -= size


class ConsensusReceiverHandler:
    #: wire tag -> label on the received-message counters (index == tag)
    TAG_NAMES = (
        "propose", "vote", "timeout", "tc", "sync_request", "producer",
        "producer_v2", "state_request", "state_manifest", "state_chunk",
        "state_read", "reconfig",
    )

    def __init__(
        self,
        tx_consensus: asyncio.Queue,
        tx_helper: asyncio.Queue,
        tx_producer: asyncio.Queue,
        scheme: str | None = None,
        bodies: PayloadBodies | None = None,
        telemetry=None,
        admission=None,
        tx_state_requests: asyncio.Queue | None = None,
        tx_state_sync: asyncio.Queue | None = None,
        state=None,
        committee=None,
    ):
        self.tx_consensus = tx_consensus
        self.tx_helper = tx_helper
        self.tx_producer = tx_producer
        # Epoch schedule (docs/RECONFIG.md): a committed reconfiguration
        # can widen the set of signature schemes on the wire, so the
        # decode-time scheme narrowing is re-derived whenever the
        # schedule's splice generation moves.
        self._committee = committee
        self._scheme_gen = (
            getattr(committee, "generation", None)
            if committee is not None
            else None
        )
        # State-sync plumbing (consensus/statesync.py): peer snapshot
        # requests go to the server actor; manifest/chunk replies go to
        # the boot-time sync client.  ``state`` is the node's
        # StateMachine, consulted inline for TAG_STATE_READ (the
        # QC-anchored stale-read path — a lagging node answers at its
        # last applied version while it catches up).
        self.tx_state_requests = tx_state_requests
        self.tx_state_sync = tx_state_sync
        self.state = state
        # Ingest admission controller (ingest/admission.py): every
        # producer frame consults it; None keeps the legacy
        # always-accept path (bare component tests).
        self.admission = admission
        # fail at construction (node boot), not per-message in dispatch
        if scheme is not None and scheme not in SCHEME_WIRE_SIZES:
            raise ValueError(f"unknown committee scheme '{scheme}'")
        self.scheme = scheme
        self.bodies = bodies
        # Per-tag received counters, built once at boot (telemetry on) so
        # the dispatch hot path is one tuple index + int add, no lookups.
        self._msg_counters = None
        self._dropped = None
        # flight recorder: receive edges are journaled HERE (post-decode)
        # rather than at the socket, so each record carries the decoded
        # (round, digest, author) — exactly what the cross-node offset
        # estimation in benchmark/traces.py matches against send records
        self._journal = telemetry.journal if telemetry is not None else None
        if telemetry is not None:
            self._msg_counters = tuple(
                telemetry.registry.counter(
                    "net_messages_received",
                    "Consensus messages received, by wire tag",
                    {**telemetry.labels, "tag": tag_name},
                )
                for tag_name in self.TAG_NAMES
            )
            self._dropped = telemetry.counter(
                "net_messages_dropped",
                "Received frames dropped (malformed or poisoned payload)",
            )

    async def dispatch(self, writer: Writer, message: bytes) -> None:
        com = self._committee
        if com is not None:
            gen = getattr(com, "generation", None)
            if gen != self._scheme_gen:
                self._scheme_gen = gen
                self.scheme = com.wire_scheme()
        try:
            tag, payload = decode_message(message, scheme=self.scheme)
        except SerializationError as e:
            log.warning("Dropping malformed message: %s", e)
            if self._dropped is not None:
                self._dropped.inc()
            return
        if self._msg_counters is not None and tag < len(self._msg_counters):
            self._msg_counters[tag].inc()
        j = self._journal
        if j is not None:
            if tag == TAG_PROPOSE:
                j.record(
                    "recv.propose",
                    payload.round,
                    payload.digest(),
                    str(payload.author)[:8],
                )
            elif tag == TAG_VOTE:
                j.record(
                    "recv.vote",
                    payload.round,
                    payload.hash,
                    str(payload.author)[:8],
                )
            elif tag == TAG_TIMEOUT:
                j.record(
                    "recv.timeout",
                    payload.round,
                    None,
                    str(payload.author)[:8],
                )
            elif tag == TAG_TC:
                j.record("recv.tc", payload.round)
            elif tag == TAG_SYNC_REQUEST:
                j.record("recv.sync_req", 0, payload[0], str(payload[1])[:8])
            elif tag == TAG_PRODUCER:
                # producer-channel edge (ROADMAP PR 2 follow-up): lets
                # traces attribute payload starvation vs consensus stall
                j.record("recv.producer", 0, payload[0], "client")
            elif tag == TAG_PRODUCER_V2:
                # sampled: the batch's first digest stands for the frame
                j.record("recv.producer", 0, payload[0][0], "client")
            elif tag == TAG_STATE_REQUEST:
                j.record(
                    "recv.state_req",
                    payload.from_round,
                    None,
                    str(payload.origin)[:8],
                )
            elif tag == TAG_RECONFIG:
                j.record(
                    "recv.reconfig",
                    0,
                    None,
                    str(payload.sponsor)[:8],
                )
        if tag == TAG_SYNC_REQUEST:
            await self.tx_helper.put(payload)
        elif tag == TAG_PROPOSE:
            try:
                await writer.send(ACK)
            except (ConnectionError, OSError):
                pass
            await self.tx_consensus.put((tag, payload))
        elif tag == TAG_PRODUCER:
            digest, body = payload
            if body:
                # content addressing: a body that doesn't hash to its
                # digest is a poisoned submission — drop it (no ACK)
                from ..crypto import Digest

                if Digest.of(body) != digest:
                    log.warning(
                        "Dropping producer payload whose body does not "
                        "match its digest"
                    )
                    return
            if self.admission is not None:
                decision = self.admission.admit(1)
                if decision.shed:
                    # typed BUSY instead of a silent drop: the legacy
                    # b"Ack" stays byte-compatible on the accept path,
                    # v1 clients that don't parse the busy frame just
                    # discard it and retry at their own pace
                    try:
                        await writer.send(
                            encode_ingest_ack(
                                0,
                                decision.shed,
                                decision.credit,
                                decision.retry_after_ms,
                            )
                        )
                    except (ConnectionError, OSError):
                        pass
                    return
            if body and self.bodies is not None:
                await self.bodies.admit(digest, body)
            try:
                await writer.send(ACK)
            except (ConnectionError, OSError):
                pass
            await self.tx_producer.put(digest)
        elif tag == TAG_PRODUCER_V2:
            # content addressing first: poisoned items are dropped and
            # never consume admission credit (a client can't burn the
            # committee's window with garbage bodies)
            from ..crypto import Digest

            valid = []
            for digest, body in payload:
                if body and Digest.of(body) != digest:
                    log.warning(
                        "Dropping batched producer payload whose body "
                        "does not match its digest"
                    )
                    if self._dropped is not None:
                        self._dropped.inc()
                    continue
                valid.append((digest, body))
            if self.admission is not None:
                decision = self.admission.admit(len(valid))
            else:
                from ..ingest import Decision

                decision = Decision(len(valid), 0, 0, 0)
            # the accepted prefix enters; the shed suffix is the
            # client's to resubmit after retry_after_ms (order is
            # preserved on the wire, so "first N" is well-defined)
            for digest, body in valid[: decision.accepted]:
                if body and self.bodies is not None:
                    await self.bodies.admit(digest, body)
                await self.tx_producer.put(digest)
            try:
                await writer.send(
                    encode_ingest_ack(
                        decision.accepted,
                        decision.shed,
                        decision.credit,
                        decision.retry_after_ms,
                    )
                )
            except (ConnectionError, OSError):
                pass
        elif tag == TAG_STATE_REQUEST:
            if self.tx_state_requests is not None:
                await self.tx_state_requests.put(payload)
        elif tag in (TAG_STATE_MANIFEST, TAG_STATE_CHUNK):
            # replies matter only while the one-shot boot catch-up is
            # collecting; afterwards nothing drains the queue, so late
            # frames are shed instead of wedging the receiver on a put
            if self.tx_state_sync is not None:
                try:
                    self.tx_state_sync.put_nowait((tag, payload))
                except asyncio.QueueFull:
                    pass
        elif tag == TAG_STATE_READ:
            await self._serve_state_read(writer, payload)
        else:
            await self.tx_consensus.put((tag, payload))

    async def dispatch_producer_v2(
        self, writer: Writer, frame: bytes, digests: bytes, spans: list
    ) -> None:
        """Zero-copy ingest fast path for batched producer frames
        (ISSUE 20): the native parser already validated wire bounds and
        emitted the digest column plus ``(offset, length)`` body windows
        into ``frame``, so this mirrors the TAG_PRODUCER_V2 branch of
        ``dispatch`` without building per-item payload tuples — bodies
        stay memoryview windows and only ACCEPTED items materialize
        bytes for the body store.  Wire parity with the Python Decoder
        is enforced by the differential fuzz corpus
        (tests/test_wire_fuzz.py); any frame the native parser rejects
        takes the normal decode path instead of this one."""
        from ..crypto import Digest

        if self._msg_counters is not None and TAG_PRODUCER_V2 < len(
            self._msg_counters
        ):
            self._msg_counters[TAG_PRODUCER_V2].inc()
        mv = memoryview(frame)
        j = self._journal
        if j is not None and spans:
            # sampled: the batch's first digest stands for the frame
            j.record("recv.producer", 0, Digest(bytes(digests[:32])), "client")
        valid = []
        for i, (off, ln) in enumerate(spans):
            digest = Digest(bytes(digests[i * 32 : (i + 1) * 32]))
            body = mv[off : off + ln]
            if ln and Digest.of(body) != digest:
                log.warning(
                    "Dropping batched producer payload whose body "
                    "does not match its digest"
                )
                if self._dropped is not None:
                    self._dropped.inc()
                continue
            valid.append((digest, body))
        if self.admission is not None:
            decision = self.admission.admit(len(valid))
        else:
            from ..ingest import Decision

            decision = Decision(len(valid), 0, 0, 0)
        for digest, body in valid[: decision.accepted]:
            if len(body) and self.bodies is not None:
                await self.bodies.admit(digest, bytes(body))
            await self.tx_producer.put(digest)
        try:
            await writer.send(
                encode_ingest_ack(
                    decision.accepted,
                    decision.shed,
                    decision.credit,
                    decision.retry_after_ms,
                )
            )
        except (ConnectionError, OSError):
            pass

    async def _serve_state_read(self, writer: Writer, payload) -> None:
        """QC-anchored stale read: answer at the last applied version —
        by construction while catching up, too — with the anchor
        (version, root, last_round) in the reply."""
        space, key = payload
        state = self.state
        if state is None:
            reply = encode_state_value(False, 0, b"\x00" * 32, 0, 0, b"")
        else:
            version, root, last_round = state.anchor()
            found, entry_round, value = False, 0, b""
            if space == STATE_READ_LEDGER:
                hit = state.read_ledger(key)
                if hit is not None:
                    entry_round, seq = hit
                    found, value = True, seq.to_bytes(4, "little")
            else:
                hit = state.read_user(key)
                if hit is not None:
                    entry_round, value = hit
                    found = True
            reply = encode_state_value(
                found, version, root, last_round, entry_round, value
            )
        try:
            await writer.send(reply)
        except (ConnectionError, OSError):
            pass


class Consensus:
    """Owns the spawned actor stack of one node's protocol engine."""

    def __init__(self):
        self.receiver: NetworkReceiver | None = None
        self.core: Core | None = None
        self.proposer: Proposer | None = None
        self.helper: Helper | None = None
        self.synchronizer: Synchronizer | None = None
        self.tx_producer: asyncio.Queue | None = None
        self.admission = None
        self.state_machine = None
        self.state_server = None
        self._tasks: list[asyncio.Task] = []

    @classmethod
    async def spawn(
        cls,
        name: PublicKey,
        committee: Committee,
        parameters: Parameters,
        signature_service: SignatureService,
        store: Store,
        tx_commit: asyncio.Queue,
        verifier: VerifierBackend | None = None,
        bind_host: str = "0.0.0.0",
        transport: str = "asyncio",
        telemetry=None,
    ) -> "Consensus":
        self = cls()
        # NOTE: this log entry is used to compute performance.
        parameters.log()
        # BLS committees: refuse to run without a valid proof of
        # possession per member — sum-of-keys QC verification is
        # rogue-key forgeable otherwise (see Authority.pop).
        committee.verify_pops()
        if verifier is None:
            verifier = CpuVerifier()

        payload_bodies = PayloadBodies(store, parameters.payload_body_budget)
        # Replicated execution layer (store/state.py): the commit path
        # applies every committed block through it; the receiver serves
        # QC-anchored stale reads from it; the state-sync actors below
        # snapshot it for crash-recovered peers.
        from ..store.state import StateMachine

        state_machine = StateMachine(store)
        self.state_machine = state_machine
        tx_state_requests: asyncio.Queue = asyncio.Queue(
            maxsize=CHANNEL_CAPACITY
        )
        tx_state_sync: asyncio.Queue = asyncio.Queue(maxsize=CHANNEL_CAPACITY)
        if telemetry is not None:
            telemetry.gauge(
                "state_version",
                "Applied state version (committed blocks folded into "
                "the state root)",
                fn=lambda s=state_machine: s.version,
            )
            telemetry.gauge(
                "state_last_round",
                "Round of the last block applied to the state machine",
                fn=lambda s=state_machine: s.last_round,
            )
            telemetry.gauge(
                "state_applied_payloads",
                "Payload digests folded into the replicated ledger",
                fn=lambda s=state_machine: s.applied_payloads,
            )
            telemetry.gauge(
                "state_typed_ops",
                "Typed user-KV operations materialized from local bodies",
                fn=lambda s=state_machine: s.typed_ops,
            )
            telemetry.gauge(
                "state_snapshots_served",
                "Snapshot manifests served to syncing peers",
                fn=lambda s=state_machine: s.snapshots_served,
            )
            telemetry.add_section("state", state_machine.stats)
        if telemetry is not None:
            telemetry.gauge(
                "payload_pending_bytes",
                "Uncommitted payload bodies held against the byte budget",
                fn=lambda b=payload_bodies: b._pending_bytes,
            )
            telemetry.gauge(
                "payload_evictions",
                "Payload bodies evicted under budget pressure",
                fn=lambda b=payload_bodies: b.evicted,
            )
            telemetry.add_section(
                "payload_bodies",
                lambda b=payload_bodies: {
                    "pending": len(b._pending),
                    "pending_bytes": b._pending_bytes,
                    "evicted": b.evicted,
                },
            )
        # Ingest admission controller (ingest/admission.py): constructed
        # before the receiver so the handler can consult it from the
        # first frame; bound to the proposer's buffer once the proposer
        # exists below (until then occupancy reads 0 — boot window).
        from ..ingest import AdmissionController

        admission = AdmissionController(
            journal=telemetry.journal if telemetry is not None else None,
        )
        tx_producer: asyncio.Queue = asyncio.Queue(maxsize=CHANNEL_CAPACITY)
        # The core's three select sources merge into ONE event queue
        # (core.make_event_channels); producers keep channel-shaped
        # facades, so the topology the reference wires (consensus.rs:
        # 54-58) is unchanged from their side.  Capacity 2x: the merged
        # queue carries what two channels carried.
        rx_events, tx_consensus, tx_loopback = make_event_channels(
            2 * CHANNEL_CAPACITY
        )
        tx_proposer: asyncio.Queue = asyncio.Queue(maxsize=CHANNEL_CAPACITY)
        tx_helper: asyncio.Queue = asyncio.Queue(maxsize=CHANNEL_CAPACITY)
        self.tx_producer = tx_producer

        import os

        address = committee.address(name)
        joining = False
        if address is None:
            # Join mode (docs/RECONFIG.md): a node whose key is not yet
            # in any scheduled committee may boot against a peer's
            # committee file, state-sync the certified schedule in, and
            # start voting once a committed reconfiguration admits it.
            listen = os.environ.get("HOTSTUFF_RECONFIG_LISTEN")
            if not listen:
                raise ValueError(
                    "Our public key is not in the committee (set "
                    "HOTSTUFF_RECONFIG_LISTEN=host:port to join via a "
                    "certified reconfiguration)"
                )
            host, _, port = listen.rpartition(":")
            address = (host or "127.0.0.1", int(port))
            joining = True
            log.info(
                "Join mode: key not in the committee yet; listening on "
                "%s:%d and awaiting a certified schedule",
                address[0],
                address[1],
            )
        # Bind on all interfaces, listen on our committee port
        # (consensus.rs:61-73 rewrites the IP to 0.0.0.0).
        # transport="native": the C++ epoll reactor (network/native.py)
        # carries the framed TCP I/O; the actor graph is unchanged.
        # WAN emulation (HOTSTUFF_WAN_SPEC, network/wan.py): per-link
        # propagation delay on every node->node sender — the committee
        # experiences the reference's 5-region topology on localhost.
        # asyncio transport only (the native reactor does its own I/O).
        link_delay = None
        wan_spec = os.environ.get("HOTSTUFF_WAN_SPEC")
        if wan_spec and transport != "native":
            from ..network.wan import WanModel

            model = WanModel.load(wan_spec, address)
            log.info(
                "WAN emulation active: region %s", model.self_region
            )

            def link_delay(dst, _model=model):  # noqa: E731 — closure
                return lambda: _model.delay(dst)

        # Chaos plane (HOTSTUFF_FAULTS, faults/plane.py): seeded
        # deterministic fault injection, threaded through every sender
        # the same way link_delay is.  Works on both transports.
        fault_plane = None
        faults_spec = os.environ.get("HOTSTUFF_FAULTS")
        if faults_spec:
            from ..faults import FaultPlane

            fault_plane = FaultPlane.load(faults_spec, address)
            log.info("Fault plane active: %s", fault_plane.describe())

        # Byzantine adversary plane (HOTSTUFF_ADVERSARY, faults/
        # adversary.py): protocol-level attack injection at the
        # proposer/core seams.  The spec is shared committee-wide (the
        # chaos runner points it at the same file as HOTSTUFF_FAULTS);
        # the plane stays inert unless it names this node.
        adversary = None
        adversary_spec = os.environ.get("HOTSTUFF_ADVERSARY")
        if adversary_spec:
            from ..faults import AdversaryPlane

            plane = AdversaryPlane.load(adversary_spec, address)
            if plane.enabled:
                adversary = plane
                adversary.bind(committee, name)
                log.info("Adversary plane active: %s", adversary.describe())

        # Wire-level flow accounting (ISSUE 19, telemetry/flows.py):
        # one accountant per node, threaded through every sender and
        # the receiver the way the fault plane is — each frame charged
        # to a (peer, direction, class) flow at its transmit/receive
        # site, surfaced as the snapshot's ``flows`` section.
        flows = None
        if telemetry is not None:
            from ..telemetry.flows import FlowAccounting

            flows = FlowAccounting(node=str(name))
            flows.label_peers(
                (str(peer)[:8], addr)
                for peer, addr in committee.broadcast_addresses(name)
            )
            telemetry.attach_flows(flows)

        if transport == "native":
            from ..network.native import (
                NativeReceiver,
                NativeReliableSender,
                NativeSimpleSender,
            )

            receiver_cls = NativeReceiver

            def make_sender():
                return NativeSimpleSender(fault_plane=fault_plane, flows=flows)

            def make_reliable():
                return NativeReliableSender(
                    fault_plane=fault_plane, flows=flows
                )
        elif transport == "sim":
            # Virtual-time simulation (hotstuff_tpu/sim): the stock
            # asyncio senders run verbatim — the ambient connector seam
            # routes their connections through the in-memory SimNet —
            # and only the listener side needs the sim class.
            from ..network import ReliableSender, SimpleSender
            from ..sim.transport import SimReceiver

            receiver_cls = SimReceiver
            # Virtual link propagation: without it every hop lands in
            # the same virtual instant and rounds advance at raw CPU
            # speed — a 12-virtual-second run would burn thousands of
            # rounds of signature work.  A fixed per-hop delay paces the
            # protocol like a LAN and makes per-seed CPU cost
            # proportional to virtual duration, not host speed.
            if link_delay is None:
                sim_link_s = (
                    float(os.environ.get("HOTSTUFF_SIM_LINK_MS", "50"))
                    / 1000.0
                )
                if sim_link_s > 0:

                    def link_delay(dst, _d=sim_link_s):
                        return lambda: _d

            def make_sender():
                return SimpleSender(
                    link_delay=link_delay,
                    fault_plane=fault_plane,
                    flows=flows,
                )

            def make_reliable():
                return ReliableSender(
                    link_delay=link_delay,
                    fault_plane=fault_plane,
                    flows=flows,
                )
        else:
            from ..network import ReliableSender, SimpleSender

            receiver_cls = NetworkReceiver
            # Bounded per-sender connection pools for big co-located
            # committees (set by run-many from its fd budget;
            # absent/non-positive = reference parity, unbounded)
            from ..network.pool import parse_max_conns

            max_conns = parse_max_conns(
                os.environ.get("HOTSTUFF_MAX_PEER_CONNS")
            )

            def make_sender():
                return SimpleSender(
                    link_delay=link_delay,
                    max_conns=max_conns,
                    fault_plane=fault_plane,
                    flows=flows,
                )

            def make_reliable():
                return ReliableSender(
                    link_delay=link_delay,
                    max_conns=max_conns,
                    fault_plane=fault_plane,
                    flows=flows,
                )
        self.receiver = receiver_cls(
            bind_host,
            address[1],
            ConsensusReceiverHandler(
                tx_consensus, tx_helper, tx_producer,
                # mixed-scheme schedules accept the union on the wire
                scheme=committee.wire_scheme(),
                bodies=payload_bodies,
                telemetry=telemetry,
                admission=admission,
                tx_state_requests=tx_state_requests,
                tx_state_sync=tx_state_sync,
                state=state_machine,
                committee=committee,
            ),
            fault_plane=fault_plane,
            flows=flows,
        )
        await self.receiver.spawn()
        log.info(
            "Node %s listening to consensus messages on %s:%d",
            name,
            bind_host,
            address[1],
        )

        if fault_plane is not None:
            from ..faults import run_clock

            journal = telemetry.journal if telemetry is not None else None
            self._tasks.append(
                asyncio.get_running_loop().create_task(
                    run_clock(fault_plane, journal),
                    name="fault-clock",
                )
            )
            if telemetry is not None:
                for count_name, help_text in (
                    ("dropped", "Frames dropped by the fault plane"),
                    ("delayed", "Frames delayed by the fault plane"),
                    ("duplicated", "Frames duplicated by the fault plane"),
                    ("corrupted", "Frames corrupted by the fault plane"),
                    (
                        "inbound_dropped",
                        "Inbound frames swallowed during isolate windows",
                    ),
                ):
                    telemetry.gauge(
                        f"fault_{count_name}",
                        help_text,
                        fn=lambda p=fault_plane, k=count_name: p.counts[k],
                    )
                telemetry.add_section("fault_plane", fault_plane.stats)

        if adversary is not None:
            from ..faults import run_adversary_clock, run_flood

            journal = telemetry.journal if telemetry is not None else None
            adversary.journal = journal
            loop = asyncio.get_running_loop()
            self._tasks.append(
                loop.create_task(
                    run_adversary_clock(adversary, journal),
                    name="adversary-clock",
                )
            )
            if any(r.policy == "flood" for r in adversary.my_rules):
                self._tasks.append(
                    loop.create_task(
                        run_flood(adversary, committee, name),
                        name="adversary-flood",
                    )
                )
            if telemetry is not None:
                for count_name, help_text in (
                    ("byz_equivocations", "Conflicting blocks signed"),
                    ("byz_forged_qcs", "Forged QCs shipped"),
                    ("byz_votes_withheld", "Votes withheld"),
                    ("byz_double_votes", "Conflicting votes cast"),
                    ("byz_floods", "Garbage bursts sent"),
                    ("byz_shadow_commits", "Shadow-branch commits logged"),
                    ("byz_forged_reconfigs", "Forged reconfig ops proposed"),
                    ("byz_shadow_epochs", "Skewed epoch activations logged"),
                    ("byz_flood_accepted", "Flood payloads the victim admitted"),
                    ("byz_flood_shed", "Flood payloads the victim shed"),
                    ("byz_adapt_ambush", "ambush-leader trigger firings"),
                    ("byz_adapt_sync", "sync-predator trigger firings"),
                    ("byz_adapt_surf", "timeout-surfer trigger firings"),
                    ("byz_adapt_snipe", "reconfig-sniper trigger firings"),
                ):
                    telemetry.gauge(
                        count_name,
                        help_text,
                        fn=lambda p=adversary, k=count_name: p.counts[k],
                    )
                telemetry.add_section("adversary", adversary.stats)

        leader_elector = LeaderElector(committee)
        self.synchronizer = Synchronizer(
            name,
            committee,
            store,
            tx_loopback,
            parameters.sync_retry_delay,
            network=make_sender(),
            telemetry=telemetry,
        )
        # Per-peer network gauges at EVERY committee size (ISSUE 19
        # no-silent-caps rule): register_network caps the registered
        # gauge cardinality at PEER_GAUGE_MAX_COMMITTEE and counts the
        # rest in net_peers_elided — nothing is silently dropped.  All
        # four senders dial the same peer set (the broadcast
        # addresses); works for bare committees and epoch schedules
        # alike (union view).
        peers = None
        if telemetry is not None:
            peers = committee.broadcast_addresses(name)
        if telemetry is not None:
            telemetry.register_store(store)
            telemetry.register_network(
                "sync", self.synchronizer.network, peers=peers
            )
            telemetry.gauge(
                "sync_expired",
                "Parent-sync requests abandoned at the give-up deadline",
                fn=lambda s=self.synchronizer: s.expired,
            )

        self.core = Core(
            name,
            committee,
            signature_service,
            verifier,
            store,
            leader_elector,
            self.synchronizer,
            parameters.timeout_delay,
            timeout_backoff=parameters.timeout_backoff,
            timeout_cap_ms=parameters.timeout_cap_ms,
            rx_events=rx_events,
            rx_loopback=tx_loopback,
            tx_proposer=tx_proposer,
            tx_commit=tx_commit,
            network=make_sender(),
            payload_bodies=payload_bodies,
            telemetry=telemetry,
            adversary=adversary,
            state_machine=state_machine,
        )
        if adversary is not None:
            # Adaptive adversary state view (faults/adaptive.py): pure
            # reads of local protocol state, installed before any task
            # runs so triggers never observe a half-built node.  The
            # committee schedule and timer are read live — reconfig
            # splices and view-change backoff show through.
            adversary.bind_view({
                "round": lambda c=self.core: c.round,
                "leader": lambda r, le=leader_elector: le.get_leader(r),
                "self": lambda n=name: n,
                "last_tc_round": lambda c=self.core: c._last_tc_round,
                "timeout_ms": lambda c=self.core: c.timer.duration * 1000.0,
                "credit": lambda a=admission: a.last_credit,
                "boundaries": lambda c=committee: tuple(
                    r for r, _ in getattr(c, "entries", ()) if r > 0
                ),
            })
        # State-sync plane (statesync.py): every node serves snapshots;
        # a recovering node (surviving consensus state ⇒ this is a
        # restart, not a first boot) additionally runs the one-shot
        # boot catch-up before entering the protocol.  Modes:
        # HOTSTUFF_STATE_SYNC=auto (default: catch up when recovering),
        # always (also on a fresh join), 0/off (never).
        self.state_server = StateSyncServer(
            name,
            committee,
            state_machine,
            rx_requests=tx_state_requests,
            high_qc=lambda c=self.core: c.high_qc,
            network=make_sender(),
            telemetry=telemetry,
            store=store,
            adversary=adversary,
        )
        sync_mode = os.environ.get("HOTSTUFF_STATE_SYNC", "auto").lower()
        if sync_mode not in ("0", "off", "never"):
            recovering = (await store.read(CONSENSUS_STATE_KEY)) is not None
            if (recovering or joining or sync_mode == "always") and (
                committee.broadcast_addresses(name)
            ):
                self.core.state_sync = StateSyncClient(
                    name,
                    committee,
                    state_machine,
                    verifier,
                    rx_replies=tx_state_sync,
                    network=make_sender(),
                    # a joiner adopts whatever certified snapshot is on
                    # offer — its alternative is walking history it may
                    # not be able to fetch at all
                    min_lag=0 if joining else None,
                    telemetry=telemetry,
                    store=store,
                    synchronizer=self.synchronizer,
                )
        self._tasks.append(self.state_server.spawn())
        self._tasks.append(self.core.spawn())

        self.proposer = Proposer(
            name,
            committee,
            signature_service,
            rx_producer=tx_producer,
            rx_message=tx_proposer,
            tx_loopback=tx_loopback,
            network=make_reliable(),
            telemetry=telemetry,
            adversary=adversary,
            admission=admission,
        )
        self._tasks.append(self.proposer.spawn())
        self.admission = admission
        # Credit windows now track the real buffer: occupancy is the
        # proposer's pending map, capacity its (env-tunable) cap.
        admission.bind(
            lambda p=self.proposer: len(p.pending),
            capacity=self.proposer.max_pending,
        )
        if telemetry is not None:
            telemetry.gauge(
                "ingest_credit",
                "Current admission credit window (payloads)",
                fn=lambda a=admission: a.last_credit,
            )
            telemetry.gauge(
                "ingest_accepted",
                "Producer payloads admitted by the ingest plane",
                fn=lambda a=admission: a.accepted_total,
            )
            telemetry.gauge(
                "ingest_shed",
                "Producer payloads shed with a typed BUSY reply",
                fn=lambda a=admission: a.shed_total,
            )
            telemetry.gauge(
                "ingest_busy_frames",
                "Producer frames answered with a BUSY ingest ACK",
                fn=lambda a=admission: a.busy_frames,
            )
            telemetry.gauge(
                "ingest_connections",
                "Live accepted connections on the consensus port",
                fn=lambda r=self.receiver: getattr(r, "connections", 0),
            )
            # one section carries the whole admission story: the
            # controller's own counters plus the buffer's silent-drop
            # count (zero whenever backpressure is doing its job)
            telemetry.add_section(
                "ingest",
                lambda a=admission, p=self.proposer: {
                    **a.stats(),
                    "drop_newest": p.drop_newest,
                },
            )

        self.helper = Helper(
            committee,
            store,
            rx_requests=tx_helper,
            network=make_sender(),
            telemetry=telemetry,
        )
        self._tasks.append(self.helper.spawn())
        if telemetry is not None:
            telemetry.register_network("core", self.core.network, peers=peers)
            telemetry.register_network(
                "proposer", self.proposer.network, peers=peers
            )
            telemetry.register_network(
                "helper", self.helper.network, peers=peers
            )
        return self

    async def shutdown(self) -> None:
        if self.receiver is not None:
            await self.receiver.shutdown()
        for component in (
            self.core, self.proposer, self.helper, self.state_server,
        ):
            if component is not None:
                component.shutdown()
        if self.synchronizer is not None:
            self.synchronizer.shutdown()
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()
