"""Vote/timeout aggregation into certificates — accumulate-then-dispatch.

Parity target: reference ``Aggregator``/``QCMaker``/``TCMaker``
(consensus/src/aggregator.rs:13-139), restructured per the BASELINE.json
north star: votes are accumulated *unverified* and the whole signature set
ships to the ``VerifierBackend`` as ONE batch when a quorum's stake has
arrived — one batched kernel call per certificate instead of 2f+1
sequential verifies on the hot path.

Hardening beyond the reference (messages arrive over unauthenticated TCP,
so deferred verification must not open spoofing holes):

- If the batch fails at quorum, invalid entries are identified
  per-signature and evicted, their authors are *released* (so the honest
  authority's real vote can still land — a spoofed garbage vote cannot
  suppress it) and marked suspect: subsequent votes naming a suspect
  author are verified eagerly on entry, so garbage floods cost the
  attacker a rejected verify instead of aggregator state.
- Aggregation state is bounded: votes/timeouts further than
  ``ROUND_LOOKAHEAD`` past the node's current round are rejected, and at
  most ``MAX_DIGEST_CELLS`` distinct block digests are tracked per round
  (the reference's unbounded maps are a known DoS, aggregator.rs:29-30).

Timeouts are verified on entry by the core (like the reference,
core.rs:288), so ``TCMaker`` accumulates pre-verified entries and emits
the TC without re-verification.
"""

from __future__ import annotations

import logging

from ..crypto import Digest, PublicKey, Signature
from ..crypto.service import VerifierBackend
from .config import Committee
from .errors import AuthorityReuse, ConsensusError, InvalidSignature, UnknownAuthority
from .messages import QC, TC, Round, Timeout, Vote

log = logging.getLogger(__name__)

# How far past the current round aggregation state may be created.
ROUND_LOOKAHEAD = 64
# Distinct block digests tracked per round (honest case: exactly one).
MAX_DIGEST_CELLS = 8


class AggregationBounds(ConsensusError):
    def __init__(self, what: str):
        super().__init__(f"Rejected {what}: aggregation bounds exceeded")


class QCMaker:
    """Accumulates votes over one (round, block-digest) cell into a QC."""

    def __init__(self):
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature]] = []
        self.used: set[PublicKey] = set()
        self.suspect: set[PublicKey] = set()  # authors with an evicted sig
        # owning Aggregator (set at cell admission) — rejected-signature
        # accounting rolls up there so it survives round cleanup
        self.owner: "Aggregator | None" = None
        # True once the cell holds at least one signature that passed
        # verification.  Cells that never earn this are evictable when the
        # per-round digest-cell budget fills up (ADVICE r1: otherwise 8
        # spoofed votes with random digests suppress honest votes for the
        # real block all round).
        self.verified = False
        # Protected cells (the digest this node itself voted for) are
        # never evicted.
        self.protected = False
        # Entries whose signature was NOT individually pre-verified on
        # entry (async-preverify path, core._preverify_burst).  When
        # empty at quorum, the batch dispatch is skipped — every
        # signature in the certificate already passed.
        self.unverified: set[PublicKey] = set()

    def append(
        self,
        vote: Vote,
        committee: Committee,
        verifier: VerifierBackend,
        stake: int | None = None,
        sig_verified: bool = False,
    ) -> QC | None:
        author = vote.author
        if author in self.used:
            # A second vote naming an already-counted author. Since votes
            # are unauthenticated on entry, the FIRST one may have been an
            # attacker's spoof racing the honest vote — if this one carries
            # a different, eagerly-verified-valid signature and the stored
            # one is invalid, swap it in (weight is unchanged: the author
            # was already counted). Without the swap, whichever message
            # wins the race would decide whether the honest vote ever
            # counts (vote-suppression attack).
            self._maybe_replace(vote, verifier, incoming_verified=sig_verified)
            raise AuthorityReuse(author)
        if stake is None:
            stake = committee.stake(author)
        if stake <= 0:
            raise UnknownAuthority(author)
        if sig_verified:
            self.verified = True
        elif author in self.suspect:
            # this author's slot was already poisoned once — pay one eager
            # verify instead of trusting the deferred batch again
            if not verifier.verify_one(vote.digest(), author, vote.signature):
                if self.owner is not None:
                    self.owner.qc_rejects += 1
                raise InvalidSignature(f"bad signature on vote {vote!r}")
            self.verified = True
        else:
            self.unverified.add(author)
        self.used.add(author)
        self.votes.append((author, vote.signature))
        self.weight += stake
        if self.weight < committee.quorum_threshold():
            return None

        # Quorum reached: dispatch the whole set as one batch — unless
        # every entry was already individually pre-verified (the async
        # preverify path), in which case the certificate is proven.
        if self.unverified and not verifier.verify_shared_msg(
            vote.digest(), self.votes
        ):
            self._evict_invalid(vote.digest(), committee, verifier)
            if self.weight < committee.quorum_threshold():
                return None  # keep accumulating

        self.verified = True
        self.weight = 0  # a QC is made at most once
        return QC(hash=vote.hash, round=vote.round, votes=list(self.votes))

    def check_any_valid(self, digest: Digest, verifier: VerifierBackend) -> bool:
        """Verify the stored signatures against the cell's vote digest;
        mark the cell verified (and report True) if any is genuine."""
        if not self.votes:
            return False
        ok = verifier.verify_many(
            [digest.to_bytes()] * len(self.votes),
            [pk.to_bytes() for pk, _ in self.votes],
            [sig.to_bytes() for _, sig in self.votes],
        )
        if any(ok):
            self.verified = True
            return True
        return False

    def _maybe_replace(
        self, vote: Vote, verifier: VerifierBackend,
        incoming_verified: bool = False,
    ) -> None:
        for i, (pk, sig) in enumerate(self.votes):
            if pk != vote.author:
                continue
            if sig == vote.signature:
                return  # true duplicate
            if (
                incoming_verified
                or verifier.verify_one(vote.digest(), vote.author, vote.signature)
            ) and not verifier.verify_one(vote.digest(), pk, sig):
                log.warning(
                    "Replacing spoofed vote signature naming %s with the "
                    "authenticated one",
                    pk,
                )
                self.votes[i] = (vote.author, vote.signature)
                self.unverified.discard(pk)
            return

    def _evict_invalid(
        self, digest: Digest, committee: Committee, verifier: VerifierBackend
    ) -> None:
        ok = verifier.verify_many(
            [digest.to_bytes()] * len(self.votes),
            [pk.to_bytes() for pk, _ in self.votes],
            [sig.to_bytes() for _, sig in self.votes],
        )
        for (pk, _), valid in zip(self.votes, ok):
            if not valid:
                log.warning("Evicting invalid vote signature naming %s", pk)
                if self.owner is not None:
                    self.owner.qc_rejects += 1
                # release the author — the signature was never authenticated,
                # so this may be a spoof and the real vote must still count —
                # but demand eager verification from now on
                self.used.discard(pk)
                self.suspect.add(pk)
        self.votes = [v for v, valid in zip(self.votes, ok) if valid]
        # every survivor just passed a per-signature check
        self.unverified.clear()
        self.weight = sum(committee.stake(pk) for pk, _ in self.votes)
        if self.votes:
            self.verified = True  # survivors passed per-signature checks


class TCMaker:
    """Accumulates timeouts for one round into a TC.

    Entries are verified by the core before they reach this accumulator
    (core._handle_timeout, mirroring reference core.rs:288), so the TC is
    emitted without re-verification — same shape as the reference's
    TCMaker (aggregator.rs:97-139).
    """

    def __init__(self):
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature, Round]] = []
        self.used: set[PublicKey] = set()

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        author = timeout.author
        if author in self.used:
            raise AuthorityReuse(author)
        stake = committee.stake(author)
        if stake <= 0:
            raise UnknownAuthority(author)
        self.used.add(author)
        self.votes.append((author, timeout.signature, timeout.high_qc.round))
        self.weight += stake
        if self.weight < committee.quorum_threshold():
            return None
        self.weight = 0  # a TC is made at most once
        return TC(round=timeout.round, votes=list(self.votes))


class Aggregator:
    """Per-round certificate accumulators with cleanup and DoS bounds.

    ``self_key`` (the node's own public key) powers the liveness
    guarantee: QC formation only ever matters for the block this node
    itself voted for (voters address votes to the next leader, and the
    leader votes for its own proposal), so the digest cell matching a
    self-authored vote is admitted unconditionally — evicting a
    non-protected cell at the cap — and can never be evicted itself.
    """

    def __init__(
        self,
        committee: Committee,
        verifier: VerifierBackend,
        self_key: PublicKey | None = None,
    ):
        self.committee = committee
        self.verifier = verifier
        self.self_key = self_key
        self.votes_aggregators: dict[Round, dict[Digest, QCMaker]] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}
        # Authors whose valid signature already paid for an extra digest
        # cell this round: a second paid cell from the same author is
        # proof of equivocation and is refused (one Byzantine member must
        # not consume the whole cell budget with validly-signed votes for
        # random digests).
        self.cell_payers: dict[Round, set[PublicKey]] = {}
        # Verified votes that found the cell budget exhausted before this
        # node's own (protected) cell existed — replayed into the
        # protected cell when it is admitted, so a coalition racing its
        # equivocations ahead of the real proposal can't permanently drop
        # honest votes.  Bounded: one vote per author per round.
        self.parked: dict[Round, dict[PublicKey, Vote]] = {}
        # Cumulative accounting (plain ints, always on — telemetry reads
        # them through Core's snapshot section when enabled).
        self.cells_evicted = 0
        self.votes_parked = 0
        # Honest-side Byzantine defense counters: signatures rejected in
        # certificate verification (vote evictions, suspect-path
        # rejects, and invalid timeout certificates counted by the
        # core) and equivocation evidence (a second paid digest cell
        # from one author — conflicting validly-signed votes).
        self.qc_rejects = 0
        self.vote_conflicts = 0

    def add_vote(
        self,
        vote: Vote,
        current_round: Round | None = None,
        sig_verified: bool = False,
    ) -> QC | None:
        """``sig_verified=True``: the vote's signature was individually
        pre-verified (async burst preverify or a self-signed vote) — the
        cell skips deferred-batch bookkeeping for it and, when every
        entry arrived pre-verified, emits the QC without a quorum batch."""
        if (
            current_round is not None
            and vote.round > current_round + ROUND_LOOKAHEAD
        ):
            raise AggregationBounds(f"vote for far-future round {vote.round}")
        # Authority check before any aggregation state is created, so
        # UnknownAuthority rejections cannot leave empty cells behind.
        # Epoch seam: stake/quorum come from the VOTE round's committee.
        com = self.committee.for_round(vote.round)
        stake = com.stake(vote.author)
        if stake <= 0:
            raise UnknownAuthority(vote.author)
        makers = self.votes_aggregators.setdefault(vote.round, {})
        digest = vote.digest()
        maker = makers.get(digest)
        created = maker is None
        if created:
            maker = self._admit_cell(
                vote, digest, makers, sig_verified=sig_verified
            )
        qc = maker.append(
            vote, com, self.verifier, stake=stake, sig_verified=sig_verified
        )
        if created and maker.protected:
            qc = self._replay_parked(vote.round, digest, maker) or qc
        return qc

    def _park(self, vote: Vote) -> None:
        """Remember a verified-but-unplaceable vote (one per author/round)."""
        self.parked.setdefault(vote.round, {}).setdefault(vote.author, vote)
        self.votes_parked += 1

    def _replay_parked(
        self, round_: Round, digest: Digest, maker: QCMaker
    ) -> QC | None:
        """Feed parked votes matching the protected cell's digest back in."""
        parked = self.parked.get(round_)
        if not parked:
            return None
        qc = None
        for author in [a for a, v in parked.items() if v.digest() == digest]:
            vote = parked.pop(author)
            try:
                got = maker.append(
                    vote, self.committee.for_round(round_), self.verifier
                )
            except ConsensusError:
                continue
            qc = got or qc
        return qc

    def _admit_cell(
        self,
        vote: Vote,
        digest: Digest,
        makers: dict[Digest, QCMaker],
        sig_verified: bool = False,
    ) -> QCMaker:
        """Create a new digest cell, charging for it when it isn't the first.

        The honest case is exactly one digest per round, so every
        ADDITIONAL cell must be paid for with a valid signature — spoofed
        votes carrying random digests cost the attacker a rejected verify
        instead of a slot in the cell budget (per-round vote-suppression
        DoS otherwise: 8 garbage digests would exhaust MAX_DIGEST_CELLS
        and honest votes for the real block would bounce).  Each author
        may pay for at most one cell per round (a second one is proof of
        equivocation), and a self-authored vote's cell is admitted
        unconditionally and marked protected (see class docstring).
        """
        own = self.self_key is not None and vote.author == self.self_key
        verified = False
        if makers and not own:
            if not sig_verified and not self.verifier.verify_one(
                digest, vote.author, vote.signature
            ):
                raise InvalidSignature(f"bad signature on vote {vote!r}")
            payers = self.cell_payers.setdefault(vote.round, set())
            if vote.author in payers:
                # One paid cell per author per round.  The vote itself is
                # genuine though — votes may legitimately join an
                # EXISTING cell regardless of the author's history — so
                # park it for replay in case its digest gets the
                # protected cell later.  Two validly-signed conflicting
                # votes from one author = equivocation evidence.
                self.vote_conflicts += 1
                self._park(vote)
                raise AggregationBounds(
                    f"second digest cell paid by {vote.author} in round "
                    f"{vote.round} (vote parked)"
                )
            if any(
                vote.author in m.used
                for d, m in makers.items()
                if d != digest
            ):
                # The payment signature verified AND another cell already
                # counts this author for a different digest this round:
                # equivocation evidence (a double-voter's second digest).
                # Accounting only — the paid cell is still admitted, the
                # protocol math is untouched.
                self.vote_conflicts += 1
                log.info(
                    "second digest cell paid by %s in round %d "
                    "(conflicting double-vote evidence)",
                    vote.author,
                    vote.round,
                )
            verified = True
        if len(makers) >= MAX_DIGEST_CELLS and not self._evict_for(
            vote, makers, own
        ):
            # Verified vote, but the budget is full of verified cells and
            # this node's own (protected) cell doesn't exist yet: PARK it
            # for replay when the protected cell lands — a coalition
            # racing equivocations ahead of the real proposal must not
            # permanently drop honest votes.
            self._park(vote)
            raise AggregationBounds(
                f"vote digest cell #{len(makers)} in round {vote.round} "
                f"(vote parked)"
            )
        if verified:
            # charge the payer only once the cell actually exists
            self.cell_payers.setdefault(vote.round, set()).add(vote.author)
        maker = makers[digest] = QCMaker()
        maker.owner = self
        maker.verified = verified or own
        maker.protected = own
        return maker

    def _evict_for(
        self, vote: Vote, makers: dict[Digest, QCMaker], own: bool
    ) -> bool:
        """Make room at the cell cap; False if no cell may be evicted.

        A cell is only evictable if NONE of its stored signatures verify —
        an unverified cell may be the honest block's cell whose batch check
        is simply deferred until quorum, and evicting it would destroy
        accumulated honest votes (per-round liveness loss a Byzantine
        insider could trigger at will).  Checking promotes genuinely
        honest cells to verified, so each cell pays the check at most
        once.  For a SELF-authored vote the cell must be admitted even if
        every other cell is verified: all other cells are by definition
        not this node's block, so evict any non-protected one.
        """
        victim = None
        for d, m in makers.items():
            if m.protected:
                continue
            if not m.verified and not m.check_any_valid(d, self.verifier):
                victim = d
                break
        if victim is None and own:
            victim = next(
                (d for d, m in makers.items() if not m.protected), None
            )
        if victim is None:
            return False
        log.warning("Evicting digest cell to admit %s",
                    "own-vote cell" if own else "a verified one")
        del makers[victim]
        self.cells_evicted += 1
        return True

    def add_timeout(
        self, timeout: Timeout, current_round: Round | None = None
    ) -> TC | None:
        if (
            current_round is not None
            and timeout.round > current_round + ROUND_LOOKAHEAD
        ):
            raise AggregationBounds(
                f"timeout for far-future round {timeout.round}"
            )
        maker = self.timeouts_aggregators.setdefault(timeout.round, TCMaker())
        return maker.append(
            timeout, self.committee.for_round(timeout.round)
        )

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            r: v for r, v in self.votes_aggregators.items() if r >= round_
        }
        self.timeouts_aggregators = {
            r: v for r, v in self.timeouts_aggregators.items() if r >= round_
        }
        self.cell_payers = {
            r: v for r, v in self.cell_payers.items() if r >= round_
        }
        self.parked = {r: v for r, v in self.parked.items() if r >= round_}

    def stats(self) -> dict:
        """Snapshot of aggregation pressure (telemetry pull section)."""
        return {
            "vote_rounds": len(self.votes_aggregators),
            "vote_cells": sum(
                len(m) for m in self.votes_aggregators.values()
            ),
            "pending_votes": sum(
                len(maker.votes)
                for makers in self.votes_aggregators.values()
                for maker in makers.values()
            ),
            "timeout_rounds": len(self.timeouts_aggregators),
            "parked_votes": sum(len(p) for p in self.parked.values()),
            "votes_parked_total": self.votes_parked,
            "cells_evicted_total": self.cells_evicted,
            "qc_rejects_total": self.qc_rejects,
            "vote_conflicts_total": self.vote_conflicts,
        }
