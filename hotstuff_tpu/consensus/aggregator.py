"""Vote/timeout aggregation into certificates — accumulate-then-dispatch.

Parity target: reference ``Aggregator``/``QCMaker``/``TCMaker``
(consensus/src/aggregator.rs:13-139), restructured per the BASELINE.json
north star: votes are accumulated *unverified* and the whole signature set
ships to the ``VerifierBackend`` as ONE batch when a quorum's stake has
arrived — one batched kernel call per certificate instead of 2f+1
sequential verifies on the hot path.

Hardening beyond the reference (messages arrive over unauthenticated TCP,
so deferred verification must not open spoofing holes):

- If the batch fails at quorum, invalid entries are identified
  per-signature and evicted, their authors are *released* (so the honest
  authority's real vote can still land — a spoofed garbage vote cannot
  suppress it) and marked suspect: subsequent votes naming a suspect
  author are verified eagerly on entry, so garbage floods cost the
  attacker a rejected verify instead of aggregator state.
- Aggregation state is bounded: votes/timeouts further than
  ``ROUND_LOOKAHEAD`` past the node's current round are rejected, and at
  most ``MAX_DIGEST_CELLS`` distinct block digests are tracked per round
  (the reference's unbounded maps are a known DoS, aggregator.rs:29-30).

Timeouts are verified on entry by the core (like the reference,
core.rs:288), so ``TCMaker`` accumulates pre-verified entries and emits
the TC without re-verification.
"""

from __future__ import annotations

import logging
import os
import sys

from ..crypto import Digest, PublicKey, Signature
from ..crypto.service import VerifierBackend
from .config import Committee
from .errors import AuthorityReuse, ConsensusError, InvalidSignature, UnknownAuthority
from .messages import QC, TC, Round, Timeout, Vote, make_signer_bitmap

log = logging.getLogger(__name__)

# How far past the current round aggregation state may be created.
ROUND_LOOKAHEAD = 64
# Distinct block digests tracked per round (honest case: exactly one).
MAX_DIGEST_CELLS = 8


def _compact_enabled(committee: Committee) -> bool:
    """Compact (one-agg-sig + signer-bitmap) certificate emission:
    default ON for BLS committees — their G1 signatures aggregate —
    HOTSTUFF_COMPACT_QC=0 reverts to the vote-list form.  Ed25519
    committees always emit vote lists (no aggregate form; the wire
    layer rejects compact certificates for them outright)."""
    return (
        getattr(committee, "scheme", "ed25519") == "bls"
        and os.environ.get("HOTSTUFF_COMPACT_QC", "1").strip() != "0"
    )


class _SigAccumulator:
    """Running Σ sig_i over a cell's vote list (ISSUE 9): one G1 add per
    arriving vote, so the aggregate signature already exists when quorum
    lands — O(1) marginal work per vote instead of an O(n) sum at QC
    formation.

    The sum runs on DEVICE (``tpu.bls.TpuG1RunningSum``, one fixed-shape
    ``point_add`` dispatch per vote) when an accelerator backend is live
    or HOTSTUFF_AGG_DEVICE_SUM=1 forces it; otherwise an incremental
    host Jacobian add.  Per-signature decompress skips the r-torsion
    ladder — the emitted aggregate is subgroup-checked by every verifier
    (the same soundness argument as ``BlsVerifier.verify_shared_msg``).

    ``count`` mirrors the number of accumulated signatures; the owning
    cell compares it against its vote list to detect evict/replace
    divergence and rebuilds from the surviving votes (rare, adversarial
    path)."""

    def __init__(self):
        self.count = 0
        self._device = None
        self._host = None
        if "jax" in sys.modules and self._want_device():
            try:
                from ..tpu.bls import TpuG1RunningSum

                self._device = TpuG1RunningSum()
            except Exception:  # noqa: BLE001 — device absence is non-fatal
                self._device = None
        if self._device is None:
            from ..crypto.bls.curve import G1Point

            self._host = G1Point.identity()

    @staticmethod
    def _want_device() -> bool:
        env = os.environ.get("HOTSTUFF_AGG_DEVICE_SUM", "").strip().lower()
        if env:
            return env not in ("0", "off", "no", "false")
        try:
            import jax

            return jax.default_backend() in ("tpu", "gpu")
        except Exception:  # noqa: BLE001
            return False

    def add(self, sig: Signature) -> bool:
        """Accumulate one signature; False when it doesn't decompress
        (a spoofed blob — the cell falls back to rebuild-at-quorum)."""
        from ..crypto.bls.curve import G1Point

        pt = G1Point.from_bytes(sig.to_bytes(), subgroup_check=False)
        if pt is None:
            return False
        if self._device is not None:
            self._device.add(pt)
        else:
            self._host = self._host + pt
        self.count += 1
        return True

    def aggregate(self) -> bytes | None:
        """The compressed 48-byte aggregate, or None for the empty sum."""
        pt = (
            self._device.snapshot()
            if self._device is not None
            else self._host
        )
        if pt.inf:
            return None
        return pt.to_bytes()


class AggregationBounds(ConsensusError):
    def __init__(self, what: str):
        super().__init__(f"Rejected {what}: aggregation bounds exceeded")


class QCMaker:
    """Accumulates votes over one (round, block-digest) cell into a QC."""

    def __init__(self):
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature]] = []
        self.used: set[PublicKey] = set()
        self.suspect: set[PublicKey] = set()  # authors with an evicted sig
        # owning Aggregator (set at cell admission) — rejected-signature
        # accounting rolls up there so it survives round cleanup
        self.owner: "Aggregator | None" = None
        # True once the cell holds at least one signature that passed
        # verification.  Cells that never earn this are evictable when the
        # per-round digest-cell budget fills up (ADVICE r1: otherwise 8
        # spoofed votes with random digests suppress honest votes for the
        # real block all round).
        self.verified = False
        # Protected cells (the digest this node itself voted for) are
        # never evicted.
        self.protected = False
        # Entries whose signature was NOT individually pre-verified on
        # entry (async-preverify path, core._preverify_burst).  When
        # empty at quorum, the batch dispatch is skipped — every
        # signature in the certificate already passed.
        self.unverified: set[PublicKey] = set()
        # Running Σ sig for compact-QC emission (BLS committees only;
        # built lazily on the first vote).  None when the committee
        # scheme has no aggregate form or compact emission is off.
        self._acc: _SigAccumulator | None = None

    def append(
        self,
        vote: Vote,
        committee: Committee,
        verifier: VerifierBackend,
        stake: int | None = None,
        sig_verified: bool = False,
    ) -> QC | None:
        author = vote.author
        if author in self.used:
            # A second vote naming an already-counted author. Since votes
            # are unauthenticated on entry, the FIRST one may have been an
            # attacker's spoof racing the honest vote — if this one carries
            # a different, eagerly-verified-valid signature and the stored
            # one is invalid, swap it in (weight is unchanged: the author
            # was already counted). Without the swap, whichever message
            # wins the race would decide whether the honest vote ever
            # counts (vote-suppression attack).
            self._maybe_replace(vote, verifier, incoming_verified=sig_verified)
            raise AuthorityReuse(author)
        if stake is None:
            stake = committee.stake(author)
        if stake <= 0:
            raise UnknownAuthority(author)
        if sig_verified:
            self.verified = True
        elif author in self.suspect:
            # this author's slot was already poisoned once — pay one eager
            # verify instead of trusting the deferred batch again
            if not verifier.verify_one(vote.digest(), author, vote.signature):
                if self.owner is not None:
                    self.owner.qc_rejects += 1
                raise InvalidSignature(f"bad signature on vote {vote!r}")
            self.verified = True
        else:
            self.unverified.add(author)
        self.used.add(author)
        self.votes.append((author, vote.signature))
        if _compact_enabled(committee):
            # O(1) marginal work per vote: the aggregate signature is
            # ready the moment quorum lands (ISSUE 9)
            if self._acc is None:
                self._acc = _SigAccumulator()
            self._acc.add(vote.signature)  # failure -> count diverges,
            # _compact_qc rebuilds from the (verified) survivors
        self.weight += stake
        if self.weight < committee.quorum_threshold():
            return None

        # Quorum reached: dispatch the whole set as one batch — unless
        # every entry was already individually pre-verified (the async
        # preverify path), in which case the certificate is proven.
        if self.unverified and not verifier.verify_shared_msg(
            vote.digest(), self.votes
        ):
            self._evict_invalid(vote.digest(), committee, verifier)
            if self.weight < committee.quorum_threshold():
                return None  # keep accumulating

        self.verified = True
        self.weight = 0  # a QC is made at most once
        if _compact_enabled(committee):
            qc = self._compact_qc(vote, committee)
            if qc is not None:
                return qc
        return QC(hash=vote.hash, round=vote.round, votes=list(self.votes))

    def _compact_qc(self, vote: Vote, committee: Committee) -> QC | None:
        """Emit the constant-size form: one aggregate signature + signer
        bitmap.  None (vote-list fallback) when the signer set doesn't
        map onto the committee bitmap or no aggregate can be formed —
        correctness never depends on the compact path."""
        try:
            bitmap = make_signer_bitmap(
                [pk for pk, _ in self.votes], committee.sorted_keys()
            )
        except (UnknownAuthority, ValueError):
            return None
        if self._acc is None or self._acc.count != len(self.votes):
            # evict/replace (or a non-decompressing spoof) diverged the
            # running sum from the vote list: rebuild from the survivors
            # — all of them just passed verification
            acc = _SigAccumulator()
            if not all(acc.add(sig) for _, sig in self.votes):
                return None
            self._acc = acc
        agg = self._acc.aggregate()
        if agg is None:
            return None
        if self.owner is not None:
            self.owner.compact_qcs += 1
        return QC(
            hash=vote.hash,
            round=vote.round,
            votes=[],
            agg_sig=Signature(agg),
            signers=bitmap,
        )

    def check_any_valid(self, digest: Digest, verifier: VerifierBackend) -> bool:
        """Verify the stored signatures against the cell's vote digest;
        mark the cell verified (and report True) if any is genuine."""
        if not self.votes:
            return False
        ok = verifier.verify_many(
            [digest.to_bytes()] * len(self.votes),
            [pk.to_bytes() for pk, _ in self.votes],
            [sig.to_bytes() for _, sig in self.votes],
        )
        if any(ok):
            self.verified = True
            return True
        return False

    def _maybe_replace(
        self, vote: Vote, verifier: VerifierBackend,
        incoming_verified: bool = False,
    ) -> None:
        for i, (pk, sig) in enumerate(self.votes):
            if pk != vote.author:
                continue
            if sig == vote.signature:
                return  # true duplicate
            if (
                incoming_verified
                or verifier.verify_one(vote.digest(), vote.author, vote.signature)
            ) and not verifier.verify_one(vote.digest(), pk, sig):
                log.warning(
                    "Replacing spoofed vote signature naming %s with the "
                    "authenticated one",
                    pk,
                )
                self.votes[i] = (vote.author, vote.signature)
                self.unverified.discard(pk)
                self._acc = None  # running sum diverged; rebuilt on emit
            return

    def _evict_invalid(
        self, digest: Digest, committee: Committee, verifier: VerifierBackend
    ) -> None:
        ok = verifier.verify_many(
            [digest.to_bytes()] * len(self.votes),
            [pk.to_bytes() for pk, _ in self.votes],
            [sig.to_bytes() for _, sig in self.votes],
        )
        for (pk, _), valid in zip(self.votes, ok):
            if not valid:
                log.warning("Evicting invalid vote signature naming %s", pk)
                if self.owner is not None:
                    self.owner.qc_rejects += 1
                # release the author — the signature was never authenticated,
                # so this may be a spoof and the real vote must still count —
                # but demand eager verification from now on
                self.used.discard(pk)
                self.suspect.add(pk)
        self.votes = [v for v, valid in zip(self.votes, ok) if valid]
        self._acc = None  # running sum diverged; rebuilt on emit
        # every survivor just passed a per-signature check
        self.unverified.clear()
        self.weight = sum(committee.stake(pk) for pk, _ in self.votes)
        if self.votes:
            self.verified = True  # survivors passed per-signature checks


class TCMaker:
    """Accumulates timeouts for one round into a TC.

    Entries are verified by the core before they reach this accumulator
    (core._handle_timeout, mirroring reference core.rs:288), so the TC is
    emitted without re-verification — same shape as the reference's
    TCMaker (aggregator.rs:97-139).
    """

    def __init__(self):
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature, Round]] = []
        self.used: set[PublicKey] = set()
        self.owner: "Aggregator | None" = None

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        author = timeout.author
        if author in self.used:
            raise AuthorityReuse(author)
        stake = committee.stake(author)
        if stake <= 0:
            raise UnknownAuthority(author)
        self.used.add(author)
        self.votes.append((author, timeout.signature, timeout.high_qc.round))
        self.weight += stake
        if self.weight < committee.quorum_threshold():
            return None
        self.weight = 0  # a TC is made at most once
        if _compact_enabled(committee):
            tc = self._compact_tc(timeout.round, committee)
            if tc is not None:
                return tc
        return TC(round=timeout.round, votes=list(self.votes))

    def _compact_tc(self, round_: Round, committee: Committee) -> TC | None:
        """Compact TC: one (agg sig, signer bitmap) per distinct high-QC
        round.  Honest storms collapse to one or two groups, so the wire
        form is ~groups x (48 + bitmap) bytes instead of n x 144.
        Entries here were verified on entry by the core, so the host
        aggregation is over genuine signatures.  Vote-list fallback on
        any mapping/decompress failure, as with the QC path."""
        from ..crypto.bls.curve import G1Point

        ordered = committee.sorted_keys()
        by_hq: dict[Round, list[tuple[PublicKey, Signature]]] = {}
        for pk, sig, hq in self.votes:
            by_hq.setdefault(hq, []).append((pk, sig))
        groups: list[tuple[Round, Signature, bytes]] = []
        for hq in sorted(by_hq):
            members = by_hq[hq]
            try:
                bitmap = make_signer_bitmap(
                    [pk for pk, _ in members], ordered
                )
            except (UnknownAuthority, ValueError):
                return None
            pts = []
            for _, sig in members:
                pt = G1Point.from_bytes(sig.to_bytes(), subgroup_check=False)
                if pt is None:
                    return None
                pts.append(pt)
            agg = G1Point.sum(pts)
            if agg.inf:
                return None
            groups.append((hq, Signature(agg.to_bytes()), bitmap))
        if self.owner is not None:
            self.owner.compact_tcs += 1
        return TC(round=round_, votes=[], groups=groups)


class Aggregator:
    """Per-round certificate accumulators with cleanup and DoS bounds.

    ``self_key`` (the node's own public key) powers the liveness
    guarantee: QC formation only ever matters for the block this node
    itself voted for (voters address votes to the next leader, and the
    leader votes for its own proposal), so the digest cell matching a
    self-authored vote is admitted unconditionally — evicting a
    non-protected cell at the cap — and can never be evicted itself.
    """

    def __init__(
        self,
        committee: Committee,
        verifier: VerifierBackend,
        self_key: PublicKey | None = None,
    ):
        self.committee = committee
        self.verifier = verifier
        self.self_key = self_key
        self.votes_aggregators: dict[Round, dict[Digest, QCMaker]] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}
        # Authors whose valid signature already paid for an extra digest
        # cell this round: a second paid cell from the same author is
        # proof of equivocation and is refused (one Byzantine member must
        # not consume the whole cell budget with validly-signed votes for
        # random digests).
        self.cell_payers: dict[Round, set[PublicKey]] = {}
        # Verified votes that found the cell budget exhausted before this
        # node's own (protected) cell existed — replayed into the
        # protected cell when it is admitted, so a coalition racing its
        # equivocations ahead of the real proposal can't permanently drop
        # honest votes.  Bounded: one vote per author per round.
        self.parked: dict[Round, dict[PublicKey, Vote]] = {}
        # Cumulative accounting (plain ints, always on — telemetry reads
        # them through Core's snapshot section when enabled).
        self.cells_evicted = 0
        self.votes_parked = 0
        # Honest-side Byzantine defense counters: signatures rejected in
        # certificate verification (vote evictions, suspect-path
        # rejects, and invalid timeout certificates counted by the
        # core) and equivocation evidence (a second paid digest cell
        # from one author — conflicting validly-signed votes).
        self.qc_rejects = 0
        self.vote_conflicts = 0
        # Compact-certificate accounting (ISSUE 9): certificates emitted
        # in the aggregated form, and the wire size of the most recent
        # QC (compact or vote-list — the scaling SUMMARY's qc_bytes
        # column reads this to show the O(1)-vs-O(n) gap).
        self.compact_qcs = 0
        self.compact_tcs = 0
        self.qc_wire_bytes = 0

    def add_vote(
        self,
        vote: Vote,
        current_round: Round | None = None,
        sig_verified: bool = False,
    ) -> QC | None:
        """``sig_verified=True``: the vote's signature was individually
        pre-verified (async burst preverify or a self-signed vote) — the
        cell skips deferred-batch bookkeeping for it and, when every
        entry arrived pre-verified, emits the QC without a quorum batch."""
        if (
            current_round is not None
            and vote.round > current_round + ROUND_LOOKAHEAD
        ):
            raise AggregationBounds(f"vote for far-future round {vote.round}")
        # Authority check before any aggregation state is created, so
        # UnknownAuthority rejections cannot leave empty cells behind.
        # Epoch seam: stake/quorum come from the VOTE round's committee.
        com = self.committee.for_round(vote.round)
        stake = com.stake(vote.author)
        if stake <= 0:
            raise UnknownAuthority(vote.author)
        makers = self.votes_aggregators.setdefault(vote.round, {})
        digest = vote.digest()
        maker = makers.get(digest)
        created = maker is None
        if created:
            maker = self._admit_cell(
                vote, digest, makers, sig_verified=sig_verified
            )
        qc = maker.append(
            vote, com, self.verifier, stake=stake, sig_verified=sig_verified
        )
        if created and maker.protected:
            qc = self._replay_parked(vote.round, digest, maker) or qc
        if qc is not None:
            self.qc_wire_bytes = qc.wire_size()
        return qc

    def _park(self, vote: Vote) -> None:
        """Remember a verified-but-unplaceable vote (one per author/round)."""
        self.parked.setdefault(vote.round, {}).setdefault(vote.author, vote)
        self.votes_parked += 1

    def _replay_parked(
        self, round_: Round, digest: Digest, maker: QCMaker
    ) -> QC | None:
        """Feed parked votes matching the protected cell's digest back in."""
        parked = self.parked.get(round_)
        if not parked:
            return None
        qc = None
        for author in [a for a, v in parked.items() if v.digest() == digest]:
            vote = parked.pop(author)
            try:
                got = maker.append(
                    vote, self.committee.for_round(round_), self.verifier
                )
            except ConsensusError:
                continue
            qc = got or qc
        return qc

    def _admit_cell(
        self,
        vote: Vote,
        digest: Digest,
        makers: dict[Digest, QCMaker],
        sig_verified: bool = False,
    ) -> QCMaker:
        """Create a new digest cell, charging for it when it isn't the first.

        The honest case is exactly one digest per round, so every
        ADDITIONAL cell must be paid for with a valid signature — spoofed
        votes carrying random digests cost the attacker a rejected verify
        instead of a slot in the cell budget (per-round vote-suppression
        DoS otherwise: 8 garbage digests would exhaust MAX_DIGEST_CELLS
        and honest votes for the real block would bounce).  Each author
        may pay for at most one cell per round (a second one is proof of
        equivocation), and a self-authored vote's cell is admitted
        unconditionally and marked protected (see class docstring).
        """
        own = self.self_key is not None and vote.author == self.self_key
        verified = False
        if makers and not own:
            if not sig_verified and not self.verifier.verify_one(
                digest, vote.author, vote.signature
            ):
                raise InvalidSignature(f"bad signature on vote {vote!r}")
            payers = self.cell_payers.setdefault(vote.round, set())
            if vote.author in payers:
                # One paid cell per author per round.  The vote itself is
                # genuine though — votes may legitimately join an
                # EXISTING cell regardless of the author's history — so
                # park it for replay in case its digest gets the
                # protected cell later.  Two validly-signed conflicting
                # votes from one author = equivocation evidence.
                self.vote_conflicts += 1
                self._park(vote)
                raise AggregationBounds(
                    f"second digest cell paid by {vote.author} in round "
                    f"{vote.round} (vote parked)"
                )
            if any(
                vote.author in m.used
                for d, m in makers.items()
                if d != digest
            ):
                # The payment signature verified AND another cell already
                # counts this author for a different digest this round:
                # equivocation evidence (a double-voter's second digest).
                # Accounting only — the paid cell is still admitted, the
                # protocol math is untouched.
                self.vote_conflicts += 1
                log.info(
                    "second digest cell paid by %s in round %d "
                    "(conflicting double-vote evidence)",
                    vote.author,
                    vote.round,
                )
            verified = True
        if len(makers) >= MAX_DIGEST_CELLS and not self._evict_for(
            vote, makers, own
        ):
            # Verified vote, but the budget is full of verified cells and
            # this node's own (protected) cell doesn't exist yet: PARK it
            # for replay when the protected cell lands — a coalition
            # racing equivocations ahead of the real proposal must not
            # permanently drop honest votes.
            self._park(vote)
            raise AggregationBounds(
                f"vote digest cell #{len(makers)} in round {vote.round} "
                f"(vote parked)"
            )
        if verified:
            # charge the payer only once the cell actually exists
            self.cell_payers.setdefault(vote.round, set()).add(vote.author)
        maker = makers[digest] = QCMaker()
        maker.owner = self
        maker.verified = verified or own
        maker.protected = own
        return maker

    def _evict_for(
        self, vote: Vote, makers: dict[Digest, QCMaker], own: bool
    ) -> bool:
        """Make room at the cell cap; False if no cell may be evicted.

        A cell is only evictable if NONE of its stored signatures verify —
        an unverified cell may be the honest block's cell whose batch check
        is simply deferred until quorum, and evicting it would destroy
        accumulated honest votes (per-round liveness loss a Byzantine
        insider could trigger at will).  Checking promotes genuinely
        honest cells to verified, so each cell pays the check at most
        once.  For a SELF-authored vote the cell must be admitted even if
        every other cell is verified: all other cells are by definition
        not this node's block, so evict any non-protected one.
        """
        victim = None
        for d, m in makers.items():
            if m.protected:
                continue
            if not m.verified and not m.check_any_valid(d, self.verifier):
                victim = d
                break
        if victim is None and own:
            victim = next(
                (d for d, m in makers.items() if not m.protected), None
            )
        if victim is None:
            return False
        log.warning("Evicting digest cell to admit %s",
                    "own-vote cell" if own else "a verified one")
        del makers[victim]
        self.cells_evicted += 1
        return True

    def add_timeout(
        self, timeout: Timeout, current_round: Round | None = None
    ) -> TC | None:
        if (
            current_round is not None
            and timeout.round > current_round + ROUND_LOOKAHEAD
        ):
            raise AggregationBounds(
                f"timeout for far-future round {timeout.round}"
            )
        maker = self.timeouts_aggregators.get(timeout.round)
        if maker is None:
            maker = self.timeouts_aggregators[timeout.round] = TCMaker()
            maker.owner = self
        return maker.append(
            timeout, self.committee.for_round(timeout.round)
        )

    def timeout_weight(self, round_: Round) -> int:
        """Stake currently accumulated toward a TC for ``round_`` (0 once
        the TC was emitted, or if no timeout arrived).  The core's
        round-sync rule reads this to join a round the rest of the
        committee is provably timing out."""
        maker = self.timeouts_aggregators.get(round_)
        return maker.weight if maker is not None else 0

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            r: v for r, v in self.votes_aggregators.items() if r >= round_
        }
        self.timeouts_aggregators = {
            r: v for r, v in self.timeouts_aggregators.items() if r >= round_
        }
        self.cell_payers = {
            r: v for r, v in self.cell_payers.items() if r >= round_
        }
        self.parked = {r: v for r, v in self.parked.items() if r >= round_}

    def stats(self) -> dict:
        """Snapshot of aggregation pressure (telemetry pull section)."""
        return {
            "vote_rounds": len(self.votes_aggregators),
            "vote_cells": sum(
                len(m) for m in self.votes_aggregators.values()
            ),
            "pending_votes": sum(
                len(maker.votes)
                for makers in self.votes_aggregators.values()
                for maker in makers.values()
            ),
            "timeout_rounds": len(self.timeouts_aggregators),
            "parked_votes": sum(len(p) for p in self.parked.values()),
            "votes_parked_total": self.votes_parked,
            "cells_evicted_total": self.cells_evicted,
            "qc_rejects_total": self.qc_rejects,
            "vote_conflicts_total": self.vote_conflicts,
            "compact_qcs_total": self.compact_qcs,
            "compact_tcs_total": self.compact_tcs,
            "qc_wire_bytes": self.qc_wire_bytes,
        }
