"""Vote/timeout aggregation into certificates — accumulate-then-dispatch.

Parity target: reference ``Aggregator``/``QCMaker``/``TCMaker``
(consensus/src/aggregator.rs:13-139), restructured per the BASELINE.json
north star: votes are accumulated *unverified* and the whole signature set
ships to the ``VerifierBackend`` as ONE batch when a quorum's stake has
arrived — one batched kernel call per certificate instead of 2f+1
sequential verifies on the hot path.

Hardening beyond the reference (messages arrive over unauthenticated TCP,
so deferred verification must not open spoofing holes):

- If the batch fails at quorum, invalid entries are identified
  per-signature and evicted, their authors are *released* (so the honest
  authority's real vote can still land — a spoofed garbage vote cannot
  suppress it) and marked suspect: subsequent votes naming a suspect
  author are verified eagerly on entry, so garbage floods cost the
  attacker a rejected verify instead of aggregator state.
- Aggregation state is bounded: votes/timeouts further than
  ``ROUND_LOOKAHEAD`` past the node's current round are rejected, and at
  most ``MAX_DIGEST_CELLS`` distinct block digests are tracked per round
  (the reference's unbounded maps are a known DoS, aggregator.rs:29-30).

Timeouts are verified on entry by the core (like the reference,
core.rs:288), so ``TCMaker`` accumulates pre-verified entries and emits
the TC without re-verification.
"""

from __future__ import annotations

import logging

from ..crypto import Digest, PublicKey, Signature
from ..crypto.service import VerifierBackend
from .config import Committee
from .errors import AuthorityReuse, ConsensusError, InvalidSignature, UnknownAuthority
from .messages import QC, TC, Round, Timeout, Vote

log = logging.getLogger(__name__)

# How far past the current round aggregation state may be created.
ROUND_LOOKAHEAD = 64
# Distinct block digests tracked per round (honest case: exactly one).
MAX_DIGEST_CELLS = 8


class AggregationBounds(ConsensusError):
    def __init__(self, what: str):
        super().__init__(f"Rejected {what}: aggregation bounds exceeded")


class QCMaker:
    """Accumulates votes over one (round, block-digest) cell into a QC."""

    def __init__(self):
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature]] = []
        self.used: set[PublicKey] = set()
        self.suspect: set[PublicKey] = set()  # authors with an evicted sig

    def append(
        self,
        vote: Vote,
        committee: Committee,
        verifier: VerifierBackend,
    ) -> QC | None:
        author = vote.author
        if author in self.used:
            # A second vote naming an already-counted author. Since votes
            # are unauthenticated on entry, the FIRST one may have been an
            # attacker's spoof racing the honest vote — if this one carries
            # a different, eagerly-verified-valid signature and the stored
            # one is invalid, swap it in (weight is unchanged: the author
            # was already counted). Without the swap, whichever message
            # wins the race would decide whether the honest vote ever
            # counts (vote-suppression attack).
            self._maybe_replace(vote, verifier)
            raise AuthorityReuse(author)
        stake = committee.stake(author)
        if stake <= 0:
            raise UnknownAuthority(author)
        if author in self.suspect:
            # this author's slot was already poisoned once — pay one eager
            # verify instead of trusting the deferred batch again
            if not verifier.verify_one(vote.digest(), author, vote.signature):
                raise InvalidSignature(f"bad signature on vote {vote!r}")
        self.used.add(author)
        self.votes.append((author, vote.signature))
        self.weight += stake
        if self.weight < committee.quorum_threshold():
            return None

        # Quorum reached: dispatch the whole set as one batch.
        if not verifier.verify_shared_msg(vote.digest(), self.votes):
            self._evict_invalid(vote.digest(), committee, verifier)
            if self.weight < committee.quorum_threshold():
                return None  # keep accumulating

        self.weight = 0  # a QC is made at most once
        return QC(hash=vote.hash, round=vote.round, votes=list(self.votes))

    def _maybe_replace(self, vote: Vote, verifier: VerifierBackend) -> None:
        for i, (pk, sig) in enumerate(self.votes):
            if pk != vote.author:
                continue
            if sig == vote.signature:
                return  # true duplicate
            if verifier.verify_one(
                vote.digest(), vote.author, vote.signature
            ) and not verifier.verify_one(vote.digest(), pk, sig):
                log.warning(
                    "Replacing spoofed vote signature naming %s with the "
                    "authenticated one",
                    pk,
                )
                self.votes[i] = (vote.author, vote.signature)
            return

    def _evict_invalid(
        self, digest: Digest, committee: Committee, verifier: VerifierBackend
    ) -> None:
        ok = verifier.verify_many(
            [digest.to_bytes()] * len(self.votes),
            [pk.to_bytes() for pk, _ in self.votes],
            [sig.to_bytes() for _, sig in self.votes],
        )
        for (pk, _), valid in zip(self.votes, ok):
            if not valid:
                log.warning("Evicting invalid vote signature naming %s", pk)
                # release the author — the signature was never authenticated,
                # so this may be a spoof and the real vote must still count —
                # but demand eager verification from now on
                self.used.discard(pk)
                self.suspect.add(pk)
        self.votes = [v for v, valid in zip(self.votes, ok) if valid]
        self.weight = sum(committee.stake(pk) for pk, _ in self.votes)


class TCMaker:
    """Accumulates timeouts for one round into a TC.

    Entries are verified by the core before they reach this accumulator
    (core._handle_timeout, mirroring reference core.rs:288), so the TC is
    emitted without re-verification — same shape as the reference's
    TCMaker (aggregator.rs:97-139).
    """

    def __init__(self):
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature, Round]] = []
        self.used: set[PublicKey] = set()

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        author = timeout.author
        if author in self.used:
            raise AuthorityReuse(author)
        stake = committee.stake(author)
        if stake <= 0:
            raise UnknownAuthority(author)
        self.used.add(author)
        self.votes.append((author, timeout.signature, timeout.high_qc.round))
        self.weight += stake
        if self.weight < committee.quorum_threshold():
            return None
        self.weight = 0  # a TC is made at most once
        return TC(round=timeout.round, votes=list(self.votes))


class Aggregator:
    """Per-round certificate accumulators with cleanup and DoS bounds."""

    def __init__(self, committee: Committee, verifier: VerifierBackend):
        self.committee = committee
        self.verifier = verifier
        self.votes_aggregators: dict[Round, dict[Digest, QCMaker]] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}

    def add_vote(self, vote: Vote, current_round: Round | None = None) -> QC | None:
        if (
            current_round is not None
            and vote.round > current_round + ROUND_LOOKAHEAD
        ):
            raise AggregationBounds(f"vote for far-future round {vote.round}")
        makers = self.votes_aggregators.setdefault(vote.round, {})
        digest = vote.digest()
        if digest not in makers and len(makers) >= MAX_DIGEST_CELLS:
            raise AggregationBounds(
                f"vote digest cell #{len(makers)} in round {vote.round}"
            )
        maker = makers.setdefault(digest, QCMaker())
        return maker.append(vote, self.committee, self.verifier)

    def add_timeout(
        self, timeout: Timeout, current_round: Round | None = None
    ) -> TC | None:
        if (
            current_round is not None
            and timeout.round > current_round + ROUND_LOOKAHEAD
        ):
            raise AggregationBounds(
                f"timeout for far-future round {timeout.round}"
            )
        maker = self.timeouts_aggregators.setdefault(timeout.round, TCMaker())
        return maker.append(timeout, self.committee)

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            r: v for r, v in self.votes_aggregators.items() if r >= round_
        }
        self.timeouts_aggregators = {
            r: v for r, v in self.timeouts_aggregators.items() if r >= round_
        }
