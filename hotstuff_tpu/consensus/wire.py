"""The consensus wire protocol: the tagged message union.

Parity target: ``ConsensusMessage`` (reference consensus/src/consensus.rs:
30-38): Propose(Block), Vote, Timeout, TC, SyncRequest(digest, origin),
Producer(digest) — the fork's payload-ingest message.
"""

from __future__ import annotations

from ..crypto import Digest, PublicKey
from ..utils.codec import CodecError, Decoder, Encoder
from .errors import SerializationError
from .messages import (
    MAX_SIGNER_BITMAP,
    TC,
    Block,
    Timeout,
    Vote,
    _vote_struct,
    decode_pk,
    encode_pk,
)

TAG_PROPOSE = 0
TAG_VOTE = 1
TAG_TIMEOUT = 2
TAG_TC = 3
TAG_SYNC_REQUEST = 4
TAG_PRODUCER = 5
TAG_PRODUCER_V2 = 6

ACK = b"Ack"

#: producer frame v2 (ingest plane, docs/LOAD.md): versioned batched
#: payload submission.  The version byte is explicit so a v3 layout can
#: change the body without a new tag; any other value is a CodecError.
PRODUCER_FRAME_VERSION = 2
#: payload items per v2 frame (wire sanity bound: a full batch of
#: maximum bodies stays well under framing.MAX_FRAME)
MAX_PRODUCER_BATCH = 512

# Committee-scheme wire sizes for key/signature fields: (pk, sig) bytes.
# One committee never mixes schemes, so the network decode path narrows
# the accepted sizes to its own scheme (ADVICE r2: don't rely on later
# stake/crypto checks to reject the other scheme's material).
SCHEME_WIRE_SIZES = {"ed25519": (32, 64), "bls": (96, 48)}

# Compact-certificate narrowing, same contract: (aggregate-sig size,
# signer-bitmap byte cap) per scheme, or None when the scheme has no
# aggregate form — then any compact certificate off the wire is a
# CodecError, not something later stake/crypto checks must catch.  Only
# BLS aggregates; the bitmap cap admits committees up to 4096 members
# (messages.MAX_SIGNER_BITMAP).
SCHEME_COMPACT_SIZES = {
    "ed25519": None,
    "bls": (48, MAX_SIGNER_BITMAP),
}


_PROPOSE_PREFIX = bytes([TAG_PROPOSE])


def encode_propose(block: Block) -> bytes:
    # serialize() is wire-cached on the block (messages.py), so the
    # helper/synchronizer re-sends and the store write share one
    # encoding with the original broadcast
    return _PROPOSE_PREFIX + block.serialize()


_VOTE_PREFIX = bytes([TAG_VOTE])


def encode_vote(vote: Vote) -> bytes:
    # packed fast path — identical bytes to Encoder + Vote.encode (the
    # struct layouts are shared with the decode fast path)
    pk = vote.author.data
    sig = vote.signature.data
    s = _vote_struct(len(pk), len(sig))
    return _VOTE_PREFIX + s.pack(
        vote.hash.data, vote.round, len(pk), pk, len(sig), sig
    )


def encode_timeout(timeout: Timeout) -> bytes:
    enc = Encoder().u8(TAG_TIMEOUT)
    timeout.encode(enc)
    return enc.finish()


def encode_tc(tc: TC) -> bytes:
    enc = Encoder().u8(TAG_TC)
    tc.encode(enc)
    return enc.finish()


def encode_sync_request(missing: Digest, origin: PublicKey) -> bytes:
    enc = Encoder().u8(TAG_SYNC_REQUEST).raw(missing.to_bytes())
    encode_pk(enc, origin)
    return enc.finish()


# Per-payload body cap (wire sanity bound; the reference's WAN config
# uses 512-byte transactions, data/2-chain/README.md:42-57).
MAX_PAYLOAD_BODY = 65_536


def encode_producer(payload: Digest, body: bytes = b"") -> bytes:
    """The fork's ingest message (consensus.rs:37), extended with an
    optional payload BODY: the reference's 512-byte transactions flow
    through its (deleted) mempool; here the producer may attach the
    body so nodes store real bytes and the harness measures BPS
    (VERDICT r3 item 4).  An empty body preserves the digest-only
    producer contract (dissemination stays the producer's job, as in
    the reference fork)."""
    enc = Encoder().u8(TAG_PRODUCER).raw(payload.to_bytes())
    enc.var_bytes(body)
    return enc.finish()


def encode_producer_batch(items) -> bytes:
    """Producer frame v2: ``items`` is a sequence of (Digest, body)
    pairs submitted in one frame.  Batching amortizes the per-frame
    syscall/decode cost for high-rate clients; the ingest ACK the node
    replies with carries the admission decision for the whole batch
    (accepted prefix / shed suffix — the decode side preserves order)."""
    if not items or len(items) > MAX_PRODUCER_BATCH:
        raise ValueError(
            f"producer batch must carry 1..{MAX_PRODUCER_BATCH} items"
        )
    enc = Encoder().u8(TAG_PRODUCER_V2).u8(PRODUCER_FRAME_VERSION)
    enc.u32(len(items))
    for digest, body in items:
        enc.raw(digest.to_bytes())
        enc.var_bytes(body)
    return enc.finish()


# ---- ingest ACK (the reply frame on the producer socket) -------------------

#: first byte of an ingest ACK — disjoint from the legacy ``b"Ack"``
#: (0x41) so a reply frame's kind is decidable from one byte
INGEST_ACK_TAG = 0xA2
INGEST_OK = 0
INGEST_BUSY = 1


class IngestAck:
    """Typed producer ACK: the admission decision for one frame.

    ``status`` is INGEST_BUSY when anything was shed; ``credit`` is the
    node's current credit window (payloads the client may have in
    flight before the next ACK); ``retry_after_ms`` is the node's
    drain-rate-derived pause hint (0 unless busy)."""

    __slots__ = ("status", "accepted", "shed", "credit", "retry_after_ms")

    def __init__(self, status, accepted, shed, credit, retry_after_ms):
        self.status = status
        self.accepted = accepted
        self.shed = shed
        self.credit = credit
        self.retry_after_ms = retry_after_ms

    @property
    def busy(self) -> bool:
        return self.status == INGEST_BUSY


def encode_ingest_ack(
    accepted: int, shed: int, credit: int, retry_after_ms: int
) -> bytes:
    status = INGEST_BUSY if shed else INGEST_OK
    u32max = (1 << 32) - 1
    return (
        Encoder()
        .u8(INGEST_ACK_TAG)
        .u8(PRODUCER_FRAME_VERSION)
        .u8(status)
        .u32(min(u32max, max(0, accepted)))
        .u32(min(u32max, max(0, shed)))
        .u32(min(u32max, max(0, credit)))
        .u32(min(u32max, max(0, retry_after_ms)))
        .finish()
    )


def decode_ingest_ack(data: bytes) -> IngestAck | None:
    """Reply-frame decode for producer clients: None for the legacy
    ``b"Ack"`` (or any frame that isn't an ingest ACK), the typed ACK
    otherwise.  Raises SerializationError on a malformed ingest ACK."""
    if not data or data[0] != INGEST_ACK_TAG:
        return None
    try:
        dec = Decoder(data)
        dec.u8()
        version = dec.u8()
        if version != PRODUCER_FRAME_VERSION:
            raise CodecError(f"unknown ingest ACK version {version}")
        status = dec.u8()
        if status not in (INGEST_OK, INGEST_BUSY):
            raise CodecError(f"invalid ingest ACK status {status}")
        ack = IngestAck(status, dec.u32(), dec.u32(), dec.u32(), dec.u32())
        dec.finish()
        return ack
    except CodecError as e:
        raise SerializationError(str(e)) from e


def decode_message(data: bytes, scheme: str | None = None):
    """bytes -> (tag, payload). Raises SerializationError on malformed input.

    Payload by tag: Propose -> Block, Vote -> Vote, Timeout -> Timeout,
    TC -> TC, SyncRequest -> (Digest, PublicKey), Producer ->
    (Digest, body), ProducerV2 -> tuple of (Digest, body) pairs.

    ``scheme`` (the committee's signature scheme) narrows accepted
    key/signature wire sizes to that scheme's; None accepts the union.
    An unknown scheme is a caller bug — raised as ValueError at once,
    never per-message from inside the codec error path.
    """
    sizes = None
    if scheme is not None:
        sizes = SCHEME_WIRE_SIZES.get(scheme)
        if sizes is None:
            raise ValueError(f"unknown committee scheme '{scheme}'")
    try:
        dec = Decoder(data)
        if sizes is not None:
            dec.pk_size, dec.sig_size = sizes
            compact = SCHEME_COMPACT_SIZES.get(scheme)
            if compact is None:
                dec.compact_sig_size = 0  # scheme has no compact form
            else:
                dec.compact_sig_size, dec.compact_bitmap_max = compact
        tag = dec.u8()
        if tag == TAG_PROPOSE:
            out = Block.decode(dec)
        elif tag == TAG_VOTE:
            out = Vote.decode(dec)
        elif tag == TAG_TIMEOUT:
            out = Timeout.decode(dec)
        elif tag == TAG_TC:
            out = TC.decode(dec)
        elif tag == TAG_SYNC_REQUEST:
            out = (Digest(dec.raw(Digest.SIZE)), decode_pk(dec))
        elif tag == TAG_PRODUCER:
            out = (Digest(dec.raw(Digest.SIZE)), dec.var_bytes(MAX_PAYLOAD_BODY))
        elif tag == TAG_PRODUCER_V2:
            version = dec.u8()
            if version != PRODUCER_FRAME_VERSION:
                raise CodecError(f"unknown producer frame version {version}")
            count = dec.u32()
            if not 1 <= count <= MAX_PRODUCER_BATCH:
                raise CodecError(
                    f"producer batch count {count} outside "
                    f"1..{MAX_PRODUCER_BATCH}"
                )
            out = tuple(
                (Digest(dec.raw(Digest.SIZE)), dec.var_bytes(MAX_PAYLOAD_BODY))
                for _ in range(count)
            )
        else:
            raise CodecError(f"unknown message tag {tag}")
        dec.finish()
        return tag, out
    except CodecError as e:
        raise SerializationError(str(e)) from e
