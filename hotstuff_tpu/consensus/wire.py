"""The consensus wire protocol: the tagged message union.

Parity target: ``ConsensusMessage`` (reference consensus/src/consensus.rs:
30-38): Propose(Block), Vote, Timeout, TC, SyncRequest(digest, origin),
Producer(digest) — the fork's payload-ingest message.
"""

from __future__ import annotations

from ..crypto import Digest, PublicKey
from ..utils.codec import CodecError, Decoder, Encoder
from .errors import SerializationError
from .messages import (
    MAX_SIGNER_BITMAP,
    TC,
    Block,
    Timeout,
    Vote,
    _vote_struct,
    decode_pk,
    encode_pk,
)

TAG_PROPOSE = 0
TAG_VOTE = 1
TAG_TIMEOUT = 2
TAG_TC = 3
TAG_SYNC_REQUEST = 4
TAG_PRODUCER = 5

ACK = b"Ack"

# Committee-scheme wire sizes for key/signature fields: (pk, sig) bytes.
# One committee never mixes schemes, so the network decode path narrows
# the accepted sizes to its own scheme (ADVICE r2: don't rely on later
# stake/crypto checks to reject the other scheme's material).
SCHEME_WIRE_SIZES = {"ed25519": (32, 64), "bls": (96, 48)}

# Compact-certificate narrowing, same contract: (aggregate-sig size,
# signer-bitmap byte cap) per scheme, or None when the scheme has no
# aggregate form — then any compact certificate off the wire is a
# CodecError, not something later stake/crypto checks must catch.  Only
# BLS aggregates; the bitmap cap admits committees up to 4096 members
# (messages.MAX_SIGNER_BITMAP).
SCHEME_COMPACT_SIZES = {
    "ed25519": None,
    "bls": (48, MAX_SIGNER_BITMAP),
}


_PROPOSE_PREFIX = bytes([TAG_PROPOSE])


def encode_propose(block: Block) -> bytes:
    # serialize() is wire-cached on the block (messages.py), so the
    # helper/synchronizer re-sends and the store write share one
    # encoding with the original broadcast
    return _PROPOSE_PREFIX + block.serialize()


_VOTE_PREFIX = bytes([TAG_VOTE])


def encode_vote(vote: Vote) -> bytes:
    # packed fast path — identical bytes to Encoder + Vote.encode (the
    # struct layouts are shared with the decode fast path)
    pk = vote.author.data
    sig = vote.signature.data
    s = _vote_struct(len(pk), len(sig))
    return _VOTE_PREFIX + s.pack(
        vote.hash.data, vote.round, len(pk), pk, len(sig), sig
    )


def encode_timeout(timeout: Timeout) -> bytes:
    enc = Encoder().u8(TAG_TIMEOUT)
    timeout.encode(enc)
    return enc.finish()


def encode_tc(tc: TC) -> bytes:
    enc = Encoder().u8(TAG_TC)
    tc.encode(enc)
    return enc.finish()


def encode_sync_request(missing: Digest, origin: PublicKey) -> bytes:
    enc = Encoder().u8(TAG_SYNC_REQUEST).raw(missing.to_bytes())
    encode_pk(enc, origin)
    return enc.finish()


# Per-payload body cap (wire sanity bound; the reference's WAN config
# uses 512-byte transactions, data/2-chain/README.md:42-57).
MAX_PAYLOAD_BODY = 65_536


def encode_producer(payload: Digest, body: bytes = b"") -> bytes:
    """The fork's ingest message (consensus.rs:37), extended with an
    optional payload BODY: the reference's 512-byte transactions flow
    through its (deleted) mempool; here the producer may attach the
    body so nodes store real bytes and the harness measures BPS
    (VERDICT r3 item 4).  An empty body preserves the digest-only
    producer contract (dissemination stays the producer's job, as in
    the reference fork)."""
    enc = Encoder().u8(TAG_PRODUCER).raw(payload.to_bytes())
    enc.var_bytes(body)
    return enc.finish()


def decode_message(data: bytes, scheme: str | None = None):
    """bytes -> (tag, payload). Raises SerializationError on malformed input.

    Payload by tag: Propose -> Block, Vote -> Vote, Timeout -> Timeout,
    TC -> TC, SyncRequest -> (Digest, PublicKey), Producer -> Digest.

    ``scheme`` (the committee's signature scheme) narrows accepted
    key/signature wire sizes to that scheme's; None accepts the union.
    An unknown scheme is a caller bug — raised as ValueError at once,
    never per-message from inside the codec error path.
    """
    sizes = None
    if scheme is not None:
        sizes = SCHEME_WIRE_SIZES.get(scheme)
        if sizes is None:
            raise ValueError(f"unknown committee scheme '{scheme}'")
    try:
        dec = Decoder(data)
        if sizes is not None:
            dec.pk_size, dec.sig_size = sizes
            compact = SCHEME_COMPACT_SIZES.get(scheme)
            if compact is None:
                dec.compact_sig_size = 0  # scheme has no compact form
            else:
                dec.compact_sig_size, dec.compact_bitmap_max = compact
        tag = dec.u8()
        if tag == TAG_PROPOSE:
            out = Block.decode(dec)
        elif tag == TAG_VOTE:
            out = Vote.decode(dec)
        elif tag == TAG_TIMEOUT:
            out = Timeout.decode(dec)
        elif tag == TAG_TC:
            out = TC.decode(dec)
        elif tag == TAG_SYNC_REQUEST:
            out = (Digest(dec.raw(Digest.SIZE)), decode_pk(dec))
        elif tag == TAG_PRODUCER:
            out = (Digest(dec.raw(Digest.SIZE)), dec.var_bytes(MAX_PAYLOAD_BODY))
        else:
            raise CodecError(f"unknown message tag {tag}")
        dec.finish()
        return tag, out
    except CodecError as e:
        raise SerializationError(str(e)) from e
