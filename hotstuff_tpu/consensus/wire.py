"""The consensus wire protocol: the tagged message union.

Parity target: ``ConsensusMessage`` (reference consensus/src/consensus.rs:
30-38): Propose(Block), Vote, Timeout, TC, SyncRequest(digest, origin),
Producer(digest) — the fork's payload-ingest message.
"""

from __future__ import annotations

from ..crypto import Digest, PublicKey
from ..utils.codec import CodecError, Decoder, Encoder
from .errors import SerializationError
from .messages import (
    MAX_SIGNER_BITMAP,
    QC,
    TC,
    Block,
    Timeout,
    Vote,
    _vote_struct,
    decode_pk,
    encode_pk,
)
from .reconfig import ReconfigOp

TAG_PROPOSE = 0
TAG_VOTE = 1
TAG_TIMEOUT = 2
TAG_TC = 3
TAG_SYNC_REQUEST = 4
TAG_PRODUCER = 5
TAG_PRODUCER_V2 = 6
TAG_STATE_REQUEST = 7
TAG_STATE_MANIFEST = 8
TAG_STATE_CHUNK = 9
TAG_STATE_READ = 10
TAG_RECONFIG = 11

ACK = b"Ack"

#: producer frame v2 (ingest plane, docs/LOAD.md): versioned batched
#: payload submission.  The version byte is explicit so a v3 layout can
#: change the body without a new tag; any other value is a CodecError.
PRODUCER_FRAME_VERSION = 2
#: payload items per v2 frame (wire sanity bound: a full batch of
#: maximum bodies stays well under framing.MAX_FRAME)
MAX_PRODUCER_BATCH = 512

# Committee-scheme wire sizes for key/signature fields: (pk, sig) bytes.
# One committee never mixes schemes, so the network decode path narrows
# the accepted sizes to its own scheme (ADVICE r2: don't rely on later
# stake/crypto checks to reject the other scheme's material).
SCHEME_WIRE_SIZES = {"ed25519": (32, 64), "bls": (96, 48)}

# Compact-certificate narrowing, same contract: (aggregate-sig size,
# signer-bitmap byte cap) per scheme, or None when the scheme has no
# aggregate form — then any compact certificate off the wire is a
# CodecError, not something later stake/crypto checks must catch.  Only
# BLS aggregates; the bitmap cap admits committees up to 4096 members
# (messages.MAX_SIGNER_BITMAP).
SCHEME_COMPACT_SIZES = {
    "ed25519": None,
    "bls": (48, MAX_SIGNER_BITMAP),
}


_PROPOSE_PREFIX = bytes([TAG_PROPOSE])


def encode_propose(block: Block) -> bytes:
    # serialize() is wire-cached on the block (messages.py), so the
    # helper/synchronizer re-sends and the store write share one
    # encoding with the original broadcast
    return _PROPOSE_PREFIX + block.serialize()


_VOTE_PREFIX = bytes([TAG_VOTE])


def encode_vote(vote: Vote) -> bytes:
    # packed fast path — identical bytes to Encoder + Vote.encode (the
    # struct layouts are shared with the decode fast path)
    pk = vote.author.data
    sig = vote.signature.data
    s = _vote_struct(len(pk), len(sig))
    return _VOTE_PREFIX + s.pack(
        vote.hash.data, vote.round, len(pk), pk, len(sig), sig
    )


def encode_timeout(timeout: Timeout) -> bytes:
    enc = Encoder().u8(TAG_TIMEOUT)
    timeout.encode(enc)
    return enc.finish()


def encode_tc(tc: TC) -> bytes:
    enc = Encoder().u8(TAG_TC)
    tc.encode(enc)
    return enc.finish()


def encode_sync_request(missing: Digest, origin: PublicKey) -> bytes:
    enc = Encoder().u8(TAG_SYNC_REQUEST).raw(missing.to_bytes())
    encode_pk(enc, origin)
    return enc.finish()


# Per-payload body cap (wire sanity bound; the reference's WAN config
# uses 512-byte transactions, data/2-chain/README.md:42-57).
MAX_PAYLOAD_BODY = 65_536


def encode_producer(payload: Digest, body: bytes = b"") -> bytes:
    """The fork's ingest message (consensus.rs:37), extended with an
    optional payload BODY: the reference's 512-byte transactions flow
    through its (deleted) mempool; here the producer may attach the
    body so nodes store real bytes and the harness measures BPS
    (VERDICT r3 item 4).  An empty body preserves the digest-only
    producer contract (dissemination stays the producer's job, as in
    the reference fork)."""
    enc = Encoder().u8(TAG_PRODUCER).raw(payload.to_bytes())
    enc.var_bytes(body)
    return enc.finish()


def encode_producer_batch(items) -> bytes:
    """Producer frame v2: ``items`` is a sequence of (Digest, body)
    pairs submitted in one frame.  Batching amortizes the per-frame
    syscall/decode cost for high-rate clients; the ingest ACK the node
    replies with carries the admission decision for the whole batch
    (accepted prefix / shed suffix — the decode side preserves order)."""
    if not items or len(items) > MAX_PRODUCER_BATCH:
        raise ValueError(
            f"producer batch must carry 1..{MAX_PRODUCER_BATCH} items"
        )
    enc = Encoder().u8(TAG_PRODUCER_V2).u8(PRODUCER_FRAME_VERSION)
    enc.u32(len(items))
    for digest, body in items:
        enc.raw(digest.to_bytes())
        enc.var_bytes(body)
    return enc.finish()


# ---- ingest ACK (the reply frame on the producer socket) -------------------

#: first byte of an ingest ACK — disjoint from the legacy ``b"Ack"``
#: (0x41) so a reply frame's kind is decidable from one byte
INGEST_ACK_TAG = 0xA2
INGEST_OK = 0
INGEST_BUSY = 1


class IngestAck:
    """Typed producer ACK: the admission decision for one frame.

    ``status`` is INGEST_BUSY when anything was shed; ``credit`` is the
    node's current credit window (payloads the client may have in
    flight before the next ACK); ``retry_after_ms`` is the node's
    drain-rate-derived pause hint (0 unless busy)."""

    __slots__ = ("status", "accepted", "shed", "credit", "retry_after_ms")

    def __init__(self, status, accepted, shed, credit, retry_after_ms):
        self.status = status
        self.accepted = accepted
        self.shed = shed
        self.credit = credit
        self.retry_after_ms = retry_after_ms

    @property
    def busy(self) -> bool:
        return self.status == INGEST_BUSY


def encode_ingest_ack(
    accepted: int, shed: int, credit: int, retry_after_ms: int
) -> bytes:
    status = INGEST_BUSY if shed else INGEST_OK
    u32max = (1 << 32) - 1
    return (
        Encoder()
        .u8(INGEST_ACK_TAG)
        .u8(PRODUCER_FRAME_VERSION)
        .u8(status)
        .u32(min(u32max, max(0, accepted)))
        .u32(min(u32max, max(0, shed)))
        .u32(min(u32max, max(0, credit)))
        .u32(min(u32max, max(0, retry_after_ms)))
        .finish()
    )


def decode_ingest_ack(data: bytes) -> IngestAck | None:
    """Reply-frame decode for producer clients: None for the legacy
    ``b"Ack"`` (or any frame that isn't an ingest ACK), the typed ACK
    otherwise.  Raises SerializationError on a malformed ingest ACK."""
    if not data or data[0] != INGEST_ACK_TAG:
        return None
    try:
        dec = Decoder(data)
        dec.u8()
        version = dec.u8()
        if version != PRODUCER_FRAME_VERSION:
            raise CodecError(f"unknown ingest ACK version {version}")
        status = dec.u8()
        if status not in (INGEST_OK, INGEST_BUSY):
            raise CodecError(f"invalid ingest ACK status {status}")
        ack = IngestAck(status, dec.u32(), dec.u32(), dec.u32(), dec.u32())
        dec.finish()
        return ack
    except CodecError as e:
        raise SerializationError(str(e)) from e


# ---- reconfiguration submission (docs/RECONFIG.md) -------------------------


def encode_reconfig(op: ReconfigOp) -> bytes:
    """Operator-facing submission frame: a sponsored ReconfigOp sent to
    any current member's consensus port.  The receiving node validates
    it (sponsor membership + signature, epoch succession, margin and
    continuity bounds) and buffers it for its next leader slot — the op
    only takes effect once 2-chain committed inside a block."""
    enc = Encoder().u8(TAG_RECONFIG)
    op.encode(enc)
    return enc.finish()


# ---- state-sync frames (docs/STATE.md) -------------------------------------

#: versioned like the producer v2 frame: the byte is explicit so a v2
#: snapshot layout can change the body without new tags; any other
#: value is a CodecError.  v2: the manifest carries the certified
#: committee-schedule links (one committed reconfig block + its QC per
#: epoch change) so a joiner can verify the schedule it never saw.
STATE_FRAME_VERSION = 2
#: decode-time cap on schedule links in one manifest (one per epoch
#: change since genesis — 32 epoch changes is far beyond any run)
MAX_SCHEDULE_LINKS = 32
#: decode-time cap on one serialized link element (a reconfig block or
#: its certifying QC; a 128-member committee plus a full certificate
#: stays well under this)
MAX_SCHEDULE_LINK_BYTES = 131_072
def encode_schedule_links(links) -> bytes:
    """Store form of the certified schedule-link list (core persists one
    ``(reconfig block bytes, certifying QC bytes)`` pair per committed
    epoch change; the state-sync server serves them in the manifest)."""
    enc = Encoder().u16(len(links))
    for block_bytes, qc_bytes in links:
        enc.var_bytes(block_bytes)
        enc.var_bytes(qc_bytes)
    return enc.finish()


def decode_schedule_links(data: bytes) -> list:
    dec = Decoder(data)
    n = dec.u16()
    if n > MAX_SCHEDULE_LINKS:
        raise CodecError(
            f"schedule link count {n} exceeds cap {MAX_SCHEDULE_LINKS}"
        )
    out = [
        (
            dec.var_bytes(MAX_SCHEDULE_LINK_BYTES),
            dec.var_bytes(MAX_SCHEDULE_LINK_BYTES),
        )
        for _ in range(n)
    ]
    dec.finish()
    return out


#: request kinds: full-snapshot manifest, one chunk, or a delta
#: manifest restricted to entries newer than ``from_round`` (what a
#: crash-recovered node with surviving state asks for)
STATE_REQ_MANIFEST = 0
STATE_REQ_CHUNK = 1
STATE_REQ_DELTA = 2
#: read spaces for TAG_STATE_READ (store/state.py namespaces)
STATE_READ_LEDGER = 0
STATE_READ_USER = 1

#: wire sanity bounds for snapshot entries: keys are namespace prefix +
#: digest or a typed-op key (<= 256), values are headers + at most one
#: producer body
MAX_STATE_KEY = 512
MAX_STATE_VALUE = MAX_PAYLOAD_BODY + 64
MAX_STATE_CHUNK_ENTRIES = 1024


class StateRequest:
    __slots__ = ("kind", "index", "from_round", "origin")

    def __init__(self, kind: int, index: int, from_round: int,
                 origin: PublicKey):
        self.kind = kind
        self.index = index
        self.from_round = from_round
        self.origin = origin


class StateManifestMsg:
    """A peer's snapshot offer: its state cursor plus the high QC that
    anchors it (the client checks ``qc.round >= last_round`` and
    verifies the certificate against its own committee before trusting
    the offered root).  ``origin`` names the offering peer so chunk
    requests go back to the same snapshot, not a random committee
    member at a different version."""

    __slots__ = ("version", "root", "last_round", "applied_payloads",
                 "chunk_count", "from_round", "qc", "origin", "links")

    def __init__(self, version, root, last_round, applied_payloads,
                 chunk_count, from_round, qc, origin, links=()):
        self.version = version
        self.root = root
        self.last_round = last_round
        self.applied_payloads = applied_payloads
        self.chunk_count = chunk_count
        self.from_round = from_round
        self.qc = qc
        self.origin = origin
        # certified schedule links: (reconfig block bytes, certifying QC
        # bytes) per committed epoch change, oldest first — the joiner
        # verifies each link against the previous epoch's committee
        # before splicing (statesync.py)
        self.links = links


class StateChunkMsg:
    __slots__ = ("version", "index", "from_round", "entries")

    def __init__(self, version, index, from_round, entries):
        self.version = version
        self.index = index
        self.from_round = from_round
        self.entries = entries  # tuple of (key, value) bytes pairs


def encode_state_request(kind: int, origin: PublicKey, index: int = 0,
                         from_round: int = 0) -> bytes:
    enc = (
        Encoder().u8(TAG_STATE_REQUEST).u8(STATE_FRAME_VERSION)
        .u8(kind).u32(index).u64(from_round)
    )
    encode_pk(enc, origin)
    return enc.finish()


def encode_state_manifest(version: int, root: bytes, last_round: int,
                          applied_payloads: int, chunk_count: int,
                          from_round: int, qc, origin: PublicKey,
                          links=()) -> bytes:
    if len(links) > MAX_SCHEDULE_LINKS:
        raise ValueError(
            f"manifest carries {len(links)} schedule links "
            f"(cap {MAX_SCHEDULE_LINKS})"
        )
    enc = (
        Encoder().u8(TAG_STATE_MANIFEST).u8(STATE_FRAME_VERSION)
        .u64(version).raw(root).u64(last_round).u64(applied_payloads)
        .u32(chunk_count).u64(from_round)
    )
    qc.encode(enc)
    encode_pk(enc, origin)
    enc.u16(len(links))
    for block_bytes, qc_bytes in links:
        enc.var_bytes(block_bytes)
        enc.var_bytes(qc_bytes)
    return enc.finish()


def encode_state_chunk(version: int, index: int, from_round: int,
                       entries) -> bytes:
    if len(entries) > MAX_STATE_CHUNK_ENTRIES:
        raise ValueError(
            f"state chunk carries {len(entries)} entries "
            f"(cap {MAX_STATE_CHUNK_ENTRIES})"
        )
    enc = (
        Encoder().u8(TAG_STATE_CHUNK).u8(STATE_FRAME_VERSION)
        .u64(version).u32(index).u64(from_round).u32(len(entries))
    )
    for key, value in entries:
        enc.var_bytes(key)
        enc.var_bytes(value)
    return enc.finish()


def encode_state_read(space: int, key: bytes) -> bytes:
    """Client-facing read at the node's last applied version (QC-anchored
    stale read — the reply carries the version/root anchor)."""
    return (
        Encoder().u8(TAG_STATE_READ).u8(STATE_FRAME_VERSION)
        .u8(space).var_bytes(key).finish()
    )


def _decode_state_version(dec: Decoder) -> None:
    version = dec.u8()
    if version != STATE_FRAME_VERSION:
        raise CodecError(f"unknown state frame version {version}")


# ---- state read reply (the reply frame on the read socket) -----------------

#: first byte of a state-read reply — disjoint from INGEST_ACK_TAG and
#: the legacy ``b"Ack"`` so reply kinds stay decidable from one byte
STATE_VALUE_TAG = 0xA3


class StateValue:
    """Typed read reply: the value (if found) plus the server's stale-
    read anchor — its applied version, state root and last applied
    round, so the client knows exactly how stale the answer is."""

    __slots__ = ("found", "state_version", "root", "last_round",
                 "entry_round", "value")

    def __init__(self, found, state_version, root, last_round,
                 entry_round, value):
        self.found = found
        self.state_version = state_version
        self.root = root
        self.last_round = last_round
        self.entry_round = entry_round
        self.value = value


def encode_state_value(found: bool, state_version: int, root: bytes,
                       last_round: int, entry_round: int,
                       value: bytes) -> bytes:
    return (
        Encoder().u8(STATE_VALUE_TAG).u8(STATE_FRAME_VERSION)
        .flag(found).u64(state_version).raw(root).u64(last_round)
        .u64(entry_round).var_bytes(value).finish()
    )


def decode_state_value(data: bytes) -> StateValue | None:
    """Reply-frame decode for read clients: None for any frame that is
    not a state-read reply; SerializationError on a malformed one."""
    if not data or data[0] != STATE_VALUE_TAG:
        return None
    try:
        dec = Decoder(data)
        dec.u8()
        _decode_state_version(dec)
        found = dec.flag()
        out = StateValue(
            found, dec.u64(), dec.raw(32), dec.u64(), dec.u64(),
            dec.var_bytes(MAX_STATE_VALUE),
        )
        dec.finish()
        return out
    except CodecError as e:
        raise SerializationError(str(e)) from e


def decode_message(data: bytes, scheme: str | None = None):
    """bytes -> (tag, payload). Raises SerializationError on malformed input.

    Payload by tag: Propose -> Block, Vote -> Vote, Timeout -> Timeout,
    TC -> TC, SyncRequest -> (Digest, PublicKey), Producer ->
    (Digest, body), ProducerV2 -> tuple of (Digest, body) pairs,
    StateRequest -> StateRequest, StateManifest -> StateManifestMsg,
    StateChunk -> StateChunkMsg, StateRead -> (space, key),
    Reconfig -> ReconfigOp.

    ``scheme`` (the committee's signature scheme) narrows accepted
    key/signature wire sizes to that scheme's; None accepts the union.
    An unknown scheme is a caller bug — raised as ValueError at once,
    never per-message from inside the codec error path.
    """
    sizes = None
    if scheme is not None:
        sizes = SCHEME_WIRE_SIZES.get(scheme)
        if sizes is None:
            raise ValueError(f"unknown committee scheme '{scheme}'")
    try:
        dec = Decoder(data)
        if sizes is not None:
            dec.pk_size, dec.sig_size = sizes
            compact = SCHEME_COMPACT_SIZES.get(scheme)
            if compact is None:
                dec.compact_sig_size = 0  # scheme has no compact form
            else:
                dec.compact_sig_size, dec.compact_bitmap_max = compact
        tag = dec.u8()
        if tag == TAG_PROPOSE:
            out = Block.decode(dec)
        elif tag == TAG_VOTE:
            out = Vote.decode(dec)
        elif tag == TAG_TIMEOUT:
            out = Timeout.decode(dec)
        elif tag == TAG_TC:
            out = TC.decode(dec)
        elif tag == TAG_SYNC_REQUEST:
            out = (Digest(dec.raw(Digest.SIZE)), decode_pk(dec))
        elif tag == TAG_PRODUCER:
            out = (Digest(dec.raw(Digest.SIZE)), dec.var_bytes(MAX_PAYLOAD_BODY))
        elif tag == TAG_PRODUCER_V2:
            version = dec.u8()
            if version != PRODUCER_FRAME_VERSION:
                raise CodecError(f"unknown producer frame version {version}")
            count = dec.u32()
            if not 1 <= count <= MAX_PRODUCER_BATCH:
                raise CodecError(
                    f"producer batch count {count} outside "
                    f"1..{MAX_PRODUCER_BATCH}"
                )
            out = tuple(
                (Digest(dec.raw(Digest.SIZE)), dec.var_bytes(MAX_PAYLOAD_BODY))
                for _ in range(count)
            )
        elif tag == TAG_STATE_REQUEST:
            _decode_state_version(dec)
            kind = dec.u8()
            if kind not in (STATE_REQ_MANIFEST, STATE_REQ_CHUNK,
                            STATE_REQ_DELTA):
                raise CodecError(f"invalid state request kind {kind}")
            out = StateRequest(kind, dec.u32(), dec.u64(), decode_pk(dec))
        elif tag == TAG_STATE_MANIFEST:
            _decode_state_version(dec)
            out = StateManifestMsg(
                dec.u64(), dec.raw(32), dec.u64(), dec.u64(),
                dec.u32(), dec.u64(), QC.decode(dec), decode_pk(dec),
            )
            n_links = dec.u16()
            if n_links > MAX_SCHEDULE_LINKS:
                raise CodecError(
                    f"manifest link count {n_links} exceeds cap "
                    f"{MAX_SCHEDULE_LINKS}"
                )
            out.links = tuple(
                (
                    dec.var_bytes(MAX_SCHEDULE_LINK_BYTES),
                    dec.var_bytes(MAX_SCHEDULE_LINK_BYTES),
                )
                for _ in range(n_links)
            )
        elif tag == TAG_STATE_CHUNK:
            _decode_state_version(dec)
            version, index, from_round = dec.u64(), dec.u32(), dec.u64()
            count = dec.u32()
            if count > MAX_STATE_CHUNK_ENTRIES:
                raise CodecError(
                    f"state chunk count {count} exceeds cap "
                    f"{MAX_STATE_CHUNK_ENTRIES}"
                )
            entries = tuple(
                (dec.var_bytes(MAX_STATE_KEY), dec.var_bytes(MAX_STATE_VALUE))
                for _ in range(count)
            )
            out = StateChunkMsg(version, index, from_round, entries)
        elif tag == TAG_STATE_READ:
            _decode_state_version(dec)
            space = dec.u8()
            if space not in (STATE_READ_LEDGER, STATE_READ_USER):
                raise CodecError(f"invalid state read space {space}")
            out = (space, dec.var_bytes(MAX_STATE_KEY))
        elif tag == TAG_RECONFIG:
            out = ReconfigOp.decode(dec)
        else:
            raise CodecError(f"unknown message tag {tag}")
        dec.finish()
        return tag, out
    except CodecError as e:
        raise SerializationError(str(e)) from e
