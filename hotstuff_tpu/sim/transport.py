"""In-memory transport: the sim's replacement for TCP.

``SimNet`` is a per-run registry of listeners keyed by port.  Opening a
connection pairs two standalone ``asyncio.StreamReader`` instances with
two :class:`SimStreamWriter` halves: writing on one side feeds the
other side's reader directly — same-loop, zero-copy, deterministic
delivery order (frames arrive in the order the sender's tasks ran).

The senders reach this through the ambient connector seam
(``hotstuff_tpu.utils.clock.default_connector``), so every production
code path — framing, fault plane ``decide()``/``barrier()``, WAN delay
scheduling, reconnect backoff, ACK pairing — runs verbatim on top of
the in-memory stream.  ``SimReceiver`` reuses the production
``Receiver._handle_connection`` loop unchanged; only listen/accept and
teardown are virtual.
"""

from __future__ import annotations

import asyncio
import logging

from ..network.receiver import Receiver

log = logging.getLogger(__name__)


class SimStreamWriter:
    """Duck-typed ``asyncio.StreamWriter`` over an in-memory pipe.

    The surface is exactly what the network stack touches: ``write`` /
    ``drain`` (framing.send_frame), ``close`` / ``is_closing`` /
    ``wait_closed`` (teardown paths), ``get_extra_info`` (peername
    logging; ``"socket"`` -> None makes framing.set_nodelay a no-op),
    and a ``transport`` with ``get_write_buffer_size`` (sender idle
    checks) and ``abort`` (pool.abort_writer)."""

    def __init__(self, peer_reader: asyncio.StreamReader, peername):
        self._peer_reader = peer_reader
        self._peername = peername
        self._peer: "SimStreamWriter | None" = None  # paired half
        self._closed = False

    # -- StreamWriter surface ------------------------------------------

    def write(self, data: bytes) -> None:
        if self._closed:
            raise ConnectionResetError("sim connection closed")
        self._peer_reader.feed_data(data)

    async def drain(self) -> None:
        if self._closed:
            raise ConnectionResetError("sim connection closed")
        await asyncio.sleep(0)  # yield, like a real flush

    def close(self) -> None:
        # Full TCP close: both directions die.  EOF the peer's read
        # side, then close the paired writer (recursion bounded by the
        # _closed flag).
        if self._closed:
            return
        self._closed = True
        try:
            self._peer_reader.feed_eof()
        except AssertionError:
            pass  # peer already fed EOF
        if self._peer is not None:
            self._peer.close()

    def is_closing(self) -> bool:
        return self._closed

    async def wait_closed(self) -> None:
        return None

    def get_extra_info(self, name: str, default=None):
        if name == "peername":
            return self._peername
        if name == "socket":
            return None  # framing.set_nodelay skips cleanly
        return default

    # -- transport duck-type (senders poke writer.transport directly) --

    @property
    def transport(self):
        return self

    def get_write_buffer_size(self) -> int:
        return 0  # writes land in the peer reader instantly

    def abort(self) -> None:
        self.close()


class SimNet:
    """One run's in-memory network: listener registry + connector."""

    def __init__(self):
        self._listeners: dict[int, "SimReceiver"] = {}
        self._conns = 0  # ephemeral "port" counter for peernames

    def listen(self, port: int, receiver: "SimReceiver") -> None:
        if port in self._listeners:
            raise OSError(f"sim: port {port} already in use")
        self._listeners[port] = receiver

    def unlisten(self, port: int) -> None:
        self._listeners.pop(port, None)

    async def open_connection(self, host: str, port: int, **_kw):
        """Ambient-connector replacement for ``asyncio.open_connection``:
        returns ``(reader, writer)`` for the client side and hands the
        server side to the listening :class:`SimReceiver`."""
        receiver = self._listeners.get(port)
        if receiver is None or receiver.closed:
            raise ConnectionRefusedError(f"sim: nothing listening on {port}")
        self._conns += 1
        client_reader = asyncio.StreamReader()
        server_reader = asyncio.StreamReader()
        client_writer = SimStreamWriter(server_reader, (host, port))
        server_writer = SimStreamWriter(
            client_reader, ("sim-client", self._conns)
        )
        client_writer._peer = server_writer
        server_writer._peer = client_writer
        receiver._accept(server_reader, server_writer)
        return client_reader, client_writer


class SimReceiver(Receiver):
    """Production :class:`Receiver` on the in-memory network: the frame
    loop, fault-plane inbound cut and handler dispatch are inherited
    verbatim; only listen/accept/teardown differ."""

    def __init__(
        self, host, port, handler, fault_plane=None, flows=None, net=None
    ):
        # flow accounting inherits the production rx charge site;
        # server-side peernames are ("sim-client", n) so receive flows
        # attribute to the deterministic "sim-client" label
        super().__init__(
            host, port, handler, fault_plane=fault_plane, flows=flows
        )
        self._net = net if net is not None else current_net()
        # dict-as-ordered-set: teardown cancels handlers in accept
        # order (determinism contract — no id()-ordered iteration)
        self._handler_tasks: dict[asyncio.Task, None] = {}
        self.closed = False

    async def spawn(self) -> None:
        self._net.listen(self.port, self)
        log.debug("Sim-listening on port %d", self.port)

    def _accept(self, reader, writer) -> None:
        task = asyncio.get_running_loop().create_task(
            self._handle_connection(reader, writer),
            name=f"sim-recv-{self.port}",
        )
        self._handler_tasks[task] = None
        task.add_done_callback(
            lambda t: self._handler_tasks.pop(t, None)
        )

    async def shutdown(self) -> None:
        self.closed = True
        self._net.unlisten(self.port)
        for w in list(self._writers):
            w.close()
        for t in list(self._handler_tasks):
            t.cancel()
        for t in list(self._handler_tasks):
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass


# --- ambient current network ------------------------------------------
# Mirrors the clock/rng/connector seams: Consensus.spawn(transport=
# "sim") builds SimReceivers without any signature change, resolving
# the net the runner installed for this run.

_CURRENT: SimNet | None = None


def set_current_net(net: SimNet | None) -> SimNet | None:
    global _CURRENT
    prev = _CURRENT
    _CURRENT = net
    return prev


def current_net() -> SimNet:
    if _CURRENT is None:
        raise RuntimeError(
            "no SimNet installed (transport='sim' outside a sim run?)"
        )
    return _CURRENT


__all__ = [
    "SimNet",
    "SimReceiver",
    "SimStreamWriter",
    "current_net",
    "set_current_net",
]
