"""Seeded schedule drawing + spec conversion (docs/SIM.md).

A **schedule** is the sim's unit of exploration: a JSON-serializable
dict drawn deterministically from a single integer seed, describing
everything that happens to the committee during one virtual-time run —
partitions, lossy/slow links, crash-points with WAL torn-tail bytes,
reconfiguration ops and Byzantine adversary policies.

``schedule_to_spec`` converts a schedule into the SAME spec dialect the
chaos plane already speaks (faults/plane.py + faults/adversary.py), so
one JSON document drives FaultPlane, AdversaryPlane and the invariant
checkers (benchmark/invariants.py ``check_run``) unchanged.
"""

from __future__ import annotations

import copy
import os
import random

from ..faults.adaptive import ADAPTIVE_POLICIES
from .loop import SIM_EPOCH

#: schedule format version (bump on incompatible changes so committed
#: seed corpora can be detected as stale instead of misread)
SCHEDULE_VERSION = 1

#: profile mix: roughly this fraction of explore seeds draw the
#: byz-collude family (expected full-history FAIL / trusted-subset PASS)
BYZ_FRACTION = 0.15

#: virtual-time layout (seconds).  Events are confined to
#: [EVENT_MIN_AT, EVENT_MAX_END] so every schedule heals with enough
#: virtual runway left for liveness recovery before the run ends.
DEFAULT_DURATION_S = 9.0
EVENT_MIN_AT = 1.5
EVENT_MAX_END = 6.0


def draw_schedule(
    seed: int,
    nodes: int = 4,
    duration_s: float | None = None,
    profile: str | None = None,
) -> dict:
    """Draw one schedule, a pure function of ``seed`` (plus the explicit
    shape arguments).  ``profile`` forces ``"honest"`` /
    ``"byz-collude"``; by default the seed decides."""
    rng = random.Random(f"sim-schedule|{seed}")
    if duration_s is None:
        duration_s = float(
            os.environ.get("HOTSTUFF_SIM_DURATION", DEFAULT_DURATION_S)
        )
    duration = float(duration_s)
    if profile is None:
        profile = "byz-collude" if rng.random() < BYZ_FRACTION else "honest"
    events: list[dict] = []

    def window(max_len: float = 2.5) -> tuple[float, float]:
        at = round(rng.uniform(EVENT_MIN_AT, EVENT_MAX_END - 1.0), 2)
        until = round(min(at + rng.uniform(0.8, max_len), EVENT_MAX_END), 2)
        return at, until

    if profile == "byz-collude":
        # f+1 colluders for the whole run: a REAL divergent history the
        # full-history safety checker must FAIL and the trusted-subset
        # regime must absolve.  Optional link noise rides along (and is
        # what the shrinker learns to drop).
        events.append(
            {
                "kind": "byz",
                "policy": "collude",
                "nodes": [0, 1],
                "at": 1.0,
                "until": None,
            }
        )
        for _ in range(rng.randint(0, 2)):
            at, until = window()
            src, dst = rng.sample(range(nodes), 2)
            events.append(
                {
                    "kind": "delay",
                    "from": [src],
                    "to": [dst],
                    "delay_ms": rng.randint(5, 40),
                    "jitter_pct": 20,
                    "at": at,
                    "until": until,
                }
            )
    elif profile == "adaptive":
        # one state-reactive adversary (faults/adaptive.py) plus the
        # protocol event its trigger preys on.  Windows are BOUNDED —
        # an unbounded liveness-impairing policy would push last_heal
        # to +inf and hide a genuine stall from the liveness check.
        policy = rng.choice(ADAPTIVE_POLICIES)
        attacker = rng.randrange(nodes)
        until = round(rng.uniform(4.5, EVENT_MAX_END), 2)
        events.append(
            {
                "kind": "byz",
                "policy": policy,
                "nodes": [attacker],
                "at": 1.0,
                "until": until,
            }
        )
        if policy == "sync-predator":
            # prey: a crash-recovered peer state-syncing mid-window
            victim = (attacker + 1 + rng.randrange(nodes - 1)) % nodes
            crash_at = round(rng.uniform(EVENT_MIN_AT, 3.0), 2)
            events.append(
                {
                    "kind": "crash",
                    "node": victim,
                    "at": crash_at,
                    "restart_at": round(
                        crash_at + rng.uniform(1.0, 1.8), 2
                    ),
                    "torn_bytes": rng.randint(1, 48),
                }
            )
        elif policy == "reconfig-sniper":
            # prey: an epoch activation inside the snipe margin
            events.append(
                {
                    "kind": "reconfig",
                    "at": round(rng.uniform(EVENT_MIN_AT, 3.5), 2),
                    "sponsor": rng.randrange(nodes),
                    "margin": rng.randint(2, 6),
                }
            )
            duration += 3.0
        elif policy == "ambush-leader":
            # prey: fresh TCs — isolate a peer so view changes seat the
            # ambusher behind one
            at, until2 = window()
            events.append(
                {
                    "kind": "isolate",
                    "node": (attacker + 1) % nodes,
                    "at": at,
                    "until": until2,
                }
            )
        elif policy == "timeout-surfer" and rng.random() < 0.5:
            # surfing alone stretches views; combined with a crashed
            # peer the committee drops to bare quorum and every
            # stretched view risks tipping into a stall
            crash_at = round(rng.uniform(EVENT_MIN_AT, 3.0), 2)
            events.append(
                {
                    "kind": "crash",
                    "node": (attacker + 1) % nodes,
                    "at": crash_at,
                    "restart_at": round(
                        crash_at + rng.uniform(1.2, 2.0), 2
                    ),
                    "torn_bytes": rng.randint(1, 48),
                }
            )
        for _ in range(rng.randint(0, 1)):
            at, until2 = window()
            src, dst = rng.sample(range(nodes), 2)
            events.append(
                {
                    "kind": "delay",
                    "from": [src],
                    "to": [dst],
                    "delay_ms": rng.randint(5, 40),
                    "jitter_pct": 20,
                    "at": at,
                    "until": until2,
                }
            )
    else:
        for _ in range(rng.randint(0, 2)):
            at, until = window()
            members = list(range(nodes))
            rng.shuffle(members)
            cut = rng.randint(1, nodes - 1)
            events.append(
                {
                    "kind": "partition",
                    "groups": [sorted(members[:cut]), sorted(members[cut:])],
                    "at": at,
                    "until": until,
                }
            )
        for _ in range(rng.randint(0, 2)):
            at, until = window()
            src, dst = rng.sample(range(nodes), 2)
            events.append(
                {
                    "kind": "loss",
                    "from": [src],
                    "to": [dst],
                    "drop": round(rng.uniform(0.05, 0.3), 3),
                    "at": at,
                    "until": until,
                }
            )
        for _ in range(rng.randint(0, 2)):
            at, until = window()
            src, dst = rng.sample(range(nodes), 2)
            events.append(
                {
                    "kind": "delay",
                    "from": [src],
                    "to": [dst],
                    "delay_ms": rng.randint(5, 60),
                    "jitter_pct": 20,
                    "at": at,
                    "until": until,
                }
            )
        if rng.random() < 0.5:
            at = round(rng.uniform(EVENT_MIN_AT, EVENT_MAX_END - 2.5), 2)
            events.append(
                {
                    "kind": "crash",
                    "node": rng.randrange(nodes),
                    "at": at,
                    "restart_at": round(at + rng.uniform(1.5, 2.5), 2),
                    "torn_bytes": rng.randint(1, 48),
                }
            )
        if rng.random() < 0.2:
            events.append(
                {
                    "kind": "reconfig",
                    "at": round(rng.uniform(EVENT_MIN_AT, EVENT_MAX_END - 2.0), 2),
                    "sponsor": rng.randrange(nodes),
                    "margin": rng.randint(2, 6),
                }
            )
            # The op can only 2-chain-commit after the last heal, and the
            # epoch boundary then costs a view change before the first
            # epoch-2 commit — give the handoff its own virtual runway.
            duration += 3.0
    return {
        "version": SCHEDULE_VERSION,
        "seed": int(seed),
        "nodes": int(nodes),
        "duration_s": duration,
        "profile": profile,
        "events": events,
    }


def schedule_to_spec(schedule: dict, base_port: int) -> dict:
    """Convert a schedule into the shared chaos/adversary spec dialect.
    ``epoch_unix`` is pinned to :data:`SIM_EPOCH` (= virtual t=0), so
    window arithmetic, liveness heal math and journal timestamps all
    share one origin."""
    nodes = int(schedule["nodes"])
    spec: dict = {
        "name": f"sim-{schedule['seed']}",
        "seed": int(schedule["seed"]),
        "epoch_unix": SIM_EPOCH,
        "nodes": {f"127.0.0.1:{base_port + i}": i for i in range(nodes)},
        "rules": [],
        "adversary": [],
        "crashes": [],
        # generous in virtual seconds: post-heal view-change backoff is
        # capped by the sim's Parameters (see harness), so recovery is
        # quick, but a bound keeps a genuinely wedged run a FAILURE
        "liveness": {"resume_within_s": 20.0, "max_round_gap": 400},
    }
    for i, ev in enumerate(schedule.get("events", ())):
        kind = ev["kind"]
        label = f"{kind}-{i}"
        if kind == "partition":
            spec["rules"].append(
                {
                    "label": label,
                    "partition": ev["groups"],
                    "at": ev["at"],
                    "until": ev["until"],
                }
            )
        elif kind == "isolate":
            spec["rules"].append(
                {
                    "label": label,
                    "isolate": ev["node"],
                    "at": ev["at"],
                    "until": ev["until"],
                }
            )
        elif kind == "loss":
            spec["rules"].append(
                {
                    "label": label,
                    "from": ev["from"],
                    "to": ev["to"],
                    "drop": ev["drop"],
                    "at": ev["at"],
                    "until": ev["until"],
                }
            )
        elif kind == "delay":
            spec["rules"].append(
                {
                    "label": label,
                    "from": ev["from"],
                    "to": ev["to"],
                    "delay_ms": ev["delay_ms"],
                    "jitter_pct": ev.get("jitter_pct", 0),
                    "at": ev["at"],
                    "until": ev["until"],
                }
            )
        elif kind == "crash":
            spec["crashes"].append(
                {
                    "node": ev["node"],
                    "at": ev["at"],
                    "restart_at": ev["restart_at"],
                    "torn_bytes": ev.get("torn_bytes", 0),
                }
            )
        elif kind == "byz":
            spec["adversary"].append(
                {
                    "policy": ev["policy"],
                    "nodes": list(ev.get("nodes", ())) or [ev.get("node", 0)],
                    "at": ev["at"],
                    "until": ev["until"],
                }
            )
        elif kind == "reconfig":
            spec.setdefault("reconfig", []).append(
                {
                    "at": ev["at"],
                    "sponsor": ev["sponsor"],
                    "margin": ev["margin"],
                }
            )
            spec["handoff_gap_rounds"] = 400
        else:
            raise ValueError(f"unknown schedule event kind {kind!r}")
    if spec["adversary"]:
        spec["quorum_mode"] = "trusted-subset"
    else:
        del spec["adversary"]
    if not spec["crashes"]:
        del spec["crashes"]
    return spec


def profile_of_events(events) -> str:
    """Recompute a schedule's profile from its event list (mutation can
    cross profile boundaries): collude anywhere ⇒ the byz-collude
    judgment, any other adversary ⇒ adaptive, else honest."""
    policies = [
        ev.get("policy") for ev in events if ev.get("kind") == "byz"
    ]
    if "collude" in policies:
        return "byz-collude"
    if policies:
        return "adaptive"
    return "honest"


def mutate_schedule(schedule: dict, salt: int) -> dict:
    """One guided-search mutation step: a pure function of
    ``(schedule, salt)``.  The child gets a derived seed (fresh
    adversary/ambient rng streams) and a recomputed profile, and every
    mutated window stays inside the healing envelope so the liveness
    check keeps applying."""
    rng = random.Random(f"sim-mutate|{schedule['seed']}|{salt}")
    child = copy.deepcopy(schedule)
    events: list[dict] = child["events"]
    nodes = int(child["nodes"])

    def window() -> tuple[float, float]:
        at = round(rng.uniform(EVENT_MIN_AT, EVENT_MAX_END - 1.0), 2)
        until = round(
            min(at + rng.uniform(0.8, 2.5), EVENT_MAX_END), 2
        )
        return at, until

    ops = [
        "add-adaptive-byz",
        "add-crash",
        "add-link-noise",
        "perturb-timing",
        "drop-event",
    ]
    op = rng.choice(ops)
    if op == "add-adaptive-byz":
        policy = rng.choice(ADAPTIVE_POLICIES)
        until = round(rng.uniform(4.5, EVENT_MAX_END), 2)
        events.append(
            {
                "kind": "byz",
                "policy": policy,
                "nodes": [rng.randrange(nodes)],
                "at": 1.0,
                "until": until,
            }
        )
        if policy == "reconfig-sniper" and not any(
            ev["kind"] == "reconfig" for ev in events
        ):
            events.append(
                {
                    "kind": "reconfig",
                    "at": round(rng.uniform(EVENT_MIN_AT, 3.5), 2),
                    "sponsor": rng.randrange(nodes),
                    "margin": rng.randint(2, 6),
                }
            )
            child["duration_s"] = float(child["duration_s"]) + 3.0
    elif op == "add-crash":
        crash_at = round(rng.uniform(EVENT_MIN_AT, 3.0), 2)
        events.append(
            {
                "kind": "crash",
                "node": rng.randrange(nodes),
                "at": crash_at,
                "restart_at": round(crash_at + rng.uniform(1.0, 2.0), 2),
                "torn_bytes": rng.randint(1, 48),
            }
        )
    elif op == "add-link-noise":
        at, until = window()
        src, dst = rng.sample(range(nodes), 2)
        if rng.random() < 0.5:
            events.append(
                {
                    "kind": "loss",
                    "from": [src],
                    "to": [dst],
                    "drop": round(rng.uniform(0.05, 0.3), 3),
                    "at": at,
                    "until": until,
                }
            )
        else:
            events.append(
                {
                    "kind": "delay",
                    "from": [src],
                    "to": [dst],
                    "delay_ms": rng.randint(5, 60),
                    "jitter_pct": 20,
                    "at": at,
                    "until": until,
                }
            )
    elif op == "perturb-timing" and events:
        ev = rng.choice(events)
        shift = round(rng.uniform(-0.4, 0.4), 2)
        if "at" in ev:
            ev["at"] = round(
                min(max(0.5, ev["at"] + shift), EVENT_MAX_END - 0.5), 2
            )
        if ev.get("until") is not None:
            ev["until"] = round(
                min(max(ev["at"] + 0.3, ev["until"] + shift), EVENT_MAX_END),
                2,
            )
        if "restart_at" in ev:
            ev["restart_at"] = round(
                max(ev["at"] + 0.5, ev["restart_at"] + shift), 2
            )
    elif op == "drop-event" and events:
        events.pop(rng.randrange(len(events)))

    # derived child seed: fresh ambient/adversary rng streams, and a
    # distinct corpus identity for promotion (deterministic in salt)
    child["seed"] = (int(schedule["seed"]) * 1000003 + int(salt)) % (1 << 31)
    child["profile"] = profile_of_events(events)
    return child


__all__ = [
    "BYZ_FRACTION",
    "DEFAULT_DURATION_S",
    "SCHEDULE_VERSION",
    "draw_schedule",
    "mutate_schedule",
    "profile_of_events",
    "schedule_to_spec",
]
