"""One virtual-time committee in a process.

``SimCluster`` boots N full consensus stacks (core, proposer,
synchronizer, aggregator, state machine, state-sync, reconfig) on the
current — virtual — event loop with ``transport="sim"``, then executes a
schedule against them: a paced payload feeder, seeded crash-points with
WAL torn-tail emulation, restarts through the REAL recovery + state-sync
path, and sponsored reconfiguration ops submitted over the in-memory
network exactly as an operator would submit them over TCP.

Everything here is deterministic given the schedule: node keys come from
a fixed seed, payloads are ``sha512("sim|<seed>|<k>")``, torn-tail bytes
are drawn from ``Random("sim-torn|<seed>|<node>")``, and all timing is
virtual-loop timers.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import struct

from ..consensus import Committee, CommitteeSchedule, Parameters
from ..consensus.consensus import Consensus
from ..consensus.reconfig import ReconfigOp
from ..consensus.wire import encode_reconfig
from ..crypto import (
    Digest,
    Signature,
    SignatureService,
    generate_keypair,
)
from ..network.framing import send_frame
from ..store import Store
from ..store.engine import WalEngine
from ..telemetry import NodeTelemetry
from ..telemetry.journal import Journal
from ..telemetry.metrics import Registry
from .transport import SimNet

log = logging.getLogger(__name__)

#: every sim committee binds 127.0.0.1:<SIM_BASE_PORT + i> on its own
#: private SimNet, so the value never collides with anything real
SIM_BASE_PORT = 7000

#: deterministic committee keys (same scheme as tests/common.py)
KEY_SEED = bytes(32)

#: consensus timing in VIRTUAL milliseconds — tight, because virtual
#: timeouts are free: a view change costs CPU, not wall-clock
SIM_TIMEOUT_MS = 1_000
SIM_SYNC_RETRY_MS = 2_000
# cap below the post-heal runway (duration - EVENT_MAX_END): a node
# whose view timer backed off during a long partition must fire at
# least once before the run ends, or every heal-at-the-edge schedule
# reads as a liveness failure
SIM_TIMEOUT_CAP_MS = 2_000


class SimNode:
    """One committee member's mortal half: store + spawned stack."""

    def __init__(self, idx: int, pk, sk, path: str):
        self.idx = idx
        self.pk = pk
        self.sk = sk
        self.path = path
        self.store: Store | None = None
        self.stack: Consensus | None = None
        self.commits: asyncio.Queue | None = None
        self.drain: asyncio.Task | None = None
        self.tel: NodeTelemetry | None = None
        self.alive = False
        self.restarts = 0


class SimCluster:
    """Boots a committee from a schedule and executes its events."""

    def __init__(self, schedule: dict, workdir: str, net: SimNet):
        self.schedule = schedule
        self.workdir = workdir
        self.net = net
        self.seed = int(schedule["seed"])
        self.n = int(schedule["nodes"])
        self.duration = float(schedule["duration_s"])
        #: payload feed rate in payloads per virtual second
        self.rate = float(os.environ.get("HOTSTUFF_SIM_RATE", "8"))
        pairs = [generate_keypair(KEY_SEED, i) for i in range(self.n)]
        pairs.sort(key=lambda kp: kp[0])
        self.pairs = pairs
        self.committee = Committee.new(
            [
                (pk, 1, ("127.0.0.1", SIM_BASE_PORT + i))
                for i, (pk, _) in enumerate(pairs)
            ],
            epoch=1,
        )
        # Reconfiguration needs splice(); wrap only when the schedule
        # actually exercises it, so plain runs keep the cheaper object.
        if any(ev["kind"] == "reconfig" for ev in schedule.get("events", ())):
            self.membership = CommitteeSchedule([(1, self.committee)])
        else:
            self.membership = self.committee
        self.params = Parameters(
            timeout_delay=SIM_TIMEOUT_MS,
            sync_retry_delay=SIM_SYNC_RETRY_MS,
            timeout_cap_ms=SIM_TIMEOUT_CAP_MS,
        )
        self.nodes = [
            SimNode(i, pk, sk, os.path.join(workdir, f"store-{i}"))
            for i, (pk, sk) in enumerate(pairs)
        ]
        # node short-name -> [flow table per boot] (telemetry/flows.py
        # ``table()``), harvested at each crash/stop: all charges are
        # driven by virtual-time scheduling, so a same-seed double-run
        # must reproduce these byte-for-byte (SimVerdict.flows)
        self.flow_tables: dict[str, list[dict]] = {}

    #: ``str(pk)[:8] -> node index``: the per-actor logger suffix
    #: (e.g. ``hotstuff_tpu.consensus.core.<pk8>``), used by the runner
    #: to attribute captured log records to committee members.
    def prefix_map(self) -> dict[str, int]:
        return {str(pk)[:8]: i for i, (pk, _) in enumerate(self.pairs)}

    # -- lifecycle ------------------------------------------------------

    async def start_node(self, i: int) -> None:
        node = self.nodes[i]
        node.store = Store(node.path, engine=WalEngine(node.path))
        node.commits = asyncio.Queue()
        # Per-node flight recorder on a PRIVATE registry (the global one
        # belongs to the host process).  resume=True so a crash-restart
        # keeps the pre-crash segments: the merge dedups the (node, seq)
        # overlap and critical-path attribution spans the whole run.
        short = str(node.pk)[:8]
        node.tel = NodeTelemetry(short, registry=Registry())
        node.tel.attach_journal(
            Journal(
                short,
                os.path.join(self.workdir, "journals"),
                resume=node.restarts > 0,
            )
        )
        node.stack = await Consensus.spawn(
            node.pk,
            self.membership,
            self.params,
            SignatureService(node.sk),
            node.store,
            node.commits,
            bind_host="127.0.0.1",
            transport="sim",
            telemetry=node.tel,
        )
        node.drain = asyncio.get_running_loop().create_task(
            self._drain(node.commits), name=f"sim-drain-{i}"
        )
        node.alive = True

    @staticmethod
    async def _drain(q: asyncio.Queue) -> None:
        while True:
            await q.get()

    async def crash(self, i: int, torn_bytes: int = 0) -> None:
        """Kill node ``i`` mid-flight and emulate a torn in-flight WAL
        append: a partial record (or bare header claiming more bytes
        than follow) lands at the tail, exactly what a power cut during
        ``WalEngine.put`` leaves behind.  Recovery's ``_replay`` must
        truncate it.  We APPEND garbage rather than truncate completed
        records — the engine flushes per put, so completed records are
        durable by contract, and deleting a persisted vote would
        manufacture a genuine (not injected) double-vote."""
        node = self.nodes[i]
        if not node.alive:
            return
        node.alive = False
        await node.stack.shutdown()
        node.drain.cancel()
        try:
            await node.drain
        except asyncio.CancelledError:
            pass
        node.store.close()
        self._harvest_flows(node)
        if node.tel is not None and node.tel.journal is not None:
            node.tel.journal.close()
        k = max(0, int(torn_bytes))
        if k:
            rng = random.Random(f"sim-torn|{self.seed}|{i}")
            if k < 8:
                tail = bytes(rng.randrange(256) for _ in range(k))
            else:
                # complete 8-byte header promising a 32B key + 200B
                # value that never made it to disk
                tail = struct.pack("<II", 32, 200) + bytes(
                    rng.randrange(256) for _ in range(k - 8)
                )
            with open(os.path.join(node.path, "wal.log"), "ab") as f:
                f.write(tail)
        log.info("sim: node %d crashed (torn tail %dB)", i, k)

    async def restart(self, i: int) -> None:
        node = self.nodes[i]
        if node.alive:
            return
        # bump BEFORE start_node: restarts > 0 is its resume signal
        node.restarts += 1
        await self.start_node(i)
        log.info("sim: node %d restarted", i)

    async def stop_all(self) -> None:
        for node in self.nodes:
            if not node.alive:
                continue
            node.alive = False
            await node.stack.shutdown()
            node.drain.cancel()
            try:
                await node.drain
            except asyncio.CancelledError:
                pass
            node.store.close()
            self._harvest_flows(node)
            if node.tel is not None and node.tel.journal is not None:
                node.tel.journal.close()

    def _harvest_flows(self, node: SimNode) -> None:
        """Snapshot the node's flow table at teardown (one entry per
        boot — the accountant is rebuilt on restart)."""
        tel = node.tel
        flows = getattr(tel, "flows", None) if tel is not None else None
        if flows is None or not flows.enabled:
            return
        self.flow_tables.setdefault(str(node.pk)[:8], []).append(
            flows.table()
        )

    # -- schedule execution ---------------------------------------------

    async def run(self) -> None:
        for i in range(self.n):
            await self.start_node(i)
        loop = asyncio.get_running_loop()
        aux = [loop.create_task(self._feed(), name="sim-feeder")]
        for ev in self.schedule.get("events", ()):
            if ev["kind"] == "crash":
                aux.append(
                    loop.create_task(self._crash_event(ev), name="sim-crash")
                )
            elif ev["kind"] == "reconfig":
                aux.append(
                    loop.create_task(
                        self._reconfig_event(ev), name="sim-reconfig"
                    )
                )
        try:
            await asyncio.sleep(self.duration)
        finally:
            for t in aux:
                t.cancel()
            for t in aux:
                try:
                    await t
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            await self.stop_all()

    async def _feed(self) -> None:
        """Paced payload feed to every live node's producer queue.  All
        nodes see the same payload stream (the proposer's dedup keeps
        one commit per digest); pacing is virtual, so a 12s run feeds
        ~12*rate payloads regardless of wall-clock."""
        interval = 1.0 / max(self.rate, 0.001)
        k = 0
        while True:
            payload = Digest.of(f"sim|{self.seed}|{k}".encode())
            k += 1
            for node in self.nodes:
                if node.alive:
                    try:
                        node.stack.tx_producer.put_nowait(payload)
                    except asyncio.QueueFull:
                        pass  # backpressure: drop, like a real client
            await asyncio.sleep(interval)

    async def _crash_event(self, ev: dict) -> None:
        await asyncio.sleep(max(0.0, ev["at"]))
        await self.crash(ev["node"], ev.get("torn_bytes", 0))
        restart_at = ev.get("restart_at")
        if restart_at is not None:
            await asyncio.sleep(max(0.0, restart_at - ev["at"]))
            await self.restart(ev["node"])

    async def _reconfig_event(self, ev: dict) -> None:
        """Submit a sponsored epoch-bump op to every member's consensus
        port, the same frames an operator's ``reconfig`` CLI sends over
        TCP.  Membership-preserving (same authorities, epoch 2): the
        run exercises admission, 2-chain commit, splice and activation
        without orphaning any node."""
        await asyncio.sleep(max(0.0, ev["at"]))
        new_com = Committee.new(
            [
                (pk, 1, ("127.0.0.1", SIM_BASE_PORT + i))
                for i, (pk, _) in enumerate(self.pairs)
            ],
            epoch=2,
        )
        pk_s, sk_s = self.pairs[int(ev["sponsor"]) % self.n]
        op = ReconfigOp(
            new_committee=new_com, margin=int(ev["margin"]), sponsor=pk_s
        )
        op.signature = Signature.new(Digest(op.digest()), sk_s)
        frame = encode_reconfig(op)
        for i in range(self.n):
            try:
                _reader, writer = await self.net.open_connection(
                    "127.0.0.1", SIM_BASE_PORT + i
                )
                await send_frame(writer, frame)
                await asyncio.sleep(0.05)  # let the handler drain first
                writer.close()
            except (ConnectionRefusedError, ConnectionResetError):
                continue  # crashed member; the live quorum suffices
        log.info(
            "sim: reconfig op submitted (sponsor %d margin %d)",
            ev["sponsor"],
            ev["margin"],
        )


__all__ = ["KEY_SEED", "SIM_BASE_PORT", "SimCluster", "SimNode"]
