"""Seeded schedule exploration + greedy failure shrinking.

``explore`` sweeps a seed range: each seed deterministically draws a
schedule (partitions, link loss/delay, crashes with torn WAL tails,
reconfig ops, Byzantine collusion), runs it in virtual time, and judges
it with the full invariant stack.  A failing seed gets a **repro
bundle** — the schedule JSON, the merged journal and the rendered
invariant block, all reproducible from the printed seed alone — and is
then **shrunk**: events are greedily removed one at a time while the
failure persists, converging to a minimal failing schedule (re-running
a candidate costs well under a second of wall-clock, so shrinking is
cheap).

Failure semantics per profile:
- ``honest`` schedules must PASS every invariant; any FAIL is a finding.
- ``byz-collude`` schedules must FAIL full-history safety AND PASS the
  trusted-subset recheck; anything else (no divergence, or divergence
  the trusted subset can't absolve) is a finding.
"""

from __future__ import annotations

import dataclasses
import json
import os

from .runner import SimVerdict, run_schedule
from .schedule import draw_schedule, mutate_schedule, schedule_to_spec


@dataclasses.dataclass
class Finding:
    seed: int
    profile: str
    failures: list[str]
    repro_dir: str | None
    minimal_events: list[dict]  #: shrunk schedule's surviving events


@dataclasses.dataclass
class ExploreResult:
    seeds: int
    passed: int
    findings: list[Finding]
    honest: int
    byz: int
    #: commit critical-path regime -> number of seeds classified there
    #: (per-seed attribution from the sim journals; seeds whose runs
    #: committed nothing don't contribute)
    regimes: dict = dataclasses.field(default_factory=dict)
    #: schedules whose run raised an invariant threat (full-history
    #: divergence or a liveness stall) — the guided-vs-flat comparison
    #: metric (scripts/adapt_check.py)
    threats: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


def shrink(
    schedule: dict,
    is_failing=None,
    progress=None,
) -> dict:
    """Greedily minimize a failing schedule: repeatedly try dropping one
    event; keep any drop under which the run still fails.  Loops until a
    full pass removes nothing (a local minimum — every remaining event
    is necessary for THIS failure)."""
    if is_failing is None:
        is_failing = lambda sched: not run_schedule(sched).ok  # noqa: E731
    current = dict(schedule)
    changed = True
    while changed and current["events"]:
        changed = False
        for i in range(len(current["events"])):
            candidate = dict(current)
            candidate["events"] = (
                current["events"][:i] + current["events"][i + 1 :]
            )
            if is_failing(candidate):
                if progress:
                    progress(
                        f"  shrink: dropped {current['events'][i]['kind']} "
                        f"event, {len(candidate['events'])} remain"
                    )
                current = candidate
                changed = True
                break
    return current


def write_repro_bundle(
    schedule: dict, verdict: SimVerdict, out_dir: str
) -> str:
    """Materialize seed + schedule JSON + merged journal + verdict in
    ``out_dir`` by re-running the schedule there (deterministic, so the
    re-run IS the original run)."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "schedule.json"), "w") as f:
        json.dump(schedule, f, indent=2)
    rerun = run_schedule(schedule, workdir=out_dir)  # writes journal.jsonl
    with open(os.path.join(out_dir, "verdict.json"), "w") as f:
        json.dump(rerun.to_json(), f, indent=2)
    with open(os.path.join(out_dir, "invariants.txt"), "w") as f:
        f.write(rerun.block + "\n")
    return out_dir


def explore(
    seeds: int,
    nodes: int = 4,
    start_seed: int = 0,
    duration_s: float | None = None,
    out_dir: str | None = None,
    do_shrink: bool = True,
    progress=None,
) -> ExploreResult:
    """Run ``seeds`` consecutive seeds starting at ``start_seed``; see
    module docstring for the failure semantics."""
    say = progress or (lambda _msg: None)
    findings: list[Finding] = []
    regimes: dict = {}
    passed = honest = byz = threats = 0
    for k in range(seeds):
        seed = start_seed + k
        schedule = draw_schedule(seed, nodes=nodes, duration_s=duration_s)
        if schedule["profile"] == "byz-collude":
            byz += 1
        else:
            honest += 1
        verdict = run_schedule(schedule)
        if verdict.threats:
            threats += 1
        if verdict.attribution is not None:
            regime = verdict.attribution.get("regime", "unknown")
            regimes[regime] = regimes.get(regime, 0) + 1
        if verdict.ok:
            passed += 1
            if (k + 1) % 25 == 0:
                say(f"  {k + 1}/{seeds} seeds, {len(findings)} findings")
            continue
        say(
            f"  FAIL seed {seed} ({schedule['profile']}): "
            + "; ".join(verdict.failures)
        )
        repro = None
        if out_dir is not None:
            repro = write_repro_bundle(
                schedule,
                verdict,
                os.path.join(out_dir, f"repro-{seed}"),
            )
            say(f"  repro bundle: {repro}")
        minimal = schedule
        if do_shrink and schedule["events"]:
            minimal = shrink(schedule, progress=say)
            say(
                f"  minimal failing schedule: "
                f"{len(minimal['events'])}/{len(schedule['events'])} events "
                f"({', '.join(e['kind'] for e in minimal['events'])})"
            )
            if repro is not None:
                with open(os.path.join(repro, "minimal.json"), "w") as f:
                    json.dump(minimal, f, indent=2)
        findings.append(
            Finding(
                seed=seed,
                profile=schedule["profile"],
                failures=list(verdict.failures),
                repro_dir=repro,
                minimal_events=list(minimal["events"]),
            )
        )
    return ExploreResult(
        seeds=seeds,
        passed=passed,
        findings=findings,
        honest=honest,
        byz=byz,
        regimes=regimes,
        threats=threats,
    )


# ---------------------------------------------------------------------------
# guided search (ISSUE 18): fitness-driven mutation instead of a flat sweep


def fitness(verdict: SimVerdict, baseline_regime: str | None = None) -> int:
    """Score one run for the guided search.  Ordered by how close the
    schedule got to breaking an invariant: an uncontained attack
    (trusted-subset FAIL) dominates everything, then full-history
    divergence, then a liveness stall, then a critpath regime shift,
    then raw timeout pressure as the gradient signal that lets the
    search climb toward stalls it hasn't reached yet."""
    score = 0
    if verdict.trusted_ok is False:
        score += 5000
    if not verdict.safety_ok:
        score += 1000
    if "liveness-stall" in verdict.threats:
        score += 200
    if baseline_regime is not None and verdict.attribution is not None:
        regime = verdict.attribution.get("regime")
        if regime and regime != baseline_regime:
            score += 25
    score += 2 * verdict.timeouts
    return score


@dataclasses.dataclass
class GuidedResult:
    """Outcome of one guided search (``explore_guided``)."""

    budget: int  #: schedules evaluated by the SEARCH (== flat's seeds)
    generations: int
    passed: int
    threats: int  #: schedules whose run raised an invariant threat
    best_fitness: int
    findings: list[Finding]
    #: corpus entries appended to tests/data/sim_seeds.json (inline
    #: schedule + expected verdict + journal digest)
    promoted: list[dict] = dataclasses.field(default_factory=list)
    #: canned scenario spec files emitted for the real-cluster
    #: chaos/byz matrix (``python -m benchmark chaos --spec <file>``)
    scenarios: list[str] = dataclasses.field(default_factory=list)
    regimes: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings


def _schedule_key(schedule: dict) -> str:
    return json.dumps(
        {k: schedule[k] for k in ("nodes", "duration_s", "events")},
        sort_keys=True,
    )


def promote_to_corpus(entries: list[dict], corpus_path: str) -> int:
    """Append promoted schedules to the regression corpus, deduplicating
    on journal digest (the run identity).  Returns how many were new."""
    with open(corpus_path) as f:
        corpus = json.load(f)
    seen = {
        e.get("journal_digest")
        for e in corpus["entries"]
        if e.get("journal_digest")
    }
    added = 0
    for entry in entries:
        if entry.get("journal_digest") in seen:
            continue
        corpus["entries"].append(entry)
        seen.add(entry.get("journal_digest"))
        added += 1
    if added:
        with open(corpus_path, "w") as f:
            json.dump(corpus, f, indent=2)
            f.write("\n")
    return added


def emit_scenario(schedule: dict, verdict: SimVerdict, out_path: str) -> str:
    """Write a promoted schedule as a canned chaos/byz scenario spec —
    the exact dialect ``python -m benchmark chaos --spec`` consumes (the
    chaos bench re-stamps ``nodes``/``epoch_unix`` at boot, so the sim
    values are placeholders)."""
    from .harness import SIM_BASE_PORT

    spec = schedule_to_spec(schedule, SIM_BASE_PORT)
    spec["name"] = f"adapt-{schedule['seed']}"
    spec["_promoted"] = {
        "profile": schedule.get("profile", "honest"),
        "threats": list(verdict.threats),
        "sim_ok": verdict.ok,
        "journal_digest": verdict.journal_digest,
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(spec, f, indent=2)
        f.write("\n")
    return out_path


def explore_guided(
    budget: int,
    nodes: int = 4,
    start_seed: int = 0,
    duration_s: float | None = None,
    out_dir: str | None = None,
    do_shrink: bool = True,
    corpus_path: str | None = None,
    scenarios_dir: str | None = None,
    max_promote: int = 4,
    progress=None,
) -> GuidedResult:
    """Fitness-guided schedule search at the SAME run budget as a flat
    ``explore(seeds=budget)`` sweep.

    Generation 0 draws ~budget/3 schedules (two thirds forced to the
    adaptive profile, the rest seed-decided like the flat sweep); every
    later generation mutates the fittest survivors
    (:func:`~hotstuff_tpu.sim.schedule.mutate_schedule`) until the
    budget is spent.  Failing schedules become findings (shrunk, repro
    bundle); the fittest invariant-threatening schedules are shrunk
    with a threat-preserving predicate and **promoted**: appended to
    the regression corpus with their inline schedule + journal digest,
    and emitted as canned chaos scenario specs.
    """
    say = progress or (lambda _msg: None)
    findings: list[Finding] = []
    regimes: dict = {}
    evaluated: list[tuple[int, dict, SimVerdict]] = []
    seen: set[str] = set()
    passed = threats = spent = 0
    baseline_regime: str | None = None
    gen = 0
    gen_size = max(2, min(budget, budget // 3 or budget))

    def evaluate(schedule: dict) -> SimVerdict:
        nonlocal passed, threats, spent
        verdict = run_schedule(schedule)
        spent += 1
        seen.add(_schedule_key(schedule))
        if verdict.ok:
            passed += 1
        if verdict.threats:
            threats += 1
            say(
                f"  THREAT seed {schedule['seed']} "
                f"({schedule['profile']}): {','.join(verdict.threats)} "
                f"fitness {fitness(verdict, baseline_regime)}"
            )
        if verdict.attribution is not None:
            regime = verdict.attribution.get("regime", "unknown")
            regimes[regime] = regimes.get(regime, 0) + 1
        evaluated.append(
            (fitness(verdict, baseline_regime), schedule, verdict)
        )
        return verdict

    # generation 0: a seeded nursery biased toward adaptive adversaries
    for k in range(min(gen_size, budget)):
        profile = "adaptive" if k % 3 != 2 else None
        schedule = draw_schedule(
            start_seed + k, nodes=nodes, duration_s=duration_s,
            profile=profile,
        )
        evaluate(schedule)
    # modal critpath regime of the nursery = the "normal" regime;
    # mutants that shift it score fitness
    if regimes:
        baseline_regime = max(regimes.items(), key=lambda kv: kv[1])[0]

    # later generations: mutate the fittest survivors
    salt = 0
    while spent < budget:
        gen += 1
        size = min(gen_size, budget - spent)
        parents = sorted(evaluated, key=lambda e: -e[0])[: max(2, size // 3)]
        say(
            f"  gen {gen}: {size} mutants from {len(parents)} parents "
            f"(best fitness {parents[0][0]})"
        )
        for i in range(size):
            parent = parents[i % len(parents)][1]
            child = None
            for _ in range(16):  # skip children identical to a past run
                salt += 1
                candidate = mutate_schedule(parent, salt)
                if _schedule_key(candidate) not in seen:
                    child = candidate
                    break
            evaluate(child if child is not None else candidate)

    # findings: schedules that FAILED their profile expectation
    for _fit, schedule, verdict in sorted(evaluated, key=lambda e: -e[0]):
        if verdict.ok:
            continue
        say(
            f"  FAIL seed {schedule['seed']} ({schedule['profile']}): "
            + "; ".join(verdict.failures)
        )
        repro = None
        if out_dir is not None:
            repro = write_repro_bundle(
                schedule, verdict,
                os.path.join(out_dir, f"repro-{schedule['seed']}"),
            )
            say(f"  repro bundle: {repro}")
        minimal = schedule
        if do_shrink and schedule["events"]:
            minimal = shrink(schedule, progress=say)
            if repro is not None:
                with open(os.path.join(repro, "minimal.json"), "w") as f:
                    json.dump(minimal, f, indent=2)
        findings.append(
            Finding(
                seed=schedule["seed"],
                profile=schedule["profile"],
                failures=list(verdict.failures),
                repro_dir=repro,
                minimal_events=list(minimal["events"]),
            )
        )

    # promotion: the fittest threatening schedules (failures first —
    # sort order above — then contained attacks), shrunk with a
    # threat-preserving predicate, re-run for their final expectations
    promoted: list[dict] = []
    scenarios: list[str] = []
    # class diversity: a fitness sort alone would fill every slot with
    # copies of the single highest-scoring attack family; cap each
    # (profile, threat-set) class so a lower-scoring but DIFFERENT
    # counterexample (e.g. an adaptive liveness stall next to collude
    # divergences) still earns a corpus slot
    per_class = max(1, max_promote // 2)
    classes: dict[tuple, int] = {}
    for _fit, schedule, verdict in sorted(evaluated, key=lambda e: -e[0]):
        if len(promoted) >= max_promote:
            break
        if not verdict.threats:
            continue
        cls = (schedule.get("profile"), tuple(sorted(verdict.threats)))
        if classes.get(cls, 0) >= per_class:
            continue
        classes[cls] = classes.get(cls, 0) + 1
        minimal = schedule
        if do_shrink and schedule["events"]:
            want = set(verdict.threats)
            minimal = shrink(
                schedule,
                is_failing=lambda s, w=want: (
                    set(run_schedule(s).threats) >= w
                ),
                progress=say,
            )
        final = run_schedule(minimal)
        entry = {
            "seed": int(minimal["seed"]),
            "profile": minimal.get("profile", "honest"),
            "ok": bool(final.ok),
            "note": (
                "guided search (ISSUE 18): "
                + ",".join(
                    ev.get("policy", ev["kind"])
                    for ev in minimal["events"]
                    if ev["kind"] in ("byz", "crash", "reconfig")
                )
                + " -> " + ",".join(final.threats)
            ),
            "threats": list(final.threats),
            "journal_digest": final.journal_digest,
            "schedule": minimal,
        }
        promoted.append(entry)
        say(
            f"  PROMOTE seed {minimal['seed']} "
            f"({entry['profile']}, ok={entry['ok']}): {entry['note']}"
        )
        if scenarios_dir is not None:
            scenarios.append(
                emit_scenario(
                    minimal, final,
                    os.path.join(
                        scenarios_dir, f"adapt-{minimal['seed']}.json"
                    ),
                )
            )
    if corpus_path is not None and promoted:
        added = promote_to_corpus(promoted, corpus_path)
        say(f"  corpus: {added} new entries -> {corpus_path}")

    return GuidedResult(
        budget=spent,
        generations=gen,
        passed=passed,
        threats=threats,
        best_fitness=max((f for f, _s, _v in evaluated), default=0),
        findings=findings,
        promoted=promoted,
        scenarios=scenarios,
        regimes=regimes,
    )


__all__ = [
    "ExploreResult",
    "Finding",
    "GuidedResult",
    "emit_scenario",
    "explore",
    "explore_guided",
    "fitness",
    "promote_to_corpus",
    "shrink",
    "write_repro_bundle",
]
