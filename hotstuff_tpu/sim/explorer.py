"""Seeded schedule exploration + greedy failure shrinking.

``explore`` sweeps a seed range: each seed deterministically draws a
schedule (partitions, link loss/delay, crashes with torn WAL tails,
reconfig ops, Byzantine collusion), runs it in virtual time, and judges
it with the full invariant stack.  A failing seed gets a **repro
bundle** — the schedule JSON, the merged journal and the rendered
invariant block, all reproducible from the printed seed alone — and is
then **shrunk**: events are greedily removed one at a time while the
failure persists, converging to a minimal failing schedule (re-running
a candidate costs well under a second of wall-clock, so shrinking is
cheap).

Failure semantics per profile:
- ``honest`` schedules must PASS every invariant; any FAIL is a finding.
- ``byz-collude`` schedules must FAIL full-history safety AND PASS the
  trusted-subset recheck; anything else (no divergence, or divergence
  the trusted subset can't absolve) is a finding.
"""

from __future__ import annotations

import dataclasses
import json
import os

from .runner import SimVerdict, run_schedule
from .schedule import draw_schedule


@dataclasses.dataclass
class Finding:
    seed: int
    profile: str
    failures: list[str]
    repro_dir: str | None
    minimal_events: list[dict]  #: shrunk schedule's surviving events


@dataclasses.dataclass
class ExploreResult:
    seeds: int
    passed: int
    findings: list[Finding]
    honest: int
    byz: int
    #: commit critical-path regime -> number of seeds classified there
    #: (per-seed attribution from the sim journals; seeds whose runs
    #: committed nothing don't contribute)
    regimes: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings


def shrink(
    schedule: dict,
    is_failing=None,
    progress=None,
) -> dict:
    """Greedily minimize a failing schedule: repeatedly try dropping one
    event; keep any drop under which the run still fails.  Loops until a
    full pass removes nothing (a local minimum — every remaining event
    is necessary for THIS failure)."""
    if is_failing is None:
        is_failing = lambda sched: not run_schedule(sched).ok  # noqa: E731
    current = dict(schedule)
    changed = True
    while changed and current["events"]:
        changed = False
        for i in range(len(current["events"])):
            candidate = dict(current)
            candidate["events"] = (
                current["events"][:i] + current["events"][i + 1 :]
            )
            if is_failing(candidate):
                if progress:
                    progress(
                        f"  shrink: dropped {current['events'][i]['kind']} "
                        f"event, {len(candidate['events'])} remain"
                    )
                current = candidate
                changed = True
                break
    return current


def write_repro_bundle(
    schedule: dict, verdict: SimVerdict, out_dir: str
) -> str:
    """Materialize seed + schedule JSON + merged journal + verdict in
    ``out_dir`` by re-running the schedule there (deterministic, so the
    re-run IS the original run)."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "schedule.json"), "w") as f:
        json.dump(schedule, f, indent=2)
    rerun = run_schedule(schedule, workdir=out_dir)  # writes journal.jsonl
    with open(os.path.join(out_dir, "verdict.json"), "w") as f:
        json.dump(rerun.to_json(), f, indent=2)
    with open(os.path.join(out_dir, "invariants.txt"), "w") as f:
        f.write(rerun.block + "\n")
    return out_dir


def explore(
    seeds: int,
    nodes: int = 4,
    start_seed: int = 0,
    duration_s: float | None = None,
    out_dir: str | None = None,
    do_shrink: bool = True,
    progress=None,
) -> ExploreResult:
    """Run ``seeds`` consecutive seeds starting at ``start_seed``; see
    module docstring for the failure semantics."""
    say = progress or (lambda _msg: None)
    findings: list[Finding] = []
    regimes: dict = {}
    passed = honest = byz = 0
    for k in range(seeds):
        seed = start_seed + k
        schedule = draw_schedule(seed, nodes=nodes, duration_s=duration_s)
        if schedule["profile"] == "byz-collude":
            byz += 1
        else:
            honest += 1
        verdict = run_schedule(schedule)
        if verdict.attribution is not None:
            regime = verdict.attribution.get("regime", "unknown")
            regimes[regime] = regimes.get(regime, 0) + 1
        if verdict.ok:
            passed += 1
            if (k + 1) % 25 == 0:
                say(f"  {k + 1}/{seeds} seeds, {len(findings)} findings")
            continue
        say(
            f"  FAIL seed {seed} ({schedule['profile']}): "
            + "; ".join(verdict.failures)
        )
        repro = None
        if out_dir is not None:
            repro = write_repro_bundle(
                schedule,
                verdict,
                os.path.join(out_dir, f"repro-{seed}"),
            )
            say(f"  repro bundle: {repro}")
        minimal = schedule
        if do_shrink and schedule["events"]:
            minimal = shrink(schedule, progress=say)
            say(
                f"  minimal failing schedule: "
                f"{len(minimal['events'])}/{len(schedule['events'])} events "
                f"({', '.join(e['kind'] for e in minimal['events'])})"
            )
            if repro is not None:
                with open(os.path.join(repro, "minimal.json"), "w") as f:
                    json.dump(minimal, f, indent=2)
        findings.append(
            Finding(
                seed=seed,
                profile=schedule["profile"],
                failures=list(verdict.failures),
                repro_dir=repro,
                minimal_events=list(minimal["events"]),
            )
        )
    return ExploreResult(
        seeds=seeds,
        passed=passed,
        findings=findings,
        honest=honest,
        byz=byz,
        regimes=regimes,
    )


__all__ = ["ExploreResult", "Finding", "explore", "shrink", "write_repro_bundle"]
