"""Deterministic virtual-time simulation plane (docs/SIM.md).

Runs an entire N-node committee — core, proposer, synchronizer,
aggregator, state machine, state-sync, reconfig — inside ONE process on
a virtual-time event loop (no real sleeps, no real sockets), with the
existing FaultPlane / AdversaryPlane threaded through an in-memory
transport.  Every run is a pure function of its schedule seed; failures
replay from the seed alone and shrink to a minimal failing schedule.
"""

from .explorer import (
    ExploreResult,
    GuidedResult,
    explore,
    explore_guided,
    fitness,
    shrink,
)
from .harness import SimCluster
from .loop import SIM_EPOCH, SimDeadlock, SimLoop, VirtualClock
from .runner import SimVerdict, run_schedule
from .schedule import (
    draw_schedule,
    mutate_schedule,
    profile_of_events,
    schedule_to_spec,
)
from .transport import SimNet, SimReceiver

__all__ = [
    "SIM_EPOCH",
    "ExploreResult",
    "GuidedResult",
    "SimCluster",
    "SimDeadlock",
    "SimLoop",
    "SimNet",
    "SimReceiver",
    "SimVerdict",
    "VirtualClock",
    "draw_schedule",
    "explore",
    "explore_guided",
    "fitness",
    "mutate_schedule",
    "profile_of_events",
    "run_schedule",
    "schedule_to_spec",
    "shrink",
]
