"""Execute one schedule in virtual time and judge it.

``run_schedule`` is the sim's unit of work: build a fresh
:class:`~hotstuff_tpu.sim.loop.SimLoop`, install the ambient
clock/rng/connector seams and the chaos/adversary env, run the committee
through the schedule, then render each node's captured log records into
``node-<i>.log`` files in the benchmark log dialect and hand them to the
EXISTING invariant stack (``benchmark.invariants.check_run``) — safety,
state-root agreement, liveness-after-heal, epoch agreement, handoff gap
and the trusted-subset recheck all run unmodified.

Determinism contract: the verdict and the journal digest are a pure
function of the schedule.  Everything ambient is pinned per run (virtual
clock at ``SIM_EPOCH``, ``Random("sim-run|<seed>")``, in-memory
network); the double-run test in tests/test_simnet.py enforces
byte-identical journals.
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime
import hashlib
import json
import logging
import os
import random
import tempfile

from ..utils.clock import (
    set_default_clock,
    set_default_connector,
    set_default_rng,
)
from .harness import SIM_BASE_PORT, SimCluster
from .loop import SIM_EPOCH, SimDeadlock, SimLoop, VirtualClock
from .schedule import schedule_to_spec
from .transport import SimNet, set_current_net

#: env the sim pins for the duration of a run (value None = unset)
_RUN_ENV_BASE = {
    "HOTSTUFF_WAN_SPEC": None,  # WAN emu draws real-region latencies
    "HOTSTUFF_MAX_PEER_CONNS": None,
    "HOTSTUFF_RECONFIG_LISTEN": None,
    "HOTSTUFF_STATE_SYNC_LAG": "2",  # rejoiners snapshot-sync promptly
}


@dataclasses.dataclass
class SimVerdict:
    """One schedule's outcome + everything needed to reproduce it."""

    seed: int
    profile: str
    ok: bool  #: run matched its profile's expectation
    all_ok: bool  #: raw full-history check_run verdict
    safety_ok: bool
    trusted_ok: bool | None  #: trusted-subset recheck (byz specs only)
    commits: int  #: total committed-block observations across nodes
    rounds: int  #: highest committed round observed by any node
    journal_digest: str
    block: str  #: rendered CHAOS/BYZ/RECONFIG report
    failures: list[str] = dataclasses.field(default_factory=list)
    #: invariant-threat classification (guided search fitness input):
    #: "full-history-divergence" when safety failed, "liveness-stall"
    #: when the run missed liveness/commit expectations with safety
    #: intact.  Empty for clean runs.
    threats: list[str] = dataclasses.field(default_factory=list)
    #: view-timeout firings observed across the committee (fitness
    #: pressure signal — more timeouts = closer to a stall)
    timeouts: int = 0
    #: commit critical-path attribution document (telemetry/critpath.py
    #: ``attribution()`` shape) merged from the committee's per-node
    #: flight-recorder journals; None when the run committed nothing.
    #: Deterministic per seed — virtual clocks stamp the journals.
    attribution: dict | None = None
    #: wire-level flow tables per node short-name, one table per boot
    #: (telemetry/flows.py ``table()``): integer byte ledgers driven
    #: entirely by virtual-time scheduling, so a same-seed double-run
    #: must reproduce them byte-for-byte (tests/test_flows.py).  None
    #: when accounting is disabled.
    flows: dict | None = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class _LogCapture(logging.Handler):
    """Collects every ``hotstuff_tpu`` log record with its VIRTUAL
    timestamp (``record.created`` is real wall time — useless here)."""

    def __init__(self, clock: VirtualClock):
        super().__init__(level=logging.INFO)
        self._clock = clock
        self.records: list[tuple[float, str, str]] = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — never let logging kill the run
            return
        self.records.append((self._clock.monotonic(), record.name, msg))


def _stamp(vt: float) -> str:
    """Render virtual seconds as the benchmark log timestamp.  The
    parser (benchmark/logs.py ``_ts``) reads it back as LOCAL time, so
    format through ``fromtimestamp`` for an exact round-trip."""
    dt = datetime.datetime.fromtimestamp(SIM_EPOCH + vt)
    return f"{dt:%Y-%m-%dT%H:%M:%S}.{dt.microsecond // 1000:03d}Z"


def _render_logs(
    records: list[tuple[float, str, str]],
    prefix_map: dict[str, int],
    logs_dir: str,
    nodes: int,
) -> None:
    """Write ``node-<i>.log`` files in the benchmark dialect.  Per-node
    attribution rides on the actor logger suffix (``...core.<pk8>``);
    unattributed records (sim harness, planes) stay journal-only."""
    lines: dict[int, list[str]] = {i: [] for i in range(nodes)}
    for vt, name, msg in records:
        suffix = name.rsplit(".", 1)[-1]
        idx = prefix_map.get(suffix)
        if idx is not None:
            lines[idx].append(f"{_stamp(vt)} INFO {msg}")
    os.makedirs(logs_dir, exist_ok=True)
    for i in range(nodes):
        with open(os.path.join(logs_dir, f"node-{i}.log"), "w") as f:
            f.write("\n".join(lines[i]) + ("\n" if lines[i] else ""))


def _write_journal(
    records: list[tuple[float, str, str]], path: str
) -> str:
    """Merged run journal: one JSONL line per captured record, virtual
    timestamps, stable key order.  Returns the sha256 hex digest — the
    byte-identity witness for the determinism contract."""
    payload = "".join(
        json.dumps({"t": round(vt, 6), "src": name, "msg": msg})
        + "\n"
        for vt, name, msg in records
    ).encode()
    with open(path, "wb") as f:
        f.write(payload)
    return hashlib.sha256(payload).hexdigest()


def run_schedule(schedule: dict, workdir: str | None = None) -> SimVerdict:
    """Run one schedule to completion in virtual time (see module
    docstring).  ``workdir`` receives stores, rendered logs and the
    journal; a temp dir (cleaned up) is used when omitted."""
    if workdir is None:
        with tempfile.TemporaryDirectory(prefix="hotstuff-sim-") as tmp:
            return run_schedule(schedule, tmp)

    spec = schedule_to_spec(schedule, SIM_BASE_PORT)
    seed = int(schedule["seed"])

    # -- pin the ambient world ----------------------------------------
    saved_env = {
        k: os.environ.get(k)
        for k in list(_RUN_ENV_BASE)
        + ["HOTSTUFF_FAULTS", "HOTSTUFF_ADVERSARY", "HOTSTUFF_ADAPT_RNG_DIR"]
    }
    for k, v in _RUN_ENV_BASE.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    os.environ["HOTSTUFF_FAULTS"] = json.dumps(spec)
    if spec.get("adversary"):
        os.environ["HOTSTUFF_ADVERSARY"] = json.dumps(spec)
        # adversary rng continuity across crash/restart (faults/
        # adaptive.py): checkpoint the per-node draw stream under the
        # run workdir so a restarted adversary resumes it — same seed
        # must keep yielding a byte-identical journal with adaptive
        # policies active
        os.environ["HOTSTUFF_ADAPT_RNG_DIR"] = os.path.join(
            workdir, "adv-rng"
        )
    else:
        os.environ.pop("HOTSTUFF_ADVERSARY", None)
        os.environ.pop("HOTSTUFF_ADAPT_RNG_DIR", None)

    loop = SimLoop()
    clock = VirtualClock(loop)
    net = SimNet()
    prev_clock = set_default_clock(clock)
    prev_rng = set_default_rng(random.Random(f"sim-run|{seed}"))
    prev_conn = set_default_connector(net.open_connection)
    prev_net = set_current_net(net)

    capture = _LogCapture(clock)
    hs_log = logging.getLogger("hotstuff_tpu")
    prev_level = hs_log.level
    hs_log.addHandler(capture)
    hs_log.setLevel(logging.INFO)

    failures: list[str] = []
    cluster = SimCluster(schedule, workdir, net)
    try:
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(cluster.run())
        except SimDeadlock as exc:
            failures.append(f"virtual-loop deadlock: {exc}")
        # drain stragglers (cancelled receiver handlers, sender
        # reconnect loops) so the loop closes clean; sorted by name so
        # cancellation order never depends on set/heap layout
        pending = sorted(
            (t for t in asyncio.all_tasks(loop) if not t.done()),
            key=lambda t: t.get_name(),
        )
        for t in pending:
            t.cancel()
        if pending:
            try:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            except SimDeadlock:
                failures.append("virtual-loop deadlock during teardown")
        loop.close()
    finally:
        asyncio.set_event_loop(None)
        set_default_clock(prev_clock)
        set_default_rng(prev_rng)
        set_default_connector(prev_conn)
        set_current_net(prev_net)
        hs_log.removeHandler(capture)
        hs_log.setLevel(prev_level)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # -- judge ---------------------------------------------------------
    from benchmark.invariants import (
        adversaries_from_spec,
        check_run,
        check_safety,
        commits_from_logs,
        trusted_subset_recheck,
    )

    logs_dir = os.path.join(workdir, "logs")
    _render_logs(capture.records, cluster.prefix_map(), logs_dir, cluster.n)
    journal_digest = _write_journal(
        capture.records, os.path.join(workdir, "journal.jsonl")
    )

    # stage attribution from the committee's flight-recorder journals
    # (best-effort: an attribution failure must never fail the verdict)
    attribution: dict | None = None
    try:
        journals_dir = os.path.join(workdir, "journals")
        if os.path.isdir(journals_dir):
            from benchmark.traces import TraceSet

            from ..telemetry import critpath as _critpath

            traces = TraceSet.load(journals_dir)
            if traces.journals:
                report = _critpath.analyze(traces)
                if report.commits:
                    attribution = report.attribution()
    except Exception as exc:  # noqa: BLE001 — observability is advisory
        logging.getLogger(__name__).warning(
            "sim critpath attribution failed: %s", exc
        )

    all_ok, block = check_run(logs_dir, spec, epoch_unix=SIM_EPOCH)
    commits = commits_from_logs(logs_dir)
    safety_ok, safety_viol = check_safety(commits)
    adversaries = adversaries_from_spec(spec)
    trusted_ok: bool | None = None
    trusted_viol: list = []
    if adversaries:
        trusted_ok, trusted_viol = trusted_subset_recheck(
            commits, set(adversaries)
        )

    # invariant-threat classification + timeout tally: the guided
    # explorer's fitness inputs (sim/explorer.py).  Independent of the
    # per-profile ok judgment below — a threat on an "adaptive" run can
    # be a correctly-contained attack and still score fitness.
    threats: list[str] = []
    if not safety_ok:
        threats.append("full-history-divergence")
    elif not all_ok:
        threats.append("liveness-stall")
    timeouts = sum(
        1 for _vt, _name, msg in capture.records
        if msg.startswith("Timeout reached for round")
    )

    profile = schedule.get("profile", "honest")
    if failures:
        ok = False
    elif profile == "byz-collude":
        # expectation: the collusion REALLY diverges the full history
        # (FAIL) while the trusted-subset regime absolves it (PASS)
        ok = (not safety_ok) and bool(trusted_ok)
        if safety_ok:
            failures.append("byz-collude schedule left no divergence")
        if not trusted_ok:
            failures.extend(
                f"trusted-subset: {v}" for v in (trusted_viol or ())
            )
    elif profile == "adaptive":
        # adaptive attacks range from fully absorbed (all invariants
        # green) to full-history divergence; the containment bar is the
        # trusted-subset regime — the f+1 honest view must stay
        # self-consistent no matter what the adversary pulled off
        ok = trusted_ok is not False
        if trusted_ok is False:
            failures.extend(
                f"trusted-subset: {v}" for v in (trusted_viol or ())
            )
    else:
        ok = all_ok
        if not all_ok:
            failures.append("invariant check failed (see block)")

    return SimVerdict(
        seed=seed,
        profile=profile,
        ok=ok,
        all_ok=all_ok,
        safety_ok=safety_ok,
        trusted_ok=trusted_ok,
        commits=sum(len(v) for v in commits.values()),
        rounds=max(
            (r for obs in commits.values() for _t, r, _d in obs),
            default=0,
        ),
        journal_digest=journal_digest,
        block=block,
        failures=failures,
        threats=threats,
        timeouts=timeouts,
        attribution=attribution,
        flows=cluster.flow_tables or None,
    )


__all__ = ["SimVerdict", "run_schedule"]
