"""Virtual-time asyncio event loop.

The simulator's clock advances ONLY when the run queue is empty: the
loop is a stock ``SelectorEventLoop`` whose selector never touches an
fd — ``select(timeout)`` simply jumps virtual time forward by
``timeout`` and reports nothing ready.  CPython's ``_run_once`` computes
that timeout as 0 while callbacks are ready and as the distance to the
nearest timer otherwise, so a committee that sleeps 5 virtual seconds
costs zero wall-clock: the whole run is CPU-bound protocol work.

``select(None)`` — no ready callbacks AND no scheduled timers — means
nothing can ever wake the loop again (the sim has no external I/O), so
it raises :class:`SimDeadlock` instead of hanging forever.

Constraint inherited by everything running on this loop: no threads.
``run_in_executor`` / ``call_soon_threadsafe`` wake a real loop through
the self-pipe, which this selector never reports ready.  The simulated
committee honours this (pure-Python WAL engine, inline ed25519
signing); see docs/SIM.md.
"""

from __future__ import annotations

import asyncio
import selectors

#: Virtual wall-clock origin (unix seconds).  Schedule specs pin
#: ``epoch_unix`` to this, so scenario t=0 == loop time 0.0 in every
#: run regardless of the real date — a precondition for byte-identical
#: journals across runs.
SIM_EPOCH = 1_700_000_000.0


class SimDeadlock(RuntimeError):
    """The virtual loop ran out of ready callbacks AND timers: every
    task is parked on an event nothing will ever set."""


class _VirtualSelector(selectors._BaseSelectorImpl):
    """Selector that advances virtual time instead of polling fds.

    ``_BaseSelectorImpl`` supplies the register/unregister/get_map
    bookkeeping the loop needs for its self-pipe; only ``select`` is
    virtual."""

    def __init__(self, loop: "SimLoop"):
        super().__init__()
        self._loop = loop

    def select(self, timeout=None):
        if timeout is None:
            raise SimDeadlock(
                "virtual loop has no ready callbacks and no timers "
                "(every task is blocked on an event that will never fire)"
            )
        if timeout > 0:
            self._loop._vtime += timeout
        return []


class SimLoop(asyncio.SelectorEventLoop):
    """A ``SelectorEventLoop`` on virtual time (see module docstring)."""

    def __init__(self):
        self._vtime = 0.0
        super().__init__(selector=_VirtualSelector(self))

    def time(self) -> float:
        return self._vtime


class VirtualClock:
    """The :class:`~hotstuff_tpu.utils.clock.Clock` implementation the
    simulator installs as the ambient default: wall time is
    ``SIM_EPOCH`` + virtual seconds, monotonic time is virtual seconds,
    sleeps are virtual-loop timers."""

    def __init__(self, loop: SimLoop):
        self._loop = loop

    def time(self) -> float:
        return SIM_EPOCH + self._loop.time()

    def monotonic(self) -> float:
        return self._loop.time()

    def monotonic_ns(self) -> int:
        return int(self._loop.time() * 1e9)

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)


__all__ = ["SIM_EPOCH", "SimDeadlock", "SimLoop", "VirtualClock"]
