"""BLS12-381 signatures: aggregation + threshold — the alternate crypto
backend (BASELINE config 5; reference boundary crypto/src/lib.rs:232-257).

Scheme (min-signature variant):
  secret key  x  in Z_r
  public key  PK = x·G2            (96-byte compressed)
  signature   sig = x·H(m) in G1   (48-byte compressed)
  verify      e(sig, G2) == e(H(m), PK)

Aggregation (same message — the QC shape): signatures ADD in G1 and
public keys ADD in G2, so a 2f+1-vote QC verifies with ONE pairing
equality regardless of committee size:
  e(sum sig_i, G2) == e(H(m), sum PK_i)
This additive structure is exactly what the TPU design exploits — G1
point addition is a psum over the mesh (docs/BLS_TPU_DESIGN.md).

Threshold (t-of-n): Shamir shares of x over Z_r; partial signatures
combine by Lagrange interpolation at zero in the exponent:
  sig = sum_i lambda_i · sig_i  for any t valid partials.

This is the CPU reference implementation; proof-of-possession (PoP) is
required against rogue-key attacks when aggregating adversarial keys —
``prove_possession``/``verify_possession`` implement the standard PoP
over the public key encoding.
"""

from __future__ import annotations

import hashlib
import secrets

from .curve import G1Point, G2Point, hash_to_g1
from .fields import R
from .pairing import pairings_equal

__all__ = [
    "BlsSecretKey",
    "BlsPublicKey",
    "BlsSignature",
    "keygen",
    "aggregate_signatures",
    "aggregate_public_keys",
    "verify_aggregate",
    "split_secret",
    "combine_partials",
    "lagrange_at_zero",
    "prove_possession",
    "verify_possession",
]


class BlsSecretKey:
    def __init__(self, scalar: int):
        self.scalar = scalar % R
        if self.scalar == 0:
            raise ValueError("zero secret key")

    def sign(self, message: bytes) -> "BlsSignature":
        return BlsSignature(hash_to_g1(message).mul(self.scalar))

    def public_key(self) -> "BlsPublicKey":
        return BlsPublicKey(G2Point.generator().mul(self.scalar))


class BlsPublicKey:
    def __init__(self, point: G2Point):
        self.point = point

    def to_bytes(self) -> bytes:
        return self.point.to_bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlsPublicKey | None":
        pt = G2Point.from_bytes(data)
        return None if pt is None else cls(pt)

    def verify(self, message: bytes, sig: "BlsSignature") -> bool:
        if sig.point.inf or self.point.inf:
            return False
        return pairings_equal(
            sig.point, G2Point.generator(), hash_to_g1(message), self.point
        )

    def __eq__(self, o: object) -> bool:
        return isinstance(o, BlsPublicKey) and self.point == o.point

    def __hash__(self) -> int:
        return hash(self.point)


class BlsSignature:
    def __init__(self, point: G1Point):
        self.point = point

    def to_bytes(self) -> bytes:
        return self.point.to_bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlsSignature | None":
        pt = G1Point.from_bytes(data)
        return None if pt is None else cls(pt)


def keygen(seed: bytes | None = None) -> tuple[BlsPublicKey, BlsSecretKey]:
    if seed is None:
        scalar = secrets.randbelow(R - 1) + 1
    else:
        scalar = (
            int.from_bytes(hashlib.sha512(b"bls-keygen" + seed).digest(), "big")
            % (R - 1)
        ) + 1
    sk = BlsSecretKey(scalar)
    return sk.public_key(), sk


def aggregate_signatures(sigs: list[BlsSignature]) -> BlsSignature:
    # Jacobian accumulation: no per-addition field inversion.
    return BlsSignature(G1Point.sum([s.point for s in sigs]))


def aggregate_public_keys(pks: list[BlsPublicKey]) -> BlsPublicKey:
    return BlsPublicKey(G2Point.sum([pk.point for pk in pks]))


def verify_aggregate(
    message: bytes, pks: list[BlsPublicKey], agg_sig: BlsSignature
) -> bool:
    """Shared-message aggregate verify: ONE pairing equality for the
    whole vote set (the reference's QC-verify batch, messages.rs:195,
    collapsed to constant pairing cost)."""
    if not pks:
        return False
    return aggregate_public_keys(pks).verify(message, agg_sig)


# -- proof of possession (rogue-key defence) --------------------------------

_POP_DST = b"HOTSTUFF_TPU_BLS_POP"


def prove_possession(sk: BlsSecretKey) -> BlsSignature:
    pk_bytes = sk.public_key().to_bytes()
    return BlsSignature(hash_to_g1(_POP_DST + pk_bytes).mul(sk.scalar))


def verify_possession(pk: BlsPublicKey, proof: BlsSignature) -> bool:
    if proof.point.inf:
        return False
    return pairings_equal(
        proof.point,
        G2Point.generator(),
        hash_to_g1(_POP_DST + pk.to_bytes()),
        pk.point,
    )


# -- threshold (t-of-n Shamir in Z_r) ---------------------------------------


def split_secret(
    sk: BlsSecretKey, t: int, n: int, seed: bytes | None = None
) -> list[tuple[int, BlsSecretKey]]:
    """Shamir shares (index_i, share_i), indices 1..n; any t reconstruct."""
    if not (1 <= t <= n):
        raise ValueError("need 1 <= t <= n")
    coeffs = [sk.scalar]
    for i in range(1, t):
        if seed is None:
            coeffs.append(secrets.randbelow(R))
        else:
            coeffs.append(
                int.from_bytes(
                    hashlib.sha512(b"bls-share" + seed + bytes([i])).digest(),
                    "big",
                )
                % R
            )
    shares = []
    for idx in range(1, n + 1):
        acc = 0
        for j, c in enumerate(coeffs):
            acc = (acc + c * pow(idx, j, R)) % R
        shares.append((idx, BlsSecretKey(acc)))
    return shares


def lagrange_at_zero(indices: list[int]) -> list[int]:
    """lambda_i = prod_{j != i} x_j / (x_j - x_i) mod R."""
    coeffs = []
    for i, xi in enumerate(indices):
        num, den = 1, 1
        for j, xj in enumerate(indices):
            if i == j:
                continue
            num = num * xj % R
            den = den * ((xj - xi) % R) % R
        coeffs.append(num * pow(den, R - 2, R) % R)
    return coeffs


def combine_partials(
    partials: list[tuple[int, BlsSignature]],
) -> BlsSignature:
    """Combine >= t partial signatures into the group signature."""
    indices = [idx for idx, _ in partials]
    lams = lagrange_at_zero(indices)
    acc = G1Point.identity()
    for (_, sig), lam in zip(partials, lams):
        acc = acc + sig.point.mul(lam)
    return BlsSignature(acc)
