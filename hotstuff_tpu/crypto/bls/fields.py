"""BLS12-381 field tower: Fq -> Fq2 -> Fq6 -> Fq12.

Pure-Python arbitrary-precision arithmetic — the CPU reference backend
for the threshold-signature variant (BASELINE config 5; the reference
exposes the equivalent boundary at crypto/src/lib.rs:232-257).  The TPU
aggregation design builds on G1 point addition only (see
docs/BLS_TPU_DESIGN.md); pairings stay host-side in both designs.

Tower construction (the standard one used by every BLS12-381
implementation):
  Fq2  = Fq[u]  / (u^2 + 1)
  Fq6  = Fq2[v] / (v^3 - (u + 1))
  Fq12 = Fq6[w] / (w^2 - v)
"""

from __future__ import annotations

# Base field prime.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order (scalar field).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative: x = -0xd201000000010000).
X = -0xD201000000010000


def fq_inv(a: int) -> int:
    return pow(a, P - 2, P)


class Fq2:
    """a + b·u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int = 0):
        self.c0 = c0 % P
        self.c1 = c1 % P

    ZERO: "Fq2"
    ONE: "Fq2"

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o: "Fq2") -> "Fq2":
        # Karatsuba: (a0 + a1 u)(b0 + b1 u) = a0b0 - a1b1 + (a0b1 + a1b0) u
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fq2(t0 - t1, t2 - t0 - t1)

    def mul_int(self, k: int) -> "Fq2":
        return Fq2(self.c0 * k, self.c1 * k)

    def square(self) -> "Fq2":
        # (a + bu)^2 = (a+b)(a-b) + 2ab u
        return Fq2(
            (self.c0 + self.c1) * (self.c0 - self.c1), 2 * self.c0 * self.c1
        )

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def inverse(self) -> "Fq2":
        # 1/(a+bu) = (a - bu)/(a^2 + b^2)
        norm = (self.c0 * self.c0 + self.c1 * self.c1) % P
        inv = fq_inv(norm)
        return Fq2(self.c0 * inv, -self.c1 * inv)

    def mul_by_nonresidue(self) -> "Fq2":
        # * (u + 1)
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __eq__(self, o: object) -> bool:
        return (
            isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1
        )

    def __hash__(self) -> int:
        return hash((self.c0, self.c1))

    def __repr__(self) -> str:
        return f"Fq2({hex(self.c0)}, {hex(self.c1)})"

    def pow(self, e: int) -> "Fq2":
        result = Fq2.ONE
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def sqrt(self) -> "Fq2 | None":
        """Square root in Fq2 (used by G2 decompression), via the
        Adj-Rodríguez-Henríquez method for p ≡ 3 (mod 4)."""
        if self.is_zero():
            return Fq2.ZERO
        a1 = self.pow((P - 3) // 4)
        alpha = a1.square() * self
        x0 = a1 * self
        if alpha == Fq2(-1 % P, 0):
            return Fq2(-x0.c1, x0.c0)
        b = (alpha + Fq2.ONE).pow((P - 1) // 2)
        cand = b * x0
        return cand if cand.square() == self else None


Fq2.ZERO = Fq2(0, 0)
Fq2.ONE = Fq2(1, 0)


class Fq6:
    """a + b·v + c·v^2 with v^3 = u + 1."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    ZERO: "Fq6"
    ONE: "Fq6"

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_nonresidue() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_nonresidue()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def square(self) -> "Fq6":
        return self * self

    def mul_by_nonresidue(self) -> "Fq6":
        # * v
        return Fq6(self.c2.mul_by_nonresidue(), self.c0, self.c1)

    def inverse(self) -> "Fq6":
        a, b, c = self.c0, self.c1, self.c2
        t0 = a.square() - (b * c).mul_by_nonresidue()
        t1 = c.square().mul_by_nonresidue() - a * b
        t2 = b.square() - a * c
        denom = a * t0 + (c * t1 + b * t2).mul_by_nonresidue()
        inv = denom.inverse()
        return Fq6(t0 * inv, t1 * inv, t2 * inv)

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, o: object) -> bool:
        return (
            isinstance(o, Fq6)
            and self.c0 == o.c0
            and self.c1 == o.c1
            and self.c2 == o.c2
        )

    def __hash__(self) -> int:
        return hash((self.c0, self.c1, self.c2))


Fq6.ZERO = Fq6(Fq2.ZERO, Fq2.ZERO, Fq2.ZERO)
Fq6.ONE = Fq6(Fq2.ONE, Fq2.ZERO, Fq2.ZERO)


class Fq12:
    """a + b·w with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0, self.c1 = c0, c1

    ONE: "Fq12"

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq12":
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o: "Fq12") -> "Fq12":
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        c0 = t0 + t1.mul_by_nonresidue()
        c1 = (self.c0 + self.c1) * (o.c0 + o.c1) - t0 - t1
        return Fq12(c0, c1)

    def square(self) -> "Fq12":
        # complex squaring: (c0 + c1 w)² = (c0² + v·c1²) + 2c0c1·w with
        # c0² + v·c1² = (c0 + c1)(c0 + v·c1) − t − v·t, t = c0c1
        # — 2 Fq6 multiplies instead of 3.
        t = self.c0 * self.c1
        m = (self.c0 + self.c1) * (self.c0 + self.c1.mul_by_nonresidue())
        return Fq12(m - t - t.mul_by_nonresidue(), t + t)

    def cyclotomic_square(self) -> "Fq12":
        """Granger-Scott squaring — valid ONLY for elements of the
        cyclotomic subgroup (f^(p⁴−p²+1) = 1, i.e. anything after the
        easy part of the final exponentiation).  Fq12 as Fq4[z]/(z³−y)
        with Fq4 components (c0.c0, c1.c1), (c1.c0, c0.c2),
        (c0.c1, c1.c2); ~3x cheaper than ``square`` — the exponentiation
        chain of the hard part runs almost entirely on this.
        Pinned against ``square`` on cyclotomic elements in tests."""
        z0, z4, z3 = self.c0.c0, self.c0.c1, self.c0.c2
        z2, z1, z5 = self.c1.c0, self.c1.c1, self.c1.c2

        def fq4_square(a0: Fq2, a1: Fq2) -> tuple[Fq2, Fq2]:
            # (a0 + a1 y)² with y² = u+1
            t = a0 * a1
            sq = (a0 + a1) * (a0 + a1.mul_by_nonresidue())
            return sq - t - t.mul_by_nonresidue(), t + t

        t0, t1 = fq4_square(z0, z1)
        t2, t3 = fq4_square(z2, z3)
        t4, t5 = fq4_square(z4, z5)
        # z_i' = 3·t − (±)2·z with the Granger-Scott sign pattern
        z0 = t0 + (t0 - z0) + (t0 - z0)
        z1 = t1 + (t1 + z1) + (t1 + z1)
        nr_t5 = t5.mul_by_nonresidue()
        z2 = nr_t5 + (nr_t5 + z2) + (nr_t5 + z2)
        z3 = t4 + (t4 - z3) + (t4 - z3)
        z4 = t2 + (t2 - z4) + (t2 - z4)
        z5 = t3 + (t3 + z5) + (t3 + z5)
        return Fq12(Fq6(z0, z4, z3), Fq6(z2, z1, z5))

    def conjugate(self) -> "Fq12":
        return Fq12(self.c0, -self.c1)

    def inverse(self) -> "Fq12":
        denom = (self.c0 * self.c0 - (self.c1 * self.c1).mul_by_nonresidue()).inverse()
        return Fq12(self.c0 * denom, -(self.c1 * denom))

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inverse().pow(-e)
        result = Fq12.ONE
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def frobenius(self, power: int) -> "Fq12":
        """x -> x^(p^power) via precomputed coefficients."""
        out = self
        for _ in range(power % 12):
            out = out._frobenius_once()
        return out

    def _frobenius_once(self) -> "Fq12":
        def frob2(x: Fq2) -> Fq2:
            return x.conjugate()

        c0 = Fq6(
            frob2(self.c0.c0),
            frob2(self.c0.c1) * _FROB6_C1[1],
            frob2(self.c0.c2) * _FROB6_C2[1],
        )
        c1 = Fq6(
            frob2(self.c1.c0) * _FROB12_C1[1],
            frob2(self.c1.c1) * _FROB6_C1[1] * _FROB12_C1[1],
            frob2(self.c1.c2) * _FROB6_C2[1] * _FROB12_C1[1],
        )
        return Fq12(c0, c1)

    def __eq__(self, o: object) -> bool:
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self) -> int:
        return hash((self.c0, self.c1))


# Frobenius coefficients: gamma = (u+1)^((p-1)/k) for the tower maps.
_NONRESIDUE = Fq2(1, 1)
_FROB6_C1 = [_NONRESIDUE.pow(((P**i) - 1) // 3) for i in range(2)]
_FROB6_C2 = [_NONRESIDUE.pow((2 * ((P**i) - 1)) // 3) for i in range(2)]
_FROB12_C1 = [_NONRESIDUE.pow(((P**i) - 1) // 6) for i in range(2)]

Fq12.ONE = Fq12(Fq6.ONE, Fq6.ZERO)
