"""Optimal ate pairing on BLS12-381.

Textbook Miller loop over affine G2 with line evaluations embedded into
Fq12, followed by the final exponentiation (p^12 - 1)/r computed
directly by integer exponentiation — slow but transparently correct;
bilinearity is asserted by tests (e(aP, bQ) == e(P, Q)^(ab)), which a
wrong line function or exponent cannot satisfy.

Embedding convention: G1 points (x, y) in Fq embed into Fq12 via the
towering Fq -> Fq2 -> Fq6 -> Fq12; the line function is evaluated with
the G2 (untwisted) coefficients in Fq12.
"""

from __future__ import annotations

from .curve import G1Point, G2Point
from .fields import P, R, X, Fq2, Fq6, Fq12


def _fq2_to_fq12(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2.ZERO, Fq2.ZERO), Fq6.ZERO)


# w in Fq12 (w^2 = v, v^3 = u+1); the twist maps G2 (x', y') to
# (x' / w^2, y' / w^3) on the curve over Fq12.
_W = Fq12(Fq6.ZERO, Fq6.ONE)
_W2 = _W * _W
_W3 = _W2 * _W
_W2_INV = _W2.inverse()
_W3_INV = _W3.inverse()


def _untwist(q: G2Point) -> tuple[Fq12, Fq12]:
    """G2 (over Fq2, the twist) -> point over Fq12 on the base curve."""
    x = _fq2_to_fq12(q.x) * _W2_INV
    y = _fq2_to_fq12(q.y) * _W3_INV
    return x, y


def _fq_to_fq12(a: int) -> Fq12:
    return _fq2_to_fq12(Fq2(a, 0))


def _line(px: Fq12, py: Fq12, qx: Fq12, qy: Fq12, rx: Fq12, ry: Fq12) -> Fq12:
    """Evaluate at (rx, ry) the line through (px, py) and (qx, qy)
    (tangent when the points coincide)."""
    if px == qx and py == qy:
        # tangent: slope = 3x^2 / 2y  (curve a-coefficient is 0)
        three = _fq_to_fq12(3)
        two = _fq_to_fq12(2)
        lam = three * px * px * (two * py).inverse()
    elif px == qx:
        # vertical line
        return rx - px
    else:
        lam = (qy - py) * (qx - px).inverse()
    return ry - py - lam * (rx - px)


def miller_loop(p: G1Point, q: G2Point) -> Fq12:
    if p.inf or q.inf:
        return Fq12.ONE
    px, py = _fq_to_fq12(p.x), _fq_to_fq12(p.y)
    qx, qy = _untwist(q)

    t = abs(X)
    bits = bin(t)[3:]  # skip the leading 1
    f = Fq12.ONE
    rx, ry = qx, qy
    for bit in bits:
        f = f * f * _line(rx, ry, rx, ry, px, py)
        # R = 2R (on the Fq12 curve)
        lam = _fq_to_fq12(3) * rx * rx * (_fq_to_fq12(2) * ry).inverse()
        new_x = lam * lam - rx - rx
        new_y = lam * (rx - new_x) - ry
        rx, ry = new_x, new_y
        if bit == "1":
            f = f * _line(rx, ry, qx, qy, px, py)
            if rx == qx and ry == qy:
                lam = _fq_to_fq12(3) * rx * rx * (_fq_to_fq12(2) * ry).inverse()
            else:
                lam = (qy - ry) * (qx - rx).inverse()
            new_x = lam * lam - rx - qx
            new_y = lam * (rx - new_x) - ry
            rx, ry = new_x, new_y
    if X < 0:
        f = f.conjugate()  # f^(p^6) inverts the exponent cheaply
    return f


def final_exponentiation(f: Fq12) -> Fq12:
    return f.pow((P**12 - 1) // R)


def pairing(p: G1Point, q: G2Point) -> Fq12:
    """e(P, Q): bilinear, non-degenerate on (G1, G2)."""
    return final_exponentiation(miller_loop(p, q))


def pairings_equal(
    p1: G1Point, q1: G2Point, p2: G1Point, q2: G2Point
) -> bool:
    """e(P1, Q1) == e(P2, Q2) via one product: e(P1,Q1)·e(-P2,Q2) == 1 —
    shares the final exponentiation between the two Miller loops."""
    f = miller_loop(p1, q1) * miller_loop(-p2, q2)
    return final_exponentiation(f) == Fq12.ONE
