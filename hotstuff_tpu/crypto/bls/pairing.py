"""Optimal ate pairing on BLS12-381 — performance-structured.

The round-1 version was a transparently-correct textbook loop (affine
arithmetic lifted into Fq12, final exponentiation by the full 4314-bit
integer) at ~2.6 s per pairing equality — unusable on a live consensus
path.  This rewrite keeps the identical tower and conventions but uses
the standard performance structure (the same shape every production
BLS12-381 library uses — e.g. the zkcrypto/blst Miller loop):

- **Miller loop on the twist**: the running point stays in affine Fq2
  coordinates on E'; each step's line function is evaluated directly in
  the sparse form ``l·w³ = (λ·xT − yT) + (−λ·xP)·v + yP·(v·w)`` (three
  non-zero Fq2 slots out of six), multiplied into the accumulator with
  an 18-mul sparse product instead of a full 54-mul Fq12 multiply.  The
  stray ``w³`` factor per line is legitimate: ``w^((p¹²−1)/r) = 1``
  (checked numerically), so the final exponentiation kills every
  monomial in ``w``.
- **Final exponentiation by the BLS12 addition chain**: easy part
  ``f^((p⁶−1)(p²+1))`` via one conjugate, one inverse and one double
  Frobenius; hard part via the standard parameter chain
  ``(x−1)²·(x+p)·(x²+p²−1) + 3  =  3·(p⁴−p²+1)/r``
  (verified exactly), i.e. five exponentiations by the 64-bit |x|
  instead of one by a 4314-bit integer.  After the easy part the value
  lies in the cyclotomic subgroup, where inversion is conjugation —
  the negative parameter costs nothing.

The computed value is therefore ``e(P,Q)³`` — a fixed cube of the ate
pairing.  Since gcd(3, r) = 1, g ↦ g³ is a bijection of the r-order
target group: the cube is itself a non-degenerate bilinear pairing, and
every protocol use (equality of pairings, bilinearity) is unaffected.
Tests pin this against the retained textbook oracle
(``pairing_textbook(P,Q)³ == pairing(P,Q)``).

Measured (this host): pairing equality 2.6 s → ~40 ms (one Miller loop
~12 ms; the shared final exponentiation ~15 ms; G1/G2 decompression and
hash-to-curve account for the rest of a signature verify).

Reference boundary this backend slots behind: the SignatureService /
verify path of crypto/src/lib.rs:186-257 (BASELINE config 5).
"""

from __future__ import annotations

from .curve import G1Point, G2Point
from .fields import P, R, X, Fq2, Fq6, Fq12

# -- sparse Fq12 accumulation ------------------------------------------------


def _mul_sparse_014(f: Fq12, a: Fq2, b: Fq2, c: Fq2) -> Fq12:
    """f · (a + b·v + c·v·w)  — the line-evaluation shape.

    With f = f0 + f1·w (f_i in Fq6) and s = s0 + s1·w where s0 = a + b·v
    and s1 = c·v:  f·s = (f0·s0 + f1·s1·v) + (f0·s1 + f1·s0)·w.
    Each sparse Fq6 product costs 6 (two-term) or 3 (one-term) Fq2 muls:
    18 total vs 54 for a generic Fq12 multiply.
    """
    f00, f01, f02 = f.c0.c0, f.c0.c1, f.c0.c2
    f10, f11, f12 = f.c1.c0, f.c1.c1, f.c1.c2

    def mul_ab(x0: Fq2, x1: Fq2, x2: Fq2) -> tuple[Fq2, Fq2, Fq2]:
        # (x0 + x1 v + x2 v²)(a + b v), v³ = u+1
        return (
            x0 * a + (x2 * b).mul_by_nonresidue(),
            x0 * b + x1 * a,
            x1 * b + x2 * a,
        )

    def mul_c(x0: Fq2, x1: Fq2, x2: Fq2) -> tuple[Fq2, Fq2, Fq2]:
        # (x0 + x1 v + x2 v²)(c v)
        return ((x2 * c).mul_by_nonresidue(), x0 * c, x1 * c)

    p00, p01, p02 = mul_ab(f00, f01, f02)  # f0·s0
    q0, q1, q2 = mul_c(f10, f11, f12)  # f1·s1
    # f1·s1·v : rotate with nonresidue
    r0, r1, r2 = q2.mul_by_nonresidue(), q0, q1
    c0 = Fq6(p00 + r0, p01 + r1, p02 + r2)

    s00, s01, s02 = mul_c(f00, f01, f02)  # f0·s1
    t0, t1, t2 = mul_ab(f10, f11, f12)  # f1·s0
    c1 = Fq6(s00 + t0, s01 + t1, s02 + t2)
    return Fq12(c0, c1)


# -- Miller loop -------------------------------------------------------------

_X_ABS_BITS = bin(abs(X))[3:]  # MSB-first, leading 1 skipped


def miller_loop(p: G1Point, q: G2Point) -> Fq12:
    """Accumulated (scaled) Miller value f_{|x|,Q}(P).

    The running point T stays in Jacobian coordinates on the twist
    (x = X/Z², y = Y/Z³) so the loop does ZERO field inversions — a
    381-bit modular inversion costs ~335 µs in Python (measured), which
    at one per step was over half the loop.  Each line is scaled by its
    projective denominator, an Fq2 factor; like the w³ embedding factor,
    anything in a proper subfield dies under the final exponentiation.

    Tangent at T, evaluated at P, scaled by 2YZ³:
      a = 3X³ − 2Y²,  b = −3X²Z²·xP,  c = 2YZ³·yP
    Chord through T and affine Q, scaled by Z³·D (D = xq·Z² − X):
      N = yq·Z³ − Y
      a = N·X − Y·D,  b = −N·Z²·xP,  c = Z³·D·yP
    """
    if p.inf or q.inf:
        return Fq12.ONE
    from .curve import _FQ2_OPS, _jac_add, _jac_double

    xp, yp = p.x, p.y
    xq, yq = q.x, q.y  # Fq2, twist affine
    q_jac = (xq, yq, Fq2.ONE)
    T = q_jac
    f = Fq12.ONE
    for bit in _X_ABS_BITS:
        Xt, Yt, Zt = T
        X2 = Xt.square()
        Y2 = Yt.square()
        Z2 = Zt.square()
        Z3 = Zt * Z2
        line_a = (Xt * X2).mul_int(3) - Y2 - Y2
        line_b = -((X2.mul_int(3) * Z2).mul_int(xp))
        line_c = ((Yt + Yt) * Z3).mul_int(yp)
        f = f.square()
        f = _mul_sparse_014(f, line_a, line_b, line_c)
        T = _jac_double(T, _FQ2_OPS)
        if bit == "1":
            Xt, Yt, Zt = T
            Z2 = Zt.square()
            Z3 = Zt * Z2
            n = yq * Z3 - Yt
            d = xq * Z2 - Xt
            line_a = n * Xt - Yt * d
            line_b = -((n * Z2).mul_int(xp))
            line_c = (Z3 * d).mul_int(yp)
            f = _mul_sparse_014(f, line_a, line_b, line_c)
            T = _jac_add(T, q_jac, _FQ2_OPS)
    if X < 0:
        f = f.conjugate()  # f^(p^6) inverts the exponent cheaply
    return f


# -- final exponentiation ----------------------------------------------------


def _pow_abs_x(f: Fq12) -> Fq12:
    """f^|x| by square-and-multiply (|x| is 64 bits, weight 6).  Callers
    only pass cyclotomic elements (post-easy-part), so the chain runs on
    Granger-Scott squarings."""
    result = f
    for bit in _X_ABS_BITS:
        result = result.cyclotomic_square()
        if bit == "1":
            result = result * f
    return result


def _pow_x(f: Fq12) -> Fq12:
    """f^x for the (negative) BLS parameter; f must be cyclotomic so
    that conjugation is inversion."""
    out = _pow_abs_x(f)
    return out.conjugate() if X < 0 else out


def final_exponentiation(f: Fq12) -> Fq12:
    """f^(3·(p¹²−1)/r) via easy part + the BLS12 parameter chain.

    Hard-part identity (verified exactly against the integers):
    (x−1)²·(x+p)·(x²+p²−1) + 3 = 3·(p⁴−p²+1)/r.
    """
    # easy part: f^((p^6−1)(p^2+1)) — lands in the cyclotomic subgroup
    t = f.conjugate() * f.inverse()  # f^(p^6 − 1)
    f = t.frobenius(2) * t  # ^(p^2 + 1)
    # hard part: ^((x−1)²(x+p)(x²+p²−1)) · f³
    t1 = _pow_x(f) * f.conjugate()  # f^(x−1)
    t1 = _pow_x(t1) * t1.conjugate()  # ^(x−1)²
    t2 = _pow_x(t1) * t1.frobenius(1)  # ^(x+p)
    t3 = _pow_x(_pow_x(t2))  # ^x²
    t3 = t3 * t2.frobenius(2) * t2.conjugate()  # ^(x²+p²−1)
    return t3 * f.square() * f  # · f³


def pairing(p: G1Point, q: G2Point) -> Fq12:
    """e(P, Q)³: a fixed cube of the optimal ate pairing — bilinear and
    non-degenerate (3 is invertible mod r)."""
    return final_exponentiation(miller_loop(p, q))


def pairings_equal(
    p1: G1Point, q1: G2Point, p2: G1Point, q2: G2Point
) -> bool:
    """e(P1, Q1) == e(P2, Q2) via one product: e(P1,Q1)·e(-P2,Q2) == 1 —
    shares the final exponentiation between the two Miller loops (the
    fixed cube preserves the equality: g³ = 1 ⇔ g = 1 in the r-group)."""
    f = miller_loop(p1, q1) * miller_loop(-p2, q2)
    return final_exponentiation(f) == Fq12.ONE


# -- textbook oracle (round-1 implementation, kept for tests) ----------------


def _fq2_to_fq12(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2.ZERO, Fq2.ZERO), Fq6.ZERO)


def _fq_to_fq12(a: int) -> Fq12:
    return _fq2_to_fq12(Fq2(a, 0))


_W = Fq12(Fq6.ZERO, Fq6.ONE)
_W2_INV = (_W * _W).inverse()
_W3_INV = (_W * _W * _W).inverse()


def _miller_loop_textbook(p: G1Point, q: G2Point) -> Fq12:
    """Round-1 textbook loop: affine arithmetic lifted into Fq12 with the
    exact (unscaled) line values — the correctness oracle for tests."""
    if p.inf or q.inf:
        return Fq12.ONE

    def line(px, py, qx, qy, rx, ry):
        if px == qx and py == qy:
            lam = _fq_to_fq12(3) * px * px * (_fq_to_fq12(2) * py).inverse()
        elif px == qx:
            return rx - px
        else:
            lam = (qy - py) * (qx - px).inverse()
        return ry - py - lam * (rx - px)

    px, py = _fq_to_fq12(p.x), _fq_to_fq12(p.y)
    qx = _fq2_to_fq12(q.x) * _W2_INV
    qy = _fq2_to_fq12(q.y) * _W3_INV
    f = Fq12.ONE
    rx, ry = qx, qy
    for bit in _X_ABS_BITS:
        f = f * f * line(rx, ry, rx, ry, px, py)
        lam = _fq_to_fq12(3) * rx * rx * (_fq_to_fq12(2) * ry).inverse()
        new_x = lam * lam - rx - rx
        new_y = lam * (rx - new_x) - ry
        rx, ry = new_x, new_y
        if bit == "1":
            f = f * line(rx, ry, qx, qy, px, py)
            if rx == qx and ry == qy:
                lam = _fq_to_fq12(3) * rx * rx * (_fq_to_fq12(2) * ry).inverse()
            else:
                lam = (qy - ry) * (qx - rx).inverse()
            new_x = lam * lam - rx - qx
            new_y = lam * (rx - new_x) - ry
            rx, ry = new_x, new_y
    if X < 0:
        f = f.conjugate()
    return f


def pairing_textbook(p: G1Point, q: G2Point) -> Fq12:
    """Exact e(P, Q) by the round-1 method (slow; tests only)."""
    return _miller_loop_textbook(p, q).pow((P**12 - 1) // R)
