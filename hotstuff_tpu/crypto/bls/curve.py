"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2).

G1: y^2 = x^3 + 4         over Fq,  order R, cofactor H1.
G2: y^2 = x^3 + 4(u + 1)  over Fq2, order R, cofactor H2.

The public API is affine (points compare and serialize by affine
coordinates, matching the wire formats), but all scalar multiplication
and multi-point accumulation run in Jacobian coordinates internally —
one field inversion per *operation* instead of one per *point addition*
(the round-1 affine ladder cost ~500 modular inversions per scalar
multiply, ~700 ms; Jacobian is ~1-3 ms).  The same generic ladder
serves both fields: the coordinate ops are passed in as closures.

Round-1 bug fixed here: ``mul`` reduces its scalar mod R, so the
serialization subgroup check ``pt.mul(R)`` was a no-op (mul(0) — every
on-curve point passed).  Subgroup and cofactor multiplications now use
the unreduced ``_mul_raw``, and the check is pinned by a test with an
on-curve point outside the r-torsion (tests/test_bls.py).
"""

from __future__ import annotations

import hashlib

from .fields import P, R, Fq2, fq_inv

H1 = 0x396C8C005555E1568C00AAAB0000AAAB
H2 = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

# Standard generators (RFC 9380 / zkcrypto test vectors).
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)


# -- generic Jacobian ladder -------------------------------------------------
#
# A point is (X, Y, Z); Z "zero" means the identity.  The element ops are
# injected per field: (mul, sqr, red, inv, is_zero, one, zero).


class _Ops:
    __slots__ = ("mul", "sqr", "red", "inv", "is_zero", "one")

    def __init__(self, mul, sqr, red, inv, is_zero, one):
        self.mul, self.sqr, self.red = mul, sqr, red
        self.inv, self.is_zero, self.one = inv, is_zero, one


_FQ_OPS = _Ops(
    mul=lambda a, b: a * b % P,
    sqr=lambda a: a * a % P,
    red=lambda a: a % P,
    inv=fq_inv,
    is_zero=lambda a: a % P == 0,
    one=1,
)

_FQ2_OPS = _Ops(
    mul=lambda a, b: a * b,
    sqr=lambda a: a.square(),
    red=lambda a: a,
    inv=lambda a: a.inverse(),
    is_zero=lambda a: a.is_zero(),
    one=Fq2.ONE,
)


def _jac_double(pt, o: _Ops):
    X1, Y1, Z1 = pt
    if o.is_zero(Z1) or o.is_zero(Y1):
        return pt if o.is_zero(Z1) else (X1, Y1, Z1 - Z1)  # 2-torsion → ∞
    A = o.sqr(X1)
    B = o.sqr(Y1)
    C = o.sqr(B)
    t = o.sqr(X1 + B) - A - C
    D = o.red(t + t)
    E = o.red(A + A + A)
    F = o.sqr(E)
    X3 = o.red(F - D - D)
    Y3 = o.red(o.mul(E, D - X3) - (C + C + C + C + C + C + C + C))
    Z3 = o.mul(Y1 + Y1, Z1)
    return (X3, Y3, Z3)


def _jac_add(p1, p2, o: _Ops):
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    if o.is_zero(Z1):
        return p2
    if o.is_zero(Z2):
        return p1
    Z1Z1 = o.sqr(Z1)
    Z2Z2 = o.sqr(Z2)
    U1 = o.mul(X1, Z2Z2)
    U2 = o.mul(X2, Z1Z1)
    S1 = o.mul(o.mul(Y1, Z2), Z2Z2)
    S2 = o.mul(o.mul(Y2, Z1), Z1Z1)
    H = o.red(U2 - U1)
    rr = o.red(S2 - S1)
    if o.is_zero(H):
        if o.is_zero(rr):
            return _jac_double(p1, o)
        return (o.one, o.one, U1 - U1)  # P + (−P) = ∞ (zero Z)
    I = o.sqr(H + H)
    J = o.mul(H, I)
    rr = rr + rr
    V = o.mul(U1, I)
    X3 = o.red(o.sqr(rr) - J - V - V)
    S1J = o.mul(S1, J)
    Y3 = o.red(o.mul(rr, V - X3) - S1J - S1J)
    Z3 = o.mul(o.red(o.sqr(Z1 + Z2) - Z1Z1 - Z2Z2), H)
    return (X3, Y3, Z3)


def _jac_mul(affine_xy, k: int, o: _Ops):
    """k·P for affine P, k >= 0 unreduced; returns a Jacobian triple."""
    x, y = affine_xy
    inf = (o.one, o.one, x - x)  # zero Z
    if k == 0:
        return inf
    base = (x, y, o.one)
    acc = inf
    for bit in bin(k)[2:]:
        acc = _jac_double(acc, o)
        if bit == "1":
            acc = _jac_add(acc, base, o)
    return acc


def _jac_sum(points_affine, o: _Ops):
    """Σ points (affine list) as a Jacobian triple — one tree-free
    left-fold; each step is a full Jacobian add (no inversions)."""
    if not points_affine:
        return (o.one, o.one, o.one - o.one)
    acc = (points_affine[0][0], points_affine[0][1], o.one)
    for x, y in points_affine[1:]:
        acc = _jac_add(acc, (x, y, o.one), o)
    return acc


def _jac_to_affine(pt, o: _Ops):
    """(x, y) or None for the identity."""
    X, Y, Z = pt
    if o.is_zero(Z):
        return None
    zi = o.inv(o.red(Z))
    zi2 = o.sqr(zi)
    return (o.mul(X, zi2), o.mul(o.mul(Y, zi), zi2))


class G1Point:
    """Affine G1 point; ``inf`` = identity."""

    __slots__ = ("x", "y", "inf")

    def __init__(self, x: int = 0, y: int = 0, inf: bool = False):
        self.x = x % P
        self.y = y % P
        self.inf = inf

    @classmethod
    def identity(cls) -> "G1Point":
        return cls(0, 0, True)

    @classmethod
    def generator(cls) -> "G1Point":
        return cls(G1_X, G1_Y)

    def is_on_curve(self) -> bool:
        if self.inf:
            return True
        return (self.y * self.y - self.x**3 - 4) % P == 0

    def __eq__(self, o: object) -> bool:
        if not isinstance(o, G1Point):
            return NotImplemented
        if self.inf or o.inf:
            return self.inf == o.inf
        return self.x == o.x and self.y == o.y

    def __hash__(self) -> int:
        return hash((self.x, self.y, self.inf))

    def __neg__(self) -> "G1Point":
        if self.inf:
            return self
        return G1Point(self.x, -self.y)

    def __add__(self, o: "G1Point") -> "G1Point":
        if self.inf:
            return o
        if o.inf:
            return self
        if self.x == o.x:
            if (self.y + o.y) % P == 0:
                return G1Point.identity()
            lam = (3 * self.x * self.x) * fq_inv(2 * self.y) % P
        else:
            lam = (o.y - self.y) * fq_inv(o.x - self.x) % P
        x3 = (lam * lam - self.x - o.x) % P
        y3 = (lam * (self.x - x3) - self.y) % P
        return G1Point(x3, y3)

    def _from_jac(self, jac) -> "G1Point":
        aff = _jac_to_affine(jac, _FQ_OPS)
        return G1Point.identity() if aff is None else G1Point(aff[0], aff[1])

    def _mul_raw(self, k: int) -> "G1Point":
        """k·P with the scalar taken as-is (cofactor clearing, subgroup
        checks — where reducing mod R would be wrong)."""
        if self.inf or k == 0:
            return G1Point.identity()
        return self._from_jac(_jac_mul((self.x, self.y), k, _FQ_OPS))

    def mul(self, k: int) -> "G1Point":
        return self._mul_raw(k % R)

    def mul_by_cofactor(self) -> "G1Point":
        return self._mul_raw(H1)

    def in_subgroup(self) -> bool:
        return self._mul_raw(R).inf

    @classmethod
    def sum(cls, points: list["G1Point"]) -> "G1Point":
        """Σ points without per-addition inversions (aggregation path)."""
        affs = [(q.x, q.y) for q in points if not q.inf]
        if not affs:
            return cls.identity()
        aff = _jac_to_affine(_jac_sum(affs, _FQ_OPS), _FQ_OPS)
        return cls.identity() if aff is None else cls(aff[0], aff[1])

    # -- serialization (zcash/ietf compressed format, 48 bytes) -------------

    def to_bytes(self) -> bytes:
        if self.inf:
            return bytes([0xC0] + [0] * 47)
        flag = 0x80 | (0x20 if self.y > (P - 1) // 2 else 0)
        out = bytearray(self.x.to_bytes(48, "big"))
        out[0] |= flag
        return bytes(out)

    @classmethod
    def from_bytes(
        cls, data: bytes, subgroup_check: bool = True
    ) -> "G1Point | None":
        """``subgroup_check=False`` skips the r-torsion ladder (~2 ms) —
        ONLY for points whose membership is established elsewhere, e.g.
        vote signatures that are summed and checked once per aggregate
        (``BlsVerifier.verify_shared_msg``)."""
        if len(data) != 48 or not data[0] & 0x80:
            return None
        if data[0] & 0x40:  # infinity
            if data[0] != 0xC0 or any(data[1:]):
                return None
            return cls.identity()
        sign = bool(data[0] & 0x20)
        x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
        if x >= P:
            return None
        y2 = (x**3 + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P != y2:
            return None
        if (y > (P - 1) // 2) != sign:
            y = P - y
        pt = cls(x, y)
        if subgroup_check and not pt.in_subgroup():
            return None
        return pt


class G2Point:
    """Affine G2 point over Fq2."""

    __slots__ = ("x", "y", "inf")

    def __init__(self, x: Fq2 = Fq2.ZERO, y: Fq2 = Fq2.ZERO, inf: bool = False):
        self.x, self.y, self.inf = x, y, inf

    @classmethod
    def identity(cls) -> "G2Point":
        return cls(Fq2.ZERO, Fq2.ZERO, True)

    @classmethod
    def generator(cls) -> "G2Point":
        return cls(Fq2(*G2_X), Fq2(*G2_Y))

    B2 = None  # set below: 4(u+1)

    def is_on_curve(self) -> bool:
        if self.inf:
            return True
        return self.y.square() == self.x.square() * self.x + G2Point.B2

    def __eq__(self, o: object) -> bool:
        if not isinstance(o, G2Point):
            return NotImplemented
        if self.inf or o.inf:
            return self.inf == o.inf
        return self.x == o.x and self.y == o.y

    def __hash__(self) -> int:
        return hash((self.x, self.y, self.inf))

    def __neg__(self) -> "G2Point":
        if self.inf:
            return self
        return G2Point(self.x, -self.y)

    def __add__(self, o: "G2Point") -> "G2Point":
        if self.inf:
            return o
        if o.inf:
            return self
        if self.x == o.x:
            if (self.y + o.y).is_zero():
                return G2Point.identity()
            lam = (self.x.square().mul_int(3)) * (self.y.mul_int(2)).inverse()
        else:
            lam = (o.y - self.y) * (o.x - self.x).inverse()
        x3 = lam.square() - self.x - o.x
        y3 = lam * (self.x - x3) - self.y
        return G2Point(x3, y3)

    def _from_jac(self, jac) -> "G2Point":
        aff = _jac_to_affine(jac, _FQ2_OPS)
        return G2Point.identity() if aff is None else G2Point(aff[0], aff[1])

    def _mul_raw(self, k: int) -> "G2Point":
        if self.inf or k == 0:
            return G2Point.identity()
        return self._from_jac(_jac_mul((self.x, self.y), k, _FQ2_OPS))

    def mul(self, k: int) -> "G2Point":
        return self._mul_raw(k % R)

    def in_subgroup(self) -> bool:
        return self._mul_raw(R).inf

    @classmethod
    def sum(cls, points: list["G2Point"]) -> "G2Point":
        affs = [(q.x, q.y) for q in points if not q.inf]
        if not affs:
            return cls.identity()
        aff = _jac_to_affine(_jac_sum(affs, _FQ2_OPS), _FQ2_OPS)
        return cls.identity() if aff is None else cls(aff[0], aff[1])

    # -- serialization (compressed, 96 bytes) --------------------------------

    def to_bytes(self) -> bytes:
        if self.inf:
            return bytes([0xC0] + [0] * 95)
        # lexicographic "greater" on (c1, c0)
        great = self.y.c1 > (P - 1) // 2 or (
            self.y.c1 == 0 and self.y.c0 > (P - 1) // 2
        )
        flag = 0x80 | (0x20 if great else 0)
        out = bytearray(
            self.x.c1.to_bytes(48, "big") + self.x.c0.to_bytes(48, "big")
        )
        out[0] |= flag
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "G2Point | None":
        if len(data) != 96 or not data[0] & 0x80:
            return None
        if data[0] & 0x40:
            if data[0] != 0xC0 or any(data[1:]):
                return None
            return cls.identity()
        sign = bool(data[0] & 0x20)
        x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:], "big")
        if x0 >= P or x1 >= P:
            return None
        x = Fq2(x0, x1)
        y2 = x.square() * x + G2Point.B2
        y = y2.sqrt()
        if y is None:
            return None
        great = y.c1 > (P - 1) // 2 or (y.c1 == 0 and y.c0 > (P - 1) // 2)
        if great != sign:
            y = -y
        pt = cls(x, y)
        if not pt.in_subgroup():
            return None
        return pt


G2Point.B2 = Fq2(4, 4)


def hash_to_g1(message: bytes, dst: bytes = b"HOTSTUFF_TPU_BLS_G1") -> G1Point:
    """Hash-and-check map to G1 with cofactor clearing.

    Deliberately NOT RFC 9380 SSWU (this backend has no external interop
    requirement); deterministic try-and-increment over SHA-256 counters,
    which is uniform enough for the signature scheme's security argument
    as long as all parties use the same map — they do, it ships with the
    framework.
    """
    counter = 0
    while True:
        h = hashlib.sha256(dst + counter.to_bytes(4, "big") + message).digest()
        x = int.from_bytes(h + hashlib.sha256(b"x2" + h).digest()[:16], "big") % P
        y2 = (x**3 + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P == y2:
            # pick the "even" root deterministically, then clear cofactor
            if y > (P - 1) // 2:
                y = P - y
            return G1Point(x, y).mul_by_cofactor()
        counter += 1
