"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2).

G1: y^2 = x^3 + 4         over Fq,  order R, cofactor H1.
G2: y^2 = x^3 + 4(u + 1)  over Fq2, order R, cofactor H2.

Affine coordinates with Python big ints — clarity over speed; this is
the CPU reference backend (the hot path for consensus is Ed25519 on the
TPU; BLS is the threshold variant, BASELINE config 5).
"""

from __future__ import annotations

import hashlib

from .fields import P, R, Fq2, fq_inv

H1 = 0x396C8C005555E1568C00AAAB0000AAAB
H2 = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

# Standard generators (RFC 9380 / zkcrypto test vectors).
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)


class G1Point:
    """Affine G1 point; None coordinates = identity."""

    __slots__ = ("x", "y", "inf")

    def __init__(self, x: int = 0, y: int = 0, inf: bool = False):
        self.x = x % P
        self.y = y % P
        self.inf = inf

    @classmethod
    def identity(cls) -> "G1Point":
        return cls(0, 0, True)

    @classmethod
    def generator(cls) -> "G1Point":
        return cls(G1_X, G1_Y)

    def is_on_curve(self) -> bool:
        if self.inf:
            return True
        return (self.y * self.y - self.x**3 - 4) % P == 0

    def __eq__(self, o: object) -> bool:
        if not isinstance(o, G1Point):
            return NotImplemented
        if self.inf or o.inf:
            return self.inf == o.inf
        return self.x == o.x and self.y == o.y

    def __hash__(self) -> int:
        return hash((self.x, self.y, self.inf))

    def __neg__(self) -> "G1Point":
        if self.inf:
            return self
        return G1Point(self.x, -self.y)

    def __add__(self, o: "G1Point") -> "G1Point":
        if self.inf:
            return o
        if o.inf:
            return self
        if self.x == o.x:
            if (self.y + o.y) % P == 0:
                return G1Point.identity()
            # doubling
            lam = (3 * self.x * self.x) * fq_inv(2 * self.y) % P
        else:
            lam = (o.y - self.y) * fq_inv(o.x - self.x) % P
        x3 = (lam * lam - self.x - o.x) % P
        y3 = (lam * (self.x - x3) - self.y) % P
        return G1Point(x3, y3)

    def mul(self, k: int) -> "G1Point":
        k %= R
        result = G1Point.identity()
        add = self
        while k > 0:
            if k & 1:
                result = result + add
            add = add + add
            k >>= 1
        return result

    # -- serialization (zcash/ietf compressed format, 48 bytes) -------------

    def to_bytes(self) -> bytes:
        if self.inf:
            return bytes([0xC0] + [0] * 47)
        flag = 0x80 | (0x20 if self.y > (P - 1) // 2 else 0)
        out = bytearray(self.x.to_bytes(48, "big"))
        out[0] |= flag
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "G1Point | None":
        if len(data) != 48 or not data[0] & 0x80:
            return None
        if data[0] & 0x40:  # infinity
            if data[0] != 0xC0 or any(data[1:]):
                return None
            return cls.identity()
        sign = bool(data[0] & 0x20)
        x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
        if x >= P:
            return None
        y2 = (x**3 + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P != y2:
            return None
        if (y > (P - 1) // 2) != sign:
            y = P - y
        pt = cls(x, y)
        # subgroup check
        if not pt.mul(R).inf:
            return None
        return pt


class G2Point:
    """Affine G2 point over Fq2."""

    __slots__ = ("x", "y", "inf")

    def __init__(self, x: Fq2 = Fq2.ZERO, y: Fq2 = Fq2.ZERO, inf: bool = False):
        self.x, self.y, self.inf = x, y, inf

    @classmethod
    def identity(cls) -> "G2Point":
        return cls(Fq2.ZERO, Fq2.ZERO, True)

    @classmethod
    def generator(cls) -> "G2Point":
        return cls(Fq2(*G2_X), Fq2(*G2_Y))

    B2 = None  # set below: 4(u+1)

    def is_on_curve(self) -> bool:
        if self.inf:
            return True
        return self.y.square() == self.x.square() * self.x + G2Point.B2

    def __eq__(self, o: object) -> bool:
        if not isinstance(o, G2Point):
            return NotImplemented
        if self.inf or o.inf:
            return self.inf == o.inf
        return self.x == o.x and self.y == o.y

    def __hash__(self) -> int:
        return hash((self.x, self.y, self.inf))

    def __neg__(self) -> "G2Point":
        if self.inf:
            return self
        return G2Point(self.x, -self.y)

    def __add__(self, o: "G2Point") -> "G2Point":
        if self.inf:
            return o
        if o.inf:
            return self
        if self.x == o.x:
            if (self.y + o.y).is_zero():
                return G2Point.identity()
            lam = (self.x.square().mul_int(3)) * (self.y.mul_int(2)).inverse()
        else:
            lam = (o.y - self.y) * (o.x - self.x).inverse()
        x3 = lam.square() - self.x - o.x
        y3 = lam * (self.x - x3) - self.y
        return G2Point(x3, y3)

    def mul(self, k: int) -> "G2Point":
        k %= R
        result = G2Point.identity()
        add = self
        while k > 0:
            if k & 1:
                result = result + add
            add = add + add
            k >>= 1
        return result

    # -- serialization (compressed, 96 bytes) --------------------------------

    def to_bytes(self) -> bytes:
        if self.inf:
            return bytes([0xC0] + [0] * 95)
        # lexicographic "greater" on (c1, c0)
        great = self.y.c1 > (P - 1) // 2 or (
            self.y.c1 == 0 and self.y.c0 > (P - 1) // 2
        )
        flag = 0x80 | (0x20 if great else 0)
        out = bytearray(
            self.x.c1.to_bytes(48, "big") + self.x.c0.to_bytes(48, "big")
        )
        out[0] |= flag
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "G2Point | None":
        if len(data) != 96 or not data[0] & 0x80:
            return None
        if data[0] & 0x40:
            if data[0] != 0xC0 or any(data[1:]):
                return None
            return cls.identity()
        sign = bool(data[0] & 0x20)
        x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:], "big")
        if x0 >= P or x1 >= P:
            return None
        x = Fq2(x0, x1)
        y2 = x.square() * x + G2Point.B2
        y = y2.sqrt()
        if y is None:
            return None
        great = y.c1 > (P - 1) // 2 or (y.c1 == 0 and y.c0 > (P - 1) // 2)
        if great != sign:
            y = -y
        pt = cls(x, y)
        if not pt.mul(R).inf:
            return None
        return pt


G2Point.B2 = Fq2(4, 4)


def hash_to_g1(message: bytes, dst: bytes = b"HOTSTUFF_TPU_BLS_G1") -> G1Point:
    """Hash-and-check map to G1 with cofactor clearing.

    Deliberately NOT RFC 9380 SSWU (this backend has no external interop
    requirement); deterministic try-and-increment over SHA-256 counters,
    which is uniform enough for the signature scheme's security argument
    as long as all parties use the same map — they do, it ships with the
    framework.
    """
    counter = 0
    while True:
        h = hashlib.sha256(dst + counter.to_bytes(4, "big") + message).digest()
        x = int.from_bytes(h + hashlib.sha256(b"x2" + h).digest()[:16], "big") % P
        y2 = (x**3 + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P == y2:
            # pick the "even" root deterministically, then clear cofactor
            if y > (P - 1) // 2:
                y = P - y
            return G1Point(x, y).mul_by_cofactor()
        counter += 1


def _mul_any(pt: G1Point, k: int) -> G1Point:
    result = G1Point.identity()
    add = pt
    while k > 0:
        if k & 1:
            result = result + add
        add = add + add
        k >>= 1
    return result


def _mul_by_cofactor(self: G1Point) -> G1Point:
    return _mul_any(self, H1)


G1Point.mul_by_cofactor = _mul_by_cofactor  # type: ignore[attr-defined]
