"""BLS backend behind the framework's verifier/signing boundaries.

Mirrors the Ed25519 ``VerifierBackend`` protocol
(hotstuff_tpu/crypto/service.py) over BLS12-381 keys (96-byte G2
pubkeys) and signatures (48-byte G1 points), and adds what only BLS can
offer: constant-cost shared-message verification via signature
aggregation — ``verify_shared_msg`` does ONE pairing equality however
many votes are in the QC, instead of a batch over 2f+1 Ed25519
signatures.

Drop-in point (reference parity): the SignatureService boundary at
crypto/src/lib.rs:232-257; BASELINE config 5's threshold variant uses
``split_secret``/``combine_partials`` from the package root.
"""

from __future__ import annotations

import asyncio

from . import (
    BlsPublicKey,
    BlsSecretKey,
    BlsSignature,
    aggregate_public_keys,
    aggregate_signatures,
    keygen,
)


class BlsVerifier:
    """VerifierBackend over BLS bytes; caches decoded public keys.

    ``aggregator="tpu"`` runs the G1 signature sum on device
    (hotstuff_tpu/tpu/bls.py — the psum-shaped reduction of
    docs/BLS_TPU_DESIGN.md); the pairing equality stays on the host in
    both modes, one constant-cost call per QC."""

    name = "bls-cpu"

    def __init__(self, aggregator: str = "cpu"):
        self._pk_cache: dict[bytes, BlsPublicKey | None] = {}
        self._tpu_agg = None
        if aggregator == "tpu":
            from ...tpu.bls import TpuG1Aggregator

            self._tpu_agg = TpuG1Aggregator()
            self.name = "bls-tpu"

    def _pk(self, pk_bytes: bytes) -> BlsPublicKey | None:
        if pk_bytes not in self._pk_cache:
            self._pk_cache[pk_bytes] = BlsPublicKey.from_bytes(pk_bytes)
        return self._pk_cache[pk_bytes]

    def precompute(self, pubkeys: list[bytes]) -> None:
        for pk in pubkeys:
            self._pk(pk)

    def verify_one(self, digest, pk, sig) -> bool:
        pk_b = pk if isinstance(pk, bytes) else pk.to_bytes()
        sig_b = sig if isinstance(sig, bytes) else sig.to_bytes()
        msg = digest if isinstance(digest, bytes) else digest.to_bytes()
        pub = self._pk(pk_b)
        s = BlsSignature.from_bytes(sig_b)
        return pub is not None and s is not None and pub.verify(msg, s)

    def verify_shared_msg(self, digest, votes) -> bool:
        """One pairing equality for the whole vote set (aggregation)."""
        msg = digest if isinstance(digest, bytes) else digest.to_bytes()
        pks, sigs = [], []
        for pk, sig in votes:
            pub = self._pk(pk if isinstance(pk, bytes) else pk.to_bytes())
            s = BlsSignature.from_bytes(
                sig if isinstance(sig, bytes) else sig.to_bytes()
            )
            if pub is None or s is None:
                return False
            pks.append(pub)
            sigs.append(s)
        if not pks:
            return False
        if self._tpu_agg is not None:
            agg_sig = BlsSignature(
                self._tpu_agg.aggregate([s.point for s in sigs])
            )
        else:
            agg_sig = aggregate_signatures(sigs)
        return aggregate_public_keys(pks).verify(msg, agg_sig)

    def verify_many(self, digests, pks, sigs) -> list[bool]:
        return [
            self.verify_one(d, p, s) for d, p, s in zip(digests, pks, sigs)
        ]


class BlsSignatureService:
    """Actor-shaped signing service (reference crypto/src/lib.rs:232-257):
    callers await ``request_signature(digest)``; one task owns the key."""

    def __init__(self, secret: BlsSecretKey):
        self._secret = secret
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: asyncio.Task | None = None

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="bls-signature-service"
            )

    async def _run(self) -> None:
        while True:
            digest, fut = await self._queue.get()
            if not fut.done():
                fut.set_result(self._secret.sign(digest))

    async def request_signature(self, digest: bytes) -> BlsSignature:
        self._ensure_started()
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put((digest, fut))
        return await fut

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


__all__ = ["BlsVerifier", "BlsSignatureService", "keygen"]
