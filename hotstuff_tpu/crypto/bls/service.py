"""BLS backend behind the framework's verifier/signing boundaries.

Mirrors the Ed25519 ``VerifierBackend`` protocol
(hotstuff_tpu/crypto/service.py) over BLS12-381 keys (96-byte G2
pubkeys) and signatures (48-byte G1 points), and adds what only BLS can
offer: constant-cost shared-message verification via signature
aggregation — ``verify_shared_msg`` does ONE pairing equality however
many votes are in the QC, instead of a batch over 2f+1 Ed25519
signatures.

Drop-in point (reference parity): the SignatureService boundary at
crypto/src/lib.rs:232-257; BASELINE config 5's threshold variant uses
``split_secret``/``combine_partials`` from the package root.
"""

from __future__ import annotations

from ...telemetry import spans as _spans
from . import (
    BlsPublicKey,
    BlsSecretKey,
    BlsSignature,
    aggregate_public_keys,
    keygen,
)


class BlsVerifier:
    """VerifierBackend over BLS bytes; caches decoded public keys.

    ``aggregator="tpu"`` runs the G1 signature sum on device
    (hotstuff_tpu/tpu/bls.py — the psum-shaped reduction of
    docs/BLS_TPU_DESIGN.md); the pairing equality stays on the host in
    both modes, one constant-cost call per QC.

    Async-claims integration (crypto/async_service.py):

    - ``prefers_aggregate``: shared-message claims (QCs, grouped timeout
      floods) MUST go through ``verify_shared_msg`` — one pairing
      equality per claim; flattening them into per-item checks would
      cost two pairings per SIGNATURE (~200x a QC under load);
    - the worker-thread offload (``async_kind``/``always_offload``):
      pairing work runs through the native C++ library via ctypes,
      which releases the GIL — so an adversarial all-distinct-digest
      TC storm (n+1 Miller loops, ~2.5 ms each) runs off the event
      loop instead of stalling every round timer mid-view-change
      (VERDICT r3 item 8)."""

    name = "bls-cpu"
    prefers_aggregate = True

    def __init__(self, aggregator: str = "cpu"):
        self._pk_cache: dict[bytes, BlsPublicKey | None] = {}
        # signer-set digest -> aggregated G2 key (compact-QC verify);
        # bounded in verify_aggregate_msg
        self._agg_pk_cache: dict[bytes, BlsPublicKey] = {}
        self._tpu_agg = None
        # Native pairing (C++ port of this package, ~8x): used for
        # per-signature checks and point aggregation when the library
        # is present/healthy
        try:
            from . import native as _native

            self._native = _native
            self._native_verify = _native.verify_one
        except ImportError:
            self._native = None
            self._native_verify = None
        self._storm = None  # TpuStormOffload (device ladders), warmed on demand
        if aggregator == "tpu":
            from ...tpu.bls import TpuG1Aggregator

            self._tpu_agg = TpuG1Aggregator()
            self.name = "bls-tpu"
        elif aggregator == "tpu-sharded":
            # batch sharded over every visible device: per-device tree
            # reduction + one all_gather of the partial points
            from ...parallel.mesh import default_mesh
            from ...tpu.bls import TpuG1Aggregator

            self._tpu_agg = TpuG1Aggregator(mesh=default_mesh())
            self.name = "bls-tpu-sharded"
        elif aggregator != "cpu":
            raise ValueError(f"unknown BLS aggregator '{aggregator}'")
        # Worker-thread offload via AsyncVerifyService: only worthwhile
        # when the native library carries the pairing work (ctypes
        # releases the GIL during C calls; the pure-Python fallback
        # would hold it and gain nothing from a thread).
        if self._native is not None:
            self.async_kind = f"{self.name}-offload"
            self.always_offload = True
            self.device_ready = True
            self.async_backend = self  # the offload target is this object
            self.cpu_backend = self  # inline fallback: same object
            # an adversarial all-distinct TC storm legitimately takes
            # ~0.4 s of (off-loop) pairing work — never deadline it back
            # onto the loop
            self.dispatch_deadline_s = 30.0

    def warmup_storm_offload(self, n: int = 171) -> None:
        """Compile the device ladder/aggregation shapes for an n-entry
        distinct-digest storm (VERDICT r5 item 8).  Only meaningful on
        the device-aggregation variants; call at node boot, never
        mid-consensus."""
        if self._tpu_agg is None or self._native is None:
            return
        from ...tpu.bls import TpuStormOffload

        if self._storm is None:
            self._storm = TpuStormOffload()
        self._storm.warmup(n)

    def storm_offload_engaged(self, n: int) -> bool:
        """True iff an n-entry all-distinct TC batch would actually run
        through the device ladder offload in ``verify_many`` — the same
        gate that method applies (warmed shapes AND the n >= 16 floor
        below which the dispatch fixed cost can't amortize).  Public so
        the storm harness can refuse to label a host-route measurement
        as the offload row."""
        return (
            self._storm is not None
            and self._storm.ready
            and self._storm.shape_ready(n)
            and n >= 16
        )

    def _storm_verify(self, db, pb, sb) -> bool:
        """Device-offloaded all-distinct batch: host hashes/decompresses
        (native), device runs all 3n G1 ladders + the wsig aggregation,
        host runs the pairing product over the returned points.  False
        verdicts (or any malformed input) fall back to the caller's
        per-item attribution path."""
        import secrets

        from ...tpu.bls import from_mont_int  # noqa: F401 — doc pointer
        from .curve import G1Point
        from .fields import P as FIELD_P

        n = len(db)
        bases_raw = self._native.hash_base_many(db)
        sigs_raw = self._native.g1_decompress_many(sb)
        if bases_raw is None or sigs_raw is None:
            return False

        def parse(points_raw, count):
            out = []
            for i in range(count):
                x = int.from_bytes(points_raw[96 * i : 96 * i + 48], "big")
                y = int.from_bytes(points_raw[96 * i + 48 : 96 * i + 96], "big")
                if x >= FIELD_P or y >= FIELD_P:
                    return None
                out.append(G1Point(x, y))
            return out

        bases = parse(bases_raw, n)
        sigs = parse(sigs_raw, n)
        if bases is None or sigs is None:
            return False
        weights = [secrets.randbits(128) | 1 for _ in range(n)]
        whm, agg, subgroup_ok = self._storm.batch_points(weights, bases, sigs)
        if not subgroup_ok:
            return False

        def ser(pt) -> bytes:
            if pt.inf:
                return bytes(96)
            return pt.x.to_bytes(48, "big") + pt.y.to_bytes(48, "big")

        return self._native.verify_batch_points(
            b"".join(ser(p) for p in whm), pb, ser(agg)
        )

    def _pk(self, pk_bytes: bytes) -> BlsPublicKey | None:
        if pk_bytes not in self._pk_cache:
            self._pk_cache[pk_bytes] = BlsPublicKey.from_bytes(pk_bytes)
        return self._pk_cache[pk_bytes]

    def precompute(self, pubkeys: list[bytes]) -> None:
        for pk in pubkeys:
            self._pk(pk)

    def verify_one(self, digest, pk, sig) -> bool:
        pk_b = pk if isinstance(pk, bytes) else pk.to_bytes()
        sig_b = sig if isinstance(sig, bytes) else sig.to_bytes()
        msg = digest if isinstance(digest, bytes) else digest.to_bytes()
        if self._native_verify is not None:
            return self._native_verify(msg, pk_b, sig_b)
        pub = self._pk(pk_b)
        s = BlsSignature.from_bytes(sig_b)
        return pub is not None and s is not None and pub.verify(msg, s)

    def verify_shared_msg(self, digest, votes) -> bool:
        """One pairing equality for the whole vote set (aggregation).

        Per-signature decode skips the r-torsion ladder; the SUM is
        subgroup-checked once instead (matching the TPU aggregator's
        r-ladder-on-the-aggregate design).  Sound: honest signatures
        carry no cofactor component, so any attack using per-vote
        cofactor components that cancel in the sum is equivalent to one
        using clean signatures — and a non-cancelling component makes
        the aggregate fail the single check."""
        from .curve import G1Point

        msg = digest if isinstance(digest, bytes) else digest.to_bytes()
        if not votes:
            return False
        if self._native is not None and self._tpu_agg is None:
            # mixed path, fastest measured: signatures aggregate in C
            # (decompress + Jacobian sum, no per-sig subgroup ladders —
            # the aggregate is checked by the native verifier); public
            # keys sum over the CACHED decoded points (a native pk
            # aggregate would re-run the expensive G2 sqrt per key that
            # the cache already paid once per epoch)
            pubs, sig_bytes = [], []
            for pk, sig in votes:
                pub = self._pk(pk if isinstance(pk, bytes) else pk.to_bytes())
                if pub is None:
                    return False
                pubs.append(pub)
                sig_bytes.append(
                    sig if isinstance(sig, bytes) else sig.to_bytes()
                )
            agg_sig = self._native.aggregate_sigs(sig_bytes)
            if agg_sig is None:
                return False
            agg_pk = aggregate_public_keys(pubs)
            with _spans.span("host.pairing"):
                return self._native.verify_one(
                    msg, agg_pk.to_bytes(), agg_sig, check_pk_subgroup=False
                )
        pks, sig_points = [], []
        for pk, sig in votes:
            pub = self._pk(pk if isinstance(pk, bytes) else pk.to_bytes())
            s = G1Point.from_bytes(
                sig if isinstance(sig, bytes) else sig.to_bytes(),
                subgroup_check=False,
            )
            if pub is None or s is None:
                return False
            pks.append(pub)
            sig_points.append(s)
        if self._tpu_agg is not None:
            agg = self._tpu_agg.aggregate(sig_points)
        else:
            agg = G1Point.sum(sig_points)
        agg_pk = aggregate_public_keys(pks)
        if self._native_verify is not None:
            # the native verifier subgroup-checks the aggregate SIGNATURE
            # itself; the aggregate PK is a sum of individually
            # subgroup-checked cached keys, so its ladder is skipped
            with _spans.span("host.pairing"):
                return self._native_verify(
                    msg,
                    agg_pk.to_bytes(),
                    BlsSignature(agg).to_bytes(),
                    check_pk_subgroup=False,
                )
        # ONE subgroup check on the aggregate (the device kernel's
        # in-kernel r-ladder is still future work, so the host checks
        # its result too — ~2 ms once per QC)
        if not agg.in_subgroup():
            return False
        with _spans.span("host.pairing"):
            return agg_pk.verify(msg, BlsSignature(agg))

    def verify_aggregate_msg(self, digest, pks, agg_sig) -> bool:
        """Compact-certificate verify (QC.verify / TC.verify over the
        aggregated wire form): the signers' public keys — gathered from
        the signer bitmap by the caller — are summed once, then ONE
        pairing equality checks the pre-aggregated 48-byte signature,
        regardless of committee size.

        Unlike ``verify_shared_msg`` the aggregate signature arrives
        off the WIRE (adversary-controlled), so it is subgroup-checked
        here: the native verifier r-ladders the signature itself, and
        the pure path decodes with the default subgroup check on.  The
        key SUM is memoized by signer-set digest — under steady state
        every QC carries the same (or one of a few) quorum bitmaps, so
        repeat certificates skip the G2 sum and pay only the pairing."""
        msg = digest if isinstance(digest, bytes) else digest.to_bytes()
        sig_b = (
            agg_sig if isinstance(agg_sig, bytes) else agg_sig.to_bytes()
        )
        if not pks or len(sig_b) != 48:
            return False
        pk_bytes = [
            p if isinstance(p, bytes) else p.to_bytes() for p in pks
        ]
        import hashlib

        set_key = hashlib.blake2b(
            b"".join(pk_bytes), digest_size=16
        ).digest()
        agg_pk = self._agg_pk_cache.get(set_key)
        if agg_pk is None:
            with _spans.span("agg.gather"):
                pubs = []
                for pb in pk_bytes:
                    pub = self._pk(pb)
                    if pub is None:
                        return False
                    pubs.append(pub)
            with _spans.span("agg.keysum"):
                agg_pk = aggregate_public_keys(pubs)
            if len(self._agg_pk_cache) >= 256:
                # bounded: distinct quorum bitmaps per view are few; an
                # adversary churning bitmaps just degrades to no-cache
                self._agg_pk_cache.clear()
            self._agg_pk_cache[set_key] = agg_pk
        if self._native_verify is not None:
            # the native verifier subgroup-checks the (wire) aggregate
            # signature itself; the key sum is over subgroup-checked
            # cached committee points (closure), so its ladder is skipped
            with _spans.span("agg.pairing"):
                return self._native_verify(
                    msg, agg_pk.to_bytes(), sig_b, check_pk_subgroup=False
                )
        sig = BlsSignature.from_bytes(sig_b)  # default: subgroup-checked
        if sig is None:
            return False
        with _spans.span("agg.pairing"):
            return agg_pk.verify(msg, sig)

    def _grouped_batch(self, db, pb, sb):
        """Group a distinct-message batch by digest and aggregate each
        group (Σ pk over cached decoded points, Σ sig natively).
        Returns (digests, agg_pks96, agg_sigs48) per group, or None if
        grouping buys nothing (all digests distinct) or any key/sig is
        undecodable (caller falls back per item)."""
        groups: dict[bytes, list[int]] = {}
        for i, d in enumerate(db):
            groups.setdefault(d, []).append(i)
        if len(groups) == len(db):
            return None
        g_db, g_pb, g_sb = [], [], []
        for d, idxs in groups.items():
            pubs = []
            for i in idxs:
                pub = self._pk(pb[i])
                if pub is None:
                    return None
                pubs.append(pub)
            agg_sig = self._native.aggregate_sigs([sb[i] for i in idxs])
            if agg_sig is None:
                return None
            g_db.append(d)
            # sum of subgroup-checked cached points stays in-subgroup
            # (closure) — the native layer is told so
            # (check_pk_subgroup=False), which also keeps these one-shot
            # aggregate keys out of its prepared-coefficient cache
            g_pb.append(aggregate_public_keys(pubs).to_bytes())
            g_sb.append(agg_sig)
        return g_db, g_pb, g_sb

    def verify_many(
        self, digests, pks, sigs, aggregate_ok: bool = False
    ) -> list[bool]:
        """Distinct-message batch (the TC-verify shape): one multi-pairing
        with random 128-bit weights sharing a single final exponentiation
        — Π e(rᵢ·H(mᵢ), pkᵢ) · e(−Σ rᵢ·sigᵢ, G2) == 1.  The random
        weights make cross-entry cancellation infeasible (standard
        small-exponents batching), so a passing product implies every
        entry verifies; on failure, fall back per-item to report WHICH
        entries are invalid.  Cost: n+1 Miller loops + 1 final exp
        (~13 ms/entry) vs n full pairing equalities (~40 ms/entry) —
        this is the view-change-storm path (TC.verify, BASELINE
        config 4), which runs on the event loop while round timers are
        already firing."""
        import secrets

        from .curve import G1Point, G2Point, hash_to_g1
        from .fields import Fq12
        from .pairing import final_exponentiation, miller_loop

        n = len(digests)
        if n == 0:
            return []
        if self._native is not None:
            db = [
                d if isinstance(d, bytes) else d.to_bytes() for d in digests
            ]
            pb = [p if isinstance(p, bytes) else p.to_bytes() for p in pks]
            sb = [s if isinstance(s, bytes) else s.to_bytes() for s in sigs]
            if n > 1 and all(len(d) == 32 for d in db):
                # TC shape.  The storm's timeout digests collapse to a
                # handful of DISTINCT values (every node signing the
                # same (round, high_qc_round) produces the same digest),
                # so first GROUP BY DIGEST and aggregate each group the
                # QC way — Π e(r_i·H(m), pk_i) = e(r·H(m), Σ pk_i) —
                # then run the native random-weight multi-pairing over
                # the G group aggregates: G+1 Miller loops instead of
                # n+1.  Within-group aggregation leans on the same
                # trust base as QC aggregation (PoP-checked keys,
                # subgroup-checked summands; committee/stake rules run
                # BEFORE signatures in TC.verify), and the RANDOM
                # WEIGHTS still apply per group, so cross-group
                # cancellation stays infeasible.  Worst adversarial
                # case (all digests distinct) degrades to exactly the
                # old per-entry multi-pairing.  Measured on the 171-
                # entry storm: 333 ms -> ~25 ms.
                grouped = (
                    self._grouped_batch(db, pb, sb) if aggregate_ok else None
                )
                if grouped is not None:
                    g_db, g_pb, g_sb = grouped
                    # check_pk_subgroup=False: the aggregates are sums
                    # of subgroup-checked cached committee points
                    # (closure), and the flag also tells the native
                    # layer these one-shot keys must not enter the
                    # prepared-line-coefficient cache
                    ok = (
                        self._native.verify_batch(
                            g_db, g_pb, g_sb, check_pk_subgroup=False
                        )
                        if len(g_db) > 1
                        else self._native.verify_one(
                            g_db[0], g_pb[0], g_sb[0],
                            check_pk_subgroup=False,
                        )
                    )
                    if ok:
                        return [True] * n
                elif (
                    aggregate_ok
                    and self.storm_offload_engaged(n)
                    and self._storm_verify(db, pb, sb)
                ):
                    # all-distinct worst case with the G1 ladders on
                    # device (VERDICT r5 item 8); False verdicts fall
                    # through to per-item attribution below
                    return [True] * n
                elif self._native.verify_batch(db, pb, sb):
                    return [True] * n
                # re-check per item to pinpoint the invalid entries
            return [
                self.verify_one(d, p, s) for d, p, s in zip(db, pb, sb)
            ]
        entries = []
        for d, p, s in zip(digests, pks, sigs):
            pub = self._pk(p if isinstance(p, bytes) else p.to_bytes())
            sig = BlsSignature.from_bytes(
                s if isinstance(s, bytes) else s.to_bytes()
            )
            msg = d if isinstance(d, bytes) else d.to_bytes()
            if pub is None or sig is None or pub.point.inf or sig.point.inf:
                entries = None  # malformed entry: no batch shortcut
                break
            entries.append((msg, pub.point, sig.point))
        if entries is not None and n > 1:
            weights = [secrets.randbits(128) | 1 for _ in range(n)]
            agg = G1Point.sum(
                [sig_pt._mul_raw(r) for (_, _, sig_pt), r in zip(entries, weights)]
            )
            f = Fq12.ONE
            for (msg, pk_pt, _), r in zip(entries, weights):
                f = f * miller_loop(hash_to_g1(msg)._mul_raw(r), pk_pt)
            f = f * miller_loop(-agg, G2Point.generator())
            if final_exponentiation(f) == Fq12.ONE:
                return [True] * n
        return [
            self.verify_one(d, p, s) for d, p, s in zip(digests, pks, sigs)
        ]


class BlsSigningService:
    """The BLS signing service behind the SignatureService API surface
    (reference crypto/src/lib.rs:232-257).  Signing is inline — the
    single-threaded loop already serializes access to the key, the same
    argument as the Ed25519 service — ~6 ms per sign (hash-to-G1 + one
    G1 scalar multiply).  Returns the scheme-agnostic consensus
    ``Signature`` wrapper (48-byte compressed G1) so votes/blocks carry
    BLS material through the identical protocol types."""

    def __init__(self, secret: BlsSecretKey | bytes):
        if isinstance(secret, (bytes, bytearray)):
            secret = BlsSecretKey(int.from_bytes(bytes(secret), "big"))
        self._sk: BlsSecretKey | None = secret
        self._closed = False

    async def request_signature(self, digest) -> "Signature":
        return self.sign_sync(digest)

    def sign_sync(self, digest) -> "Signature":
        from ..signature import Signature

        if self._closed or self._sk is None:
            raise RuntimeError("BlsSigningService is shut down")
        msg = digest if isinstance(digest, bytes) else digest.to_bytes()
        return Signature(self._sk.sign(msg).to_bytes())

    def shutdown(self) -> None:
        self._closed = True
        self._sk = None


__all__ = ["BlsVerifier", "BlsSigningService", "keygen"]
