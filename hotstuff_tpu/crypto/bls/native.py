"""ctypes bridge to the native C++ BLS12-381 verifier (native/bls_pairing.cpp).

The C++ side is a direct port of THIS package's field/curve/pairing code
(the tested Python oracle) — same tower, same Miller-loop structure,
same framework-internal hash-to-G1 — so a signature valid under one is
valid under the other (pinned by tests/test_bls.py parity tests).

Measured: one signature verification ~6 ms native vs ~53 ms pure
Python.  The per-certificate aggregate checks were already one pairing
equality; this path matters for PER-MESSAGE authentication (timeout
floods — the view-change-storm bench showed ~45 ms/timeout on the
Python backend).

Set ``HOTSTUFF_BLS_NATIVE=0`` to force the Python pairing.  The library
runs a bilinearity selftest at load; any failure falls back to Python.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_LIB_NAME = "libhs_bls.so"


def _native_dir() -> str:
    return os.path.join(
        os.path.dirname(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
        ),
        "native",
    )


def _build_locked(path: str) -> None:
    """Run ``make`` under an exclusive lock.  Always invoked — make's
    dependency tracking makes it a no-op when the library is current and
    REBUILDS a stale one (a .so from an older commit would load fine but
    miss newer symbols, silently disabling all native acceleration).
    The lock keeps a co-located committee booting on a clean checkout
    from racing N compilers onto the same output file (one process
    would dlopen a half-written .so)."""
    import fcntl

    build_dir = os.path.dirname(path)
    os.makedirs(build_dir, exist_ok=True)
    with open(os.path.join(build_dir, ".bls_build_lock"), "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            subprocess.run(
                ["make", "-C", _native_dir()],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            # no toolchain: an existing up-to-date library may still
            # work — symbol resolution below decides
            if not os.path.exists(path):
                raise


def _load_lib() -> ctypes.CDLL:
    if os.environ.get("HOTSTUFF_BLS_NATIVE") == "0":
        raise ImportError("native BLS disabled via HOTSTUFF_BLS_NATIVE=0")
    path = os.path.join(_native_dir(), "build", _LIB_NAME)
    try:
        _build_locked(path)
        lib = ctypes.CDLL(path)
        lib.hs_bls_verify_one_ex.restype = ctypes.c_int
        lib.hs_bls_verify_one_ex.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.hs_bls_selftest.restype = ctypes.c_int
        lib.hs_bls_aggregate_sigs.restype = ctypes.c_int
        lib.hs_bls_aggregate_sigs.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
        ]
        lib.hs_bls_verify_batch.restype = ctypes.c_int
        lib.hs_bls_verify_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        for name in ("hs_bls_g1_decompress_many", "hs_bls_hash_base_many"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
        lib.hs_bls_verify_batch_points.restype = ctypes.c_int
        lib.hs_bls_verify_batch_points.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        if lib.hs_bls_selftest() != 1:
            raise ImportError(f"{_LIB_NAME} failed its bilinearity selftest")
        return lib
    except ImportError:
        raise
    except Exception as e:  # OSError (bad .so), build failures, ABI drift…
        # the bridge's contract is "any failure falls back to Python" —
        # normalize every failure class to the ImportError the callers
        # catch (service.py)
        raise ImportError(f"native BLS unavailable: {e}") from e


_lib = _load_lib()


def verify_one(
    message: bytes, pk96: bytes, sig48: bytes, check_pk_subgroup: bool = True
) -> bool:
    """Native verification: e(sig, G2) == e(H(msg), pk), with on-curve
    AND subgroup checks (matching the Python path).
    ``check_pk_subgroup=False`` skips the pk r-torsion ladder — ONLY for
    keys whose membership is already established (an aggregate of
    individually checked committee keys)."""
    if len(pk96) != 96 or len(sig48) != 48:
        return False
    return bool(
        _lib.hs_bls_verify_one_ex(
            message, len(message), pk96, sig48, 1 if check_pk_subgroup else 0
        )
    )


def aggregate_sigs(sigs48: list[bytes]) -> bytes | None:
    """Sum compressed G1 signatures natively (on-curve checked; the
    aggregate's subgroup membership is checked by verify_one).  None on
    malformed input."""
    if any(len(s) != 48 for s in sigs48):
        return None
    buf = b"".join(sigs48)
    out = ctypes.create_string_buffer(48)
    if not _lib.hs_bls_aggregate_sigs(buf, len(sigs48), out):
        return None
    return out.raw


def verify_batch(
    digests32: list[bytes],
    pks96: list[bytes],
    sigs48: list[bytes],
    check_pk_subgroup: bool = True,
) -> bool:
    """Random-weight batched verification over DISTINCT 32-byte digests
    (the TC shape): n+1 Miller loops sharing one final exponentiation.
    True = every entry valid; False = at least one invalid (re-check per
    item to pinpoint).  Weights are generated here — their secrecy /
    unpredictability is what makes cross-entry cancellation infeasible."""
    import secrets

    n = len(digests32)
    if n == 0 or len(pks96) != n or len(sigs48) != n:
        return False  # a short list would read past the joined buffers
    if any(len(d) != 32 for d in digests32):
        return False
    if any(len(p) != 96 for p in pks96) or any(len(s) != 48 for s in sigs48):
        return False
    weights = b"".join(
        (secrets.randbits(128) | 1).to_bytes(16, "little") for _ in range(n)
    )
    return bool(
        _lib.hs_bls_verify_batch(
            b"".join(digests32),
            b"".join(pks96),
            b"".join(sigs48),
            n,
            weights,
            1 if check_pk_subgroup else 0,
        )
    )


# ---- TPU-offload split (VERDICT r5 item 8) ---------------------------------
# The per-entry G1 ladders of the distinct-digest batch run on device
# (tpu/bls.py); these are the host ends.


def g1_decompress_many(sigs48: list[bytes]) -> bytes | None:
    """Compressed signatures -> uncompressed affine (96 B each,
    on-curve checked; subgroup membership is the device ladder's job).
    None on malformed input."""
    n = len(sigs48)
    if n == 0 or any(len(s) != 48 for s in sigs48):
        return None
    out = ctypes.create_string_buffer(96 * n)
    if not _lib.hs_bls_g1_decompress_many(b"".join(sigs48), n, out):
        return None
    return out.raw


def hash_base_many(digests32: list[bytes]) -> bytes | None:
    """Digests -> PRE-cofactor hash base points, uncompressed affine."""
    n = len(digests32)
    if n == 0 or any(len(d) != 32 for d in digests32):
        return None
    out = ctypes.create_string_buffer(96 * n)
    if not _lib.hs_bls_hash_base_many(b"".join(digests32), n, out):
        return None
    return out.raw


def verify_batch_points(
    whm96: bytes, pks96: list[bytes], agg96: bytes,
    check_pk_subgroup: bool = True,
) -> bool:
    """Pairing product over device-computed points: whm96 = n contiguous
    uncompressed (r_i * h_eff) * H_base(m_i); agg96 = sum r_i * sig_i."""
    n = len(pks96)
    if n == 0 or len(whm96) != 96 * n or len(agg96) != 96:
        return False
    if any(len(p) != 96 for p in pks96):
        return False
    return bool(
        _lib.hs_bls_verify_batch_points(
            whm96, b"".join(pks96), n, agg96, 1 if check_pk_subgroup else 0
        )
    )
