"""Crypto scheme registry: Ed25519 (default) and BLS12-381.

The reference hard-codes ed25519-dalek behind its ``SignatureService``
boundary (crypto/src/lib.rs:232-257).  This framework makes the scheme a
committee-level property so a BLS-signed committee (BASELINE config 5 —
constant-cost QC verification via signature aggregation, TPU G1 sum) is
selectable end-to-end from the node CLI: ``keys --scheme bls`` writes a
BLS keypair file, the committee file records the scheme, and ``Node.new``
dispatches here for the signing service and verifier backend.

A scheme bundles:
- key/signature byte formats (PublicKey 32 vs 96, Signature 64 vs 48 —
  protocol wire fields are length-prefixed, so both coexist);
- deterministic + OS keygen;
- the signing-service factory (actor holding the secret key);
- the verifier-backend factory (cpu / device variants).
"""

from __future__ import annotations

import hashlib
import os
import struct

from ..telemetry import spans as _spans
from .keys import (
    PublicKey,
    SecretKey,
    WipeableSecret,
    generate_keypair,
    generate_production_keypair,
)
from .service import CpuVerifier, SignatureService, VerifierBackend

SCHEMES = ("ed25519", "bls")
DEFAULT_SCHEME = "ed25519"


class UnknownScheme(ValueError):
    def __init__(self, name: str):
        super().__init__(
            f"unknown crypto scheme '{name}' (expected one of {SCHEMES})"
        )


class OpaqueSecret(WipeableSecret):
    """Scheme-agnostic secret bytes (BLS scalar, etc.) — any length,
    same wipe contract as SecretKey."""

    __slots__ = ()


def bls_keygen(seed: bytes | None = None, index: int = 0) -> tuple[PublicKey, bytes]:
    """(96-byte G2 public key, 32-byte big-endian scalar secret).

    Deterministic derivation mirrors the Ed25519 fixture convention
    (keys.py): scalar_i = SHA-512("bls-keygen" ‖ seed ‖ u64_le(i)) mod
    (R−1) + 1."""
    from .bls.fields import R as BLS_R

    if seed is None:
        material = os.urandom(64)
    else:
        material = hashlib.sha512(
            b"bls-keygen" + seed + struct.pack("<Q", index)
        ).digest()
    scalar = (int.from_bytes(material, "big") % (BLS_R - 1)) + 1
    from .bls import BlsSecretKey

    sk = BlsSecretKey(scalar)
    pk = PublicKey(sk.public_key().to_bytes())
    return pk, scalar.to_bytes(32, "big")


def bls_pop(secret_bytes: bytes) -> bytes:
    """48-byte proof of possession for a BLS secret — REQUIRED committee
    material (``consensus.config.Authority.pop``): sum-of-keys QC
    verification is rogue-key forgeable without it."""
    from .bls import BlsSecretKey, prove_possession

    sk = BlsSecretKey(int.from_bytes(secret_bytes, "big"))
    return prove_possession(sk).to_bytes()


def check_scheme(name: str) -> str:
    if name not in SCHEMES:
        raise UnknownScheme(name)
    return name


def keygen_production(scheme: str) -> tuple[PublicKey, OpaqueSecret | SecretKey]:
    """OS-RNG keypair for the scheme; the secret supports wipe()/base64."""
    check_scheme(scheme)
    if scheme == "ed25519":
        return generate_production_keypair()
    pk, secret = bls_keygen()
    return pk, OpaqueSecret(secret)


def keygen_deterministic(
    scheme: str, seed: bytes, index: int = 0
) -> tuple[PublicKey, OpaqueSecret | SecretKey]:
    check_scheme(scheme)
    if scheme == "ed25519":
        return generate_keypair(seed, index)
    pk, secret = bls_keygen(seed, index)
    return pk, OpaqueSecret(secret)


def read_secret(scheme: str, b64: str) -> OpaqueSecret | SecretKey:
    """Decode a key-file secret for the scheme (ed25519 keeps the typed
    64-byte SecretKey; BLS secrets are opaque 32-byte scalars)."""
    check_scheme(scheme)
    if scheme == "ed25519":
        return SecretKey.decode_base64(b64)
    return OpaqueSecret.decode_base64(b64)


def make_signing_service(scheme: str, secret):
    check_scheme(scheme)
    if scheme == "ed25519":
        return SignatureService(secret)
    from .bls.service import BlsSigningService

    return BlsSigningService(secret.to_bytes())


def make_cpu_verifier(scheme: str) -> VerifierBackend:
    check_scheme(scheme)
    if scheme == "ed25519":
        return CpuVerifier()
    from .bls.service import BlsVerifier

    return BlsVerifier()


def make_device_verifier(scheme: str, kind: str) -> VerifierBackend:
    """Device-backed verifier: the Ed25519 batch kernel (with the
    lazy-import hybrid handled by the caller, node/node.py) or the BLS
    verifier with its G1 aggregation on device."""
    check_scheme(scheme)
    if scheme == "bls":
        from .bls.service import BlsVerifier

        # 'tpu': single-device G1 tree reduction; 'tpu-sharded': batch
        # sharded over the mesh with an all_gather partial-point combine
        # (docs/BLS_TPU_DESIGN.md step 4).  BlsVerifier rejects anything
        # else.
        v = BlsVerifier(aggregator=kind)
        if not hasattr(v, "dispatch_deadline_s"):
            # pure-Python pairing fallback (native lib absent): one
            # equality legitimately takes ~100 ms — the dispatch
            # pipeline's default 100 ms deadline would demote every
            # healthy wave back onto the loop it exists to protect
            v.dispatch_deadline_s = 30.0
        return v
    raise ValueError(
        "ed25519 device verifiers are constructed by node.make_verifier "
        "(lazy-import hybrid)"
    )


class DualSchemeVerifier:
    """Verifier for mixed-scheme CommitteeSchedules (a scheme changeover
    across an epoch boundary): routes each check to the per-scheme
    backend by key wire size (32 = ed25519, 96 = BLS compressed G2).

    One certificate never mixes schemes (a committee is single-scheme
    and authority/stake checks against the round's committee run before
    signatures), so routing by the first key is sound; a hostile
    mixed-material certificate simply fails verification in whichever
    backend it lands."""

    name = "dual"
    # Shared-message claims must route through verify_shared_msg so the
    # BLS side keeps its one-pairing aggregate (flattening a BLS QC into
    # per-item checks costs two pairings per SIGNATURE); the ed25519
    # side's verify_shared_msg is the same per-signature work either way.
    prefers_aggregate = True
    # Never advertise wave padding here even when the ed25519 member
    # does: the pad filler is an ed25519 claim, and a padded wave whose
    # real claims are BLS would then mis-route on the filler's 32-byte
    # key.  Fixed-shape buckets only make sense below the scheme split.
    supports_wave_padding = False

    def __init__(self, backends: dict[str, "VerifierBackend"]):
        self.backends = backends

    def _route(self, pk_bytes: bytes) -> "VerifierBackend":
        return self.backends["bls" if len(pk_bytes) == 96 else "ed25519"]

    def verify_one(self, digest, pk, sig) -> bool:
        return self._route(pk.data).verify_one(digest, pk, sig)

    def verify_shared_msg(self, digest, votes) -> bool:
        if not votes:
            return False
        with _spans.span("scheme.route"):
            backend = self._route(votes[0][0].data)
        return backend.verify_shared_msg(digest, votes)

    def verify_many(
        self, digests, pks, sigs, aggregate_ok: bool = False
    ) -> list[bool]:
        if not pks:
            return []
        with _spans.span("scheme.route"):
            backend = self._route(pks[0])
        return backend.verify_many(
            digests, pks, sigs, aggregate_ok=aggregate_ok
        )

    def verify_aggregate_msg(self, digest, pks, agg_sig) -> bool:
        """Compact-certificate verify (one agg sig + signer keys).  Only
        the BLS side has an aggregate form, but route by key size anyway:
        an ed25519 key set lands on a backend without the method and is
        rejected, same as everywhere else in this class."""
        if not pks:
            return False
        pk0 = pks[0] if isinstance(pks[0], bytes) else pks[0].to_bytes()
        with _spans.span("scheme.route"):
            backend = self._route(pk0)
        fn = getattr(backend, "verify_aggregate_msg", None)
        return fn is not None and fn(digest, pks, agg_sig)

    # boot-time hooks forwarded so device backends still warm up
    def precompute(self, pubkeys: list[bytes]) -> None:
        for pk in pubkeys:
            backend = self._route(pk)
            if hasattr(backend, "precompute"):
                backend.precompute([pk])

    def warmup(self, batch: int | None = None) -> None:
        for backend in self.backends.values():
            if hasattr(backend, "warmup"):
                backend.warmup(batch)


def make_dual_verifier(make_one) -> DualSchemeVerifier:
    """Compose a mixed-scheme verifier from per-scheme factories
    (``make_one(scheme) -> VerifierBackend``)."""
    return DualSchemeVerifier({s: make_one(s) for s in SCHEMES})
