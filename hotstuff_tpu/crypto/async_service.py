"""Asynchronous, coalescing signature verification.

This is the off-critical-path dispatch layer for the TPU verifier
(VERDICT r3 item 1): the consensus core collects every signature check a
message burst needs as *claims*, submits them here, and awaits ONE
verdict — while the actual device dispatch runs on a worker thread so
the event loop keeps processing votes, proposals and payload ingest.
Measured rationale (scripts/probe_dispatch*.py, round 4):

- a TPU dispatch through this rig's tunnel costs anywhere from ~0.3 ms
  (idle tunnel) to ~120 ms (weather), flat in batch size — so the only
  sane unit of dispatch is "everything currently pending";
- concurrent dispatches pipeline (16 in flight ≈ the cost of 1), so a
  single in-flight batch with arrivals gathering for the next one loses
  nothing;
- ``jax.block_until_ready`` releases the GIL (measured: a spinning
  thread keeps ~91% of its throughput during device verifies), so a
  worker thread parks on the device for free — while the host-side
  OpenSSL path holds the GIL (~83% occupancy measured), which is why
  the CPU fallback runs inline instead of pretending a thread helps.

Claims (the burst-level accumulate-then-dispatch unit):

- ``("one", digest_bytes, pk_bytes, sig_bytes)`` — a single signature
  over its own message (votes, block author sigs, TC entries);
- ``("shared", digest_bytes, ((pk_bytes, sig_bytes), ...))`` — many
  signatures over ONE message (the QC shape; also grouped timeout
  floods).  Verdict is all-or-nothing.

Backends that prefer aggregate verification of shared claims (BLS: one
pairing equality per claim instead of one per signature) advertise
``prefers_aggregate = True``; everything else is flattened into one
``verify_many`` batch — one device dispatch for the whole wave.

Adaptive routing: the service tracks an EWMA of device dispatch wall
time and routes each batch to the device only when that estimate beats
the measured CPU cost (n_sigs x ~140 us).  When the tunnel degrades the
service degrades to the CPU path instead of stalling consensus — and
keeps probing the device so it recovers when the weather does (the
reference's graceful best-effort philosophy at the FFI boundary,
SURVEY.md §7 "hard parts").

Pipelined dispatch (ISSUE 5): up to ``pipeline_depth`` device waves may
be in flight at once (default 2, ``HOTSTUFF_VERIFY_PIPELINE`` /
``--verify-pipeline``).  While wave N parks on the device, wave N+1
flattens, pads and transfers on a second worker thread, so the fixed
tunnel round trip amortizes across in-flight waves instead of gating
the committee per wave (the "16 in flight ≈ the cost of 1" measurement
above is exactly why this works).  Each wave lands through its own
completion future — out-of-order completion resolves each batch's own
waiters, and a failed wave poisons only its own futures.  The cost
model learns the marginal device cost: with waves already in flight,
an extra wave rides the occupied tunnel, so the EWMA is discounted by
``PIPELINE_MARGINAL_COST``.  At full occupancy a device-preferred wave
QUEUES for a slot (bounded by the earliest in-flight deadline) rather
than spilling to the CPU; an OVERDUE in-flight wave routes everything
to the CPU, preserving the anti-stall behavior of the old
single-in-flight gate.

Straight-line tunnel dispatch (ISSUE 6): device dispatches run on a
dedicated dispatch loop — ``pipeline_depth`` long-lived slot threads
over one queue — instead of a per-service ``ThreadPoolExecutor`` hop.
Each slot thread owns its thread-local staging scratch in the device
backend (tpu/ed25519.py pools scratch per thread), so the slots ARE a
ring of preallocated staging buffers: wave N parks on the device from
one slot while wave N+1 stages into the next slot's buffers.  Waves
routed to a padding-capable backend (``supports_wave_padding``) are
pre-padded to fixed bucket shapes (``HOTSTUFF_WAVE_BUCKETS``, default
16/64/256/1024) with always-valid pad claims so ``route.decide ->
dispatch`` hits a pre-compiled jitted callable every time, and an
optional round window (``HOTSTUFF_COALESCE_WINDOW_MS``) holds the wave
open so QC and TC claims from the same round merge into ONE tunnel
crossing with a claim-table fanout on readback.  The device backend
donates its staging buffers across waves (``donate_argnums`` in
tpu/ed25519.py) so XLA reuses device allocations instead of
re-allocating per wave.
"""

from __future__ import annotations

import asyncio
import atexit
import logging
import queue
import threading
import time

from ..telemetry import spans as _spans
from .digest import DIGEST_SIZE
from .native_ed25519 import NATIVE_BATCH_MIN

log = logging.getLogger(__name__)

# Measured single-signature CPU verify cost on this class of host
# (OpenSSL Ed25519 via `cryptography`, scripts in round 4: ~123-142 us).
# Only used as the device-vs-CPU routing threshold — an order-of-
# magnitude estimate is enough.
CPU_US_PER_SIG = 130.0

# Native-batch cost model: per-sig cost ~ asymptote + fixed/n (the
# Pippenger bucket cost amortizes with n).  Fit to the r5 measurements
# (~108 us/sig at 11, ~54 at 32, ~46 at 128, ~36 at 256).
CPU_BATCH_US_PER_SIG = 45.0
CPU_BATCH_FIXED_US = 700.0


def cpu_batch_estimate_s(n_sigs: int) -> float:
    """Estimated batched-CPU wall seconds for an n_sigs wave."""
    return n_sigs * (CPU_BATCH_US_PER_SIG + CPU_BATCH_FIXED_US / n_sigs) * 1e-6

# EWMA smoothing for device dispatch wall time.
_EWMA_ALPHA = 0.3

# When the device EWMA says "lose", still probe the device this often so
# a recovered tunnel is noticed (seconds).
_PROBE_INTERVAL_S = 3.0

# Default dispatch pipeline depth: waves in flight on the device at
# once.  2 gives staging/execute overlap without queueing enough work
# behind a tunnel stall to hurt (the deadline + overdue routing below
# bound the damage to one deadline regardless of depth).
DEFAULT_PIPELINE_DEPTH = 2

# Marginal cost factor for a device dispatch when waves are already in
# flight: concurrent dispatches pipeline (measured: 16 in flight ≈ the
# cost of 1), so the route cost model discounts the EWMA for every wave
# after the first instead of charging each a full round trip.
PIPELINE_MARGINAL_COST = 0.25


def pipeline_depth_from_env() -> int:
    """Dispatch pipeline depth from HOTSTUFF_VERIFY_PIPELINE (min 1)."""
    import os

    raw = os.environ.get("HOTSTUFF_VERIFY_PIPELINE", "")
    try:
        depth = int(raw)
    except ValueError:
        depth = DEFAULT_PIPELINE_DEPTH
    return max(1, depth)


# Fixed wave shapes (ISSUE 6): device-routed waves on padding-capable
# backends are pre-padded with always-valid pad claims to the smallest
# of these bucket sizes, so every dispatch hits a pre-compiled jitted
# callable instead of a shape-polymorphic retrace.  Aligned with the
# tpu/ed25519.py PAD_SIZES grid.
DEFAULT_WAVE_BUCKETS: tuple[int, ...] = (16, 64, 256, 1024)


def wave_buckets_from_env() -> tuple[int, ...]:
    """Wave bucket sizes from HOTSTUFF_WAVE_BUCKETS (comma-separated,
    e.g. "16,64,256,1024"); "0"/"off" disables fixed-shape padding
    (returns an empty tuple).  Unset or unparsable -> the default."""
    import os

    raw = os.environ.get("HOTSTUFF_WAVE_BUCKETS")
    if raw is None:
        return DEFAULT_WAVE_BUCKETS
    raw = raw.strip().lower()
    if raw in ("", "0", "off", "none", "no", "false"):
        return ()
    try:
        sizes = sorted({int(tok) for tok in raw.split(",") if tok.strip()})
    except ValueError:
        return DEFAULT_WAVE_BUCKETS
    return tuple(s for s in sizes if s > 0)


def resolve_wave_buckets(backend) -> tuple[int, ...]:
    """The bucket ladder for ``backend`` (ISSUE 7): an explicit
    ``HOTSTUFF_WAVE_BUCKETS`` always wins; otherwise a backend that
    advertises ``wave_bucket_shapes`` (the mesh verifier's mesh-multiple
    grid entries, so every padded wave IS a pre-compiled kernel shape
    with equal per-device slices) gets its own shapes; everything else
    gets the canonical default ladder."""
    import os

    if "HOTSTUFF_WAVE_BUCKETS" in os.environ:
        return wave_buckets_from_env()
    shapes = getattr(backend, "wave_bucket_shapes", None)
    if shapes:
        return tuple(sorted({int(b) for b in shapes if int(b) > 0}))
    return DEFAULT_WAVE_BUCKETS


def coalesce_window_s_from_env() -> float:
    """QC+TC coalescing window from HOTSTUFF_COALESCE_WINDOW_MS, in
    SECONDS.  Default 0: coalescing stays yield-based (two event-loop
    passes), adding zero latency; a positive window holds each wave
    open so both certificate kinds from one round share a dispatch."""
    import os

    raw = os.environ.get("HOTSTUFF_COALESCE_WINDOW_MS", "")
    try:
        ms = float(raw)
    except ValueError:
        ms = 0.0
    return max(0.0, ms) * 1e-3


def claim_sig_count(c) -> int:
    """Signatures a claim carries: 1 for "one", the vote-list length for
    "shared", the SIGNER count for "agg" (whose c[2] is the 48-byte
    aggregate-signature blob — len(c[2]) would miscount it as 48)."""
    if c[0] == "one":
        return 1
    if c[0] == "agg":
        return len(c[3])
    return len(c[2])


def flatten_claims(claims: list) -> tuple[list, list, list, list]:
    """Claims -> (digests, pks, sigs, spans); spans[i] = (start, end)
    slice of the flat arrays belonging to claims[i].

    This is the Python fallback for transports without the native
    zero-copy ingest plane (ISSUE 20) — kept allocation-lean: the
    column lists are preallocated at their final length in one sizing
    pass and filled by index, so the hot loop never grows a list or
    re-reads ``len`` per claim (measured as the ``flatten`` p50 in
    ``benchmark profile``)."""
    n_claims = len(claims)
    spans: list = [None] * n_claims
    total = 0
    for i, claim in enumerate(claims):
        k = 1 if claim[0] == "one" else len(claim[2])
        spans[i] = (total, total + k)
        total += k
    digests: list = [None] * total
    pks: list = [None] * total
    sigs: list = [None] * total
    pos = 0
    for claim in claims:
        if claim[0] == "one":
            digests[pos] = claim[1]
            pks[pos] = claim[2]
            sigs[pos] = claim[3]
            pos += 1
        else:  # "shared"
            d = claim[1]
            for pk, sig in claim[2]:
                digests[pos] = d
                pks[pos] = pk
                sigs[pos] = sig
                pos += 1
    return digests, pks, sigs, spans


def eval_claims_sync(backend, claims: list) -> list[bool]:
    """Synchronous claim evaluation on ``backend`` (the inline path and
    the worker-thread body).  Shared claims go through the backend's
    aggregate check when it prefers one (BLS); otherwise everything
    flattens into a single ``verify_many`` batch."""
    if getattr(backend, "prefers_aggregate", False):
        with _spans.span("agg.verify"):
            from .digest import Digest
            from .keys import PublicKey
            from .signature import Signature

            out: list[bool] = []
            singles: list[tuple[int, tuple]] = []
            for claim in claims:
                if claim[0] == "shared":
                    votes = [
                        (PublicKey(pk), Signature(sig))
                        for pk, sig in claim[2]
                    ]
                    # zero signatures prove nothing (flatten path below)
                    out.append(
                        bool(votes)
                        and bool(
                            backend.verify_shared_msg(Digest(claim[1]), votes)
                        )
                    )
                elif claim[0] == "agg":
                    # compact certificate: pre-aggregated signature +
                    # bitmap-resolved signer keys — ONE pairing however
                    # large the committee.  claim[2] is the agg-sig
                    # BYTES (not a vote list): it must never reach the
                    # flatten/verify_many shapes.
                    fn = getattr(backend, "verify_aggregate_msg", None)
                    out.append(
                        fn is not None
                        and bool(
                            fn(Digest(claim[1]), list(claim[3]), claim[2])
                        )
                    )
                else:
                    singles.append((len(out), claim))
                    out.append(False)  # placeholder
            if singles:
                ok = backend.verify_many(
                    [c[1] for _, c in singles],
                    [c[2] for _, c in singles],
                    [c[3] for _, c in singles],
                )
                for (pos, _), valid in zip(singles, ok):
                    out[pos] = bool(valid)
            return out

    if any(c[0] == "agg" for c in claims):
        # non-aggregating backend (ed25519) handed a compact
        # certificate: resolve each "agg" claim directly (False when the
        # backend has no aggregate verify — the wire layer already
        # rejects compact forms for such committees, this is the
        # loopback/defence-in-depth path) and recurse on the rest.
        from .digest import Digest

        fn = getattr(backend, "verify_aggregate_msg", None)
        out = []
        rest = [c for c in claims if c[0] != "agg"]
        rest_verdicts = iter(
            eval_claims_sync(backend, rest) if rest else ()
        )
        for c in claims:
            if c[0] == "agg":
                out.append(
                    fn is not None
                    and bool(fn(Digest(c[1]), list(c[3]), c[2]))
                )
            else:
                out.append(next(rest_verdicts))
        return out

    with _spans.span("flatten"):
        digests, pks, sigs, spans = flatten_claims(claims)
    if not digests:
        # every claim here is an empty "shared" (zero members): a
        # certificate with no signatures proves nothing — vacuous truth
        # (all() over an empty span) would verify a votes=[] forgery
        return [False] * len(claims)
    # Wave-level fast path (CPU backend): ONE dalek-parity batch
    # equation over the whole flattened wave — in the common all-valid
    # case this replaces len(digests) OpenSSL verifies with a single
    # Pippenger multiscalar (measured 2-3.5x).  Sound because every
    # claim's verdict here is all(span): a passing batch implies every
    # span passes.  On a failing batch fall through to per-item
    # attribution (the adversary pays for that path, not us).
    if (
        len(digests) >= NATIVE_BATCH_MIN
        and getattr(backend, "supports_flat_batch", False)
        and all(len(d) == DIGEST_SIZE for d in digests)
    ):
        from . import native_ed25519

        with _spans.span("host.verify"):
            fast_ok = native_ed25519.available() and native_ed25519.batch_verify(
                b"".join(digests),
                DIGEST_SIZE,
                b"".join(pks),
                b"".join(sigs),
                len(digests),
                shared=False,
            )
        if fast_ok:
            return [e > s for s, e in spans]
    ok = backend.verify_many(digests, pks, sigs)
    return [all(ok[s:e]) if e > s else False for s, e in spans]


# ---------------------------------------------------------------------------
# Zero-copy wire -> device ingest (ISSUE 20)
#
# With the native transport, vote frames are parsed and packed IN C++
# (native/wave_pack.cpp) straight into bucket-shaped staging arenas at
# the reactor's read path.  When a dispatch wave's claim stream turns
# out to be exactly the packed arena prefix (receive order == claim
# submission order on a single-node transport), the service ADOPTS the
# arena — flatten/prepare become NumPy frombuffer views over memory the
# native parser already filled — instead of walking Python claim
# objects.  Adoption is an exact byte-level match; ANY divergence
# (deduped duplicates, stake/lookahead-dropped votes, mixed QC+vote
# waves, co-located multi-node dedup) falls back to flatten_claims.
# The arena is an accelerator, never a correctness dependency.
# ---------------------------------------------------------------------------

#: wire tag of a vote frame (consensus/wire.py TAG_VOTE).  Hardcoded —
#: importing consensus.wire here would cycle (wire imports crypto);
#: tests/test_wire_fuzz.py asserts this constant against the live one.
INGEST_TAG_VOTE = 1

DEFAULT_INGEST_RING_DEPTH = 6


def zero_copy_from_env() -> bool:
    """HOTSTUFF_ZERO_COPY: "0"/"off" disables the native ingest-arena
    fast path; default on (subject to native-packer availability)."""
    import os

    raw = os.environ.get("HOTSTUFF_ZERO_COPY", "").strip().lower()
    return raw not in ("0", "off", "no", "false", "none")


def ingest_arena_rows_from_env() -> int:
    """HOTSTUFF_INGEST_ARENA_ROWS: staging-arena capacity in claim rows;
    default = the largest canonical wave bucket, so every bucket-shaped
    wave is a prefix view of one arena."""
    import os

    raw = os.environ.get("HOTSTUFF_INGEST_ARENA_ROWS", "")
    try:
        rows = int(raw)
    except ValueError:
        rows = 0
    return rows if rows > 0 else DEFAULT_WAVE_BUCKETS[-1]


def ingest_ring_from_env() -> int:
    """HOTSTUFF_INGEST_RING: staging arenas in the native ring (min 2:
    one open for packing while sealed ones are in flight); default 6 —
    pipeline depth 2, a probe, and headroom before pack falls back."""
    import os

    raw = os.environ.get("HOTSTUFF_INGEST_RING", "")
    try:
        depth = int(raw)
    except ValueError:
        depth = 0
    return depth if depth >= 2 else DEFAULT_INGEST_RING_DEPTH


_pad_claim_cached: tuple | None = None


def make_pad_claim() -> tuple:
    """The deterministic filler claim for fixed-shape padding: one VALID
    self-contained ed25519 signature over a reserved digest.  Shared by
    the service's Python packing (_pack_wave) and the native ingest
    arenas (wp_set_pad pre-fills every arena row with it), so an
    adopted wave's pad rows are byte-identical to Python-padded ones."""
    global _pad_claim_cached
    if _pad_claim_cached is None:
        from .digest import Digest
        from .keys import generate_keypair
        from .signature import Signature

        pk, sk = generate_keypair(b"\xa5" * 32, 0xFFFF)
        digest = Digest.of(b"hotstuff_tpu wave pad claim v1")
        sig = Signature.new(digest, sk)
        _pad_claim_cached = (
            "one", digest.to_bytes(), pk.to_bytes(), sig.to_bytes()
        )
    return _pad_claim_cached


class AdoptedWave:
    """A sealed native staging arena adopted as one verification wave:
    ``n`` real claim rows followed by valid pad rows up to ``rows`` (the
    wave bucket).  The column views die when ``release`` recycles the
    arena — every consumer releases in a ``finally``."""

    __slots__ = (
        "ingest", "arena", "n", "rows",
        "dig", "pk", "sig", "dig_addr", "pk_addr", "sig_addr",
        "_released",
    )

    def __init__(self, ingest, arena: int, n: int, rows: int, info):
        from .native_ed25519 import column_view

        self.ingest = ingest
        self.arena = arena
        self.n = n
        self.rows = rows
        self.dig_addr, self.pk_addr, self.sig_addr = info[0], info[1], info[2]
        self.dig = column_view(self.dig_addr, rows * 32)
        self.pk = column_view(self.pk_addr, rows * 32)
        self.sig = column_view(self.sig_addr, rows * 64)
        self._released = False

    def release(self) -> None:
        """Recycle the arena (idempotent; runs on verifier slot threads
        — the native mutex serializes with event-loop packing)."""
        if not self._released:
            self._released = True
            self.ingest.packer.recycle(self.arena)


class ZeroCopyIngest:
    """Process-global zero-copy ingest plane: owns the native arena
    ring and the Python-side key mirror that proves adoption safety.

    ``note_vote_frame`` (event loop, receiver path) packs each vote's
    digest/pk/sig columns natively and mirrors the claim KEY (the exact
    bytes ``Vote.claim()`` would produce).  ``try_adopt`` (event loop,
    dispatcher) hands the arena over iff the wave's claims are exactly
    the packed key prefix — verdicts bind positionally downstream, so
    the match must be exact, and the mirror makes it checkable without
    decoding anything twice."""

    def __init__(
        self, capacity: int | None = None, ring_depth: int | None = None
    ):
        from .native_ed25519 import WavePacker

        cap = capacity if capacity else ingest_arena_rows_from_env()
        depth = ring_depth if ring_depth else ingest_ring_from_env()
        self.packer = WavePacker(cap, depth)
        pad = make_pad_claim()
        if not self.packer.set_pad(pad[1], pad[2], pad[3]):
            raise RuntimeError("wave packer pad install failed")
        self._keys: list[tuple] = []
        self.packed_votes = 0
        self.zero_copy_waves = 0
        self.fallback_waves = 0

    @property
    def active(self) -> bool:
        """Any packed votes pending adoption?  The dispatcher skips the
        adoption attempt entirely when nothing was packed (sim/asyncio
        transports, non-vote traffic)."""
        return bool(self._keys)

    def note_vote_frame(self, frame: bytes) -> bool:
        r = self.packer.pack_vote(frame)
        if isinstance(r, int):
            if r == -2:
                # open arena full: the pack stream outran adoption (an
                # idle service, or votes that never became claims) —
                # resync rather than wedge with a full arena forever
                self._resync()
            return False
        _slot, digest = r
        # the claim key mirrors Vote.claim(): (digest, author pk, sig) —
        # pk/sig slices at the fixed ed25519 vote-frame offsets
        self._keys.append((digest, frame[45:77], frame[81:145]))
        self.packed_votes += 1
        return True

    def try_adopt(self, claims: list, buckets) -> AdoptedWave | None:
        """Adopt the packed prefix as ``claims``' wave, or None.

        On a mismatch that OVERLAPS the packed stream (a packed vote is
        in this wave but not at its packed position: dedup, a dropped
        vote, a mixed QC+vote wave) the open arena is discarded — those
        rows can never line up again.  A wave fully DISJOINT from the
        packed keys (pure QC/proposal wave between vote bursts) leaves
        the arena untouched for the next wave."""
        keys = self._keys
        n = len(claims)
        if n <= len(keys):
            for i in range(n):
                c = claims[i]
                if c[0] != "one" or (c[1], c[2], c[3]) != keys[i]:
                    break
            else:
                rows = next((b for b in buckets if b >= n), None)
                if rows is None or rows > self.packer.capacity:
                    rows = n
                arena = self.packer.seal(n)
                if arena is None:
                    self._resync()
                    return None
                info = self.packer.arena_info(arena)
                if info is None:  # unreachable right after seal; be safe
                    self.packer.recycle(arena)
                    self._resync()
                    return None
                del keys[:n]
                self.zero_copy_waves += 1
                return AdoptedWave(self, arena, n, rows, info)
        key_set = set(keys)
        if any(
            c[0] == "one" and (c[1], c[2], c[3]) in key_set for c in claims
        ):
            self._resync()
            self.fallback_waves += 1
        return None

    def _resync(self) -> None:
        self.packer.discard()
        self._keys.clear()

    def counters(self) -> dict:
        out = self.packer.counters()
        out["zero_copy_waves"] = self.zero_copy_waves
        out["fallback_waves"] = self.fallback_waves
        return out


#: None = never tried; False = disabled/unavailable (cached); else the
#: live ZeroCopyIngest
_zero_copy: "ZeroCopyIngest | bool | None" = None


def zero_copy_ingest() -> "ZeroCopyIngest | None":
    """The process-global ingest plane, created on first use by a
    receiver; None when disabled (``HOTSTUFF_ZERO_COPY=0``) or the
    native packer is unavailable (no toolchain — cached, never retried
    per frame)."""
    global _zero_copy
    if _zero_copy is None:
        created: ZeroCopyIngest | bool = False
        if zero_copy_from_env():
            from . import native_ed25519

            if native_ed25519.wave_pack_available():
                try:
                    created = ZeroCopyIngest()
                except Exception as e:  # noqa: BLE001 — ingest must
                    # degrade to the Python path, never break receive
                    log.info("zero-copy ingest unavailable: %s", e)
        _zero_copy = created
    return _zero_copy if type(_zero_copy) is ZeroCopyIngest else None


def zero_copy_ingest_if_active() -> "ZeroCopyIngest | None":
    """The ingest plane IF a receiver already created it — the
    dispatcher-side accessor: never triggers a native build from the
    verify path."""
    return _zero_copy if type(_zero_copy) is ZeroCopyIngest else None


def ingest_note_frame(frame: bytes) -> None:
    """Receiver-side hook: feed one raw inbound frame to the zero-copy
    plane just before handler dispatch.  Only vote frames are packed;
    anything else is a cheap tag test.  Never raises into the receive
    loop."""
    if not frame or frame[0] != INGEST_TAG_VOTE:
        return
    ing = zero_copy_ingest()
    if ing is not None:
        try:
            ing.note_vote_frame(frame)
        except Exception:  # noqa: BLE001 — a packer bug must not kill
            # the connection; the wave simply falls back to Python
            log.exception("zero-copy vote pack failed")


def eval_claims_arena(backend, wave: AdoptedWave, claims: list) -> list[bool]:
    """Evaluate an adopted zero-copy wave: the arena columns ARE the
    staging arrays — no flatten, no per-claim bytes.  Device backends
    verify through ``verify_packed`` (frombuffer views over the columns
    feed the jitted bucket callable at the pre-padded bucket shape);
    CPU backends run ONE native batch equation straight from the column
    addresses.  Any miss (failing batch equation -> per-item
    attribution, backend without a packed path) falls back to
    ``eval_claims_sync`` on the claim list.  Always releases the
    arena."""
    try:
        n = wave.n
        fn = getattr(backend, "verify_packed", None)
        if fn is not None:
            out = fn(wave.dig, wave.pk, wave.sig, wave.rows)
            return [bool(v) for v in out[:n]]
        from . import native_ed25519

        if (
            n >= NATIVE_BATCH_MIN
            and getattr(backend, "supports_flat_batch", False)
            and native_ed25519.available()
        ):
            with _spans.span("host.verify"):
                fast_ok = native_ed25519.batch_verify_columns(
                    wave.dig_addr, wave.pk_addr, wave.sig_addr, n
                )
            if fast_ok:
                return [True] * n
        return eval_claims_sync(backend, claims)
    finally:
        wave.release()


#: every live _DispatchLoop, for interpreter-exit shutdown (satellite:
#: no leaked thread keeps the interpreter from exiting — slot threads
#: are daemons AND get an explicit sentinel at atexit)
_live_dispatch_loops: "set[_DispatchLoop]" = set()


@atexit.register
def _shutdown_dispatch_loops() -> None:
    for dl in list(_live_dispatch_loops):
        dl.close()


class _DispatchLoop:
    """The dedicated dispatch loop (ISSUE 6): ``depth`` long-lived slot
    threads over one queue, replacing the per-service
    ``ThreadPoolExecutor`` hop (thread-pool bookkeeping, per-submit
    ``concurrent.futures`` machinery, idle-timeout respawn).  Each slot
    thread keeps its own thread-local staging scratch in the device
    backend, so a slot is one entry of a preallocated staging-buffer
    ring: with ``depth`` slots, up to ``depth`` waves stage/execute
    concurrently and never allocate fresh host buffers.

    Completion callbacks run ON the slot thread — callers hop back to
    their event loop with ``call_soon_threadsafe``.  Threads are lazy
    (first ``submit`` starts them), daemonic, and shut down cleanly on
    ``close()`` and at interpreter exit."""

    def __init__(self, depth: int):
        self.depth = max(1, int(depth))
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._threads: list[threading.Thread] = []
        self._closed = False
        _live_dispatch_loops.add(self)

    def submit(self, fn, on_done) -> None:
        """Queue ``fn`` for the next free slot thread;
        ``on_done(result, exc)`` runs on that thread when it finishes."""
        if self._closed:
            raise RuntimeError("dispatch loop is closed")
        if not self._threads:
            for i in range(self.depth):
                t = threading.Thread(
                    target=self._worker,
                    name=f"verify-slot-{i}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)
        self._q.put((fn, on_done))

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, on_done = item
            try:
                result, exc = fn(), None
            except BaseException as e:  # noqa: BLE001 — delivered to the
                result, exc = None, e  # waiter, never raised in the slot
            try:
                on_done(result, exc)
            except Exception:  # noqa: BLE001 — a delivery failure must
                log.exception("verify dispatch delivery failed")

    def close(self, wait: bool = False) -> None:
        """Stop the slot threads after their current job (idempotent)."""
        if self._closed:
            return
        self._closed = True
        _live_dispatch_loops.discard(self)
        for _ in range(len(self._threads)):
            self._q.put(None)
        if wait:
            for t in self._threads:
                t.join(timeout=1.0)
        self._threads = []


class AsyncVerifyService:
    """Coalesces claim batches and (for device backends) dispatches them
    from a worker thread.

    One service instance per (event loop, device backend): in-process
    committees share the backend object (node.LazyDeviceVerifier keeps a
    per-kind singleton), so every node's claims coalesce into the same
    dispatch stream — one tunnel round trip covers the whole committee's
    wave.  CPU backends get an inline service (``device=False``): claims
    evaluate synchronously at the submit point, zero added latency.
    """

    _registry: dict[tuple, tuple] = {}  # (loop id, kind) -> (loop, service)
    _serial = 0  # distinguishes private services' cumulative stat lines

    def __init__(
        self, backend, device: bool = False, pipeline_depth: int | None = None
    ):
        AsyncVerifyService._serial += 1
        # stable tag for the scraped stats line: kind#pid.serial —
        # cumulative counters from different service instances must be
        # separable in MERGED logs: the serial separates private
        # per-core services (--no-claim-dedup) within one process, the
        # pid separates processes (every node process restarts the
        # class counter at 1, and the parser sums the last line per tag)
        import os

        kind = getattr(backend, "async_kind", None) or getattr(
            backend, "name", "cpu"
        )
        self._backend_kind = kind
        self._stats_tag = f"{kind}#{os.getpid()}.{AsyncVerifyService._serial}"
        # For inline services ``backend`` is the VerifierBackend itself.
        # For device services it is the HOST (node.LazyDeviceVerifier):
        # ``host.device_ready`` gates routing (never materialize jax or
        # cold-compile mid-consensus), ``host.async_backend`` is the
        # forced-device dispatch view, ``host.cpu_backend`` the fallback.
        self.backend = backend
        self.device = device
        self._pending: list[tuple[list, asyncio.Future]] = []
        # profiling: perf_counter_ns stamps of device-path submissions in
        # the current coalescing window (empty unless HOTSTUFF_PROFILE)
        self._arrivals: list[int] = []
        self._task: asyncio.Task | None = None
        self._dispatch: _DispatchLoop | None = None
        # fixed-shape wave padding + round coalescing (ISSUE 6).
        # Packing only applies when the backend advertises
        # supports_wave_padding (real device verifiers): synthetic test
        # hosts and CPU backends see exactly the claims submitted.
        # Bucket shapes resolve dynamically (see the wave_buckets
        # property): the mesh backend's shapes only exist once the
        # device host materializes it at warmup.
        self.coalesce_window_s = coalesce_window_s_from_env()
        self._pad_claim: tuple | None = None
        self.packed_waves = 0
        self.pad_sigs = 0
        # adaptive routing state
        self._device_ewma_s: float | None = None
        self._last_probe = 0.0
        # dispatch pipeline (ISSUE 5): wave serial -> monotonic deadline
        # stamp for every device dispatch currently in flight.  Routing
        # reads occupancy (len) and overdue-ness; landers and probe
        # done-callbacks remove their wave and signal _slot_free.
        self.pipeline_depth = (
            max(1, int(pipeline_depth))
            if pipeline_depth
            else pipeline_depth_from_env()
        )
        self._inflight: dict[int, float] = {}
        self._wave_serial = 0
        self._slot_free: asyncio.Event | None = None
        self._landers: set[asyncio.Task] = set()
        self.dispatches = 0
        self.device_dispatches = 0
        self.cpu_dispatches = 0
        self.probe_dispatches = 0
        # mesh route label (ISSUE 7): device waves dispatched into a
        # mesh-sharded backend count separately so committee runs can
        # tell sharded dispatches from single-device ones in the scaling
        # SUMMARY's route column (device_dispatches stays the total)
        self.mesh_dispatches = 0
        self._device_route_label = (
            "mesh" if ("sharded" in str(kind) or "mesh" in str(kind))
            else "device"
        )
        self.device_sigs = 0
        self.cpu_sigs = 0
        # compact-certificate ("agg") claims and the signer count they
        # covered — the one-pairing route (ISSUE 9); surfaced on the
        # stats line for benchmark/logs.py's agg columns
        self.agg_claims = 0
        self.agg_sigs = 0
        self.deadline_misses = 0
        self.pipeline_waits = 0
        self.peak_inflight = 0
        # zero-copy ingest plane (ISSUE 20): waves adopted straight from
        # a native staging arena vs. vote-overlapping waves that had to
        # fall back to the Python flatten path
        self.zero_copy_waves = 0
        self.zero_copy_sigs = 0
        self.fallback_waves = 0
        self._next_stats_log = 0.0
        # Telemetry instruments (ISSUE 1), labelled by the service tag.
        # All None when telemetry is off — every hot-path touch below is
        # guarded on ``_tel_wave`` so the disabled cost is one attribute
        # test per wave.
        self._tel_wave = None
        self._tel_claims_submitted = None
        self._tel_claims_unique = None
        self._tel_device_wall = None
        self._tel_host_wall = None
        self._tel_route = None
        self._tel_zero_copy = None
        self._tel_fallback = None
        from .. import telemetry

        if telemetry.enabled():
            reg = telemetry.registry()
            # the backend label keeps multi-backend runs (cpu + tpu + bls
            # services in one process) from aliasing into one series when
            # dashboards aggregate away the per-instance svc tag
            labels = {"svc": self._stats_tag, "backend": kind}
            self._tel_claims_submitted = reg.counter(
                "verify_claims_submitted",
                "Verification claims submitted (pre-dedup, all cores)",
                labels,
            )
            self._tel_claims_unique = reg.counter(
                "verify_claims_unique",
                "Unique claims actually evaluated after cross-core dedup",
                labels,
            )
            self._tel_wave = reg.histogram(
                "verify_wave_sigs",
                "Signatures per coalesced dispatch wave",
                labels,
                bounds=telemetry.SIZE_BOUNDS,
            )
            self._tel_device_wall = reg.float_counter(
                "verify_device_wall_seconds",
                "Wall seconds spent inside device verify dispatches",
                labels,
            )
            self._tel_host_wall = reg.float_counter(
                "verify_host_wall_seconds",
                "Wall seconds spent in host (CPU) claim evaluation",
                labels,
            )
            self._tel_route = {
                r: reg.counter(
                    "verify_route",
                    "Dispatch waves by routing decision",
                    {**labels, "route": r},
                )
                for r in ("device", "mesh", "cpu", "probe", "wait")
            }
            self._tel_zero_copy = reg.counter(
                "ingest_zero_copy_waves",
                "Waves adopted straight from a native ingest arena",
                labels,
            )
            self._tel_fallback = reg.counter(
                "ingest_fallback_waves",
                "Vote-overlapping waves that fell back to Python flatten",
                labels,
            )
            reg.gauge(
                "verify_pending_batches",
                "Submissions queued for the next dispatch wave",
                labels,
                fn=lambda: len(self._pending),
            )
            reg.gauge(
                "verify_inflight_waves",
                "Device dispatch waves currently in flight",
                labels,
                fn=lambda: len(self._inflight),
            )

    @property
    def wave_buckets(self) -> tuple[int, ...]:
        """The fixed wave shapes for this service's backend, resolved
        per access (ISSUE 7): a device host only advertises its
        ``wave_bucket_shapes`` once the device backend materializes at
        warmup, and the mesh backend's shapes depend on the mesh size —
        resolving lazily means the service picks up the mesh-multiple
        ladder the moment it exists instead of freezing the canonical
        default at construction."""
        return resolve_wave_buckets(self.backend)

    @property
    def _device_busy(self) -> bool:
        """Compat view of the pre-pipeline single-in-flight gate: true
        while ANY device dispatch is in flight."""
        return bool(self._inflight)

    # ---- acquisition -------------------------------------------------------

    @classmethod
    def for_backend(cls, backend) -> "AsyncVerifyService":
        """The service for ``backend`` on the running loop.  Device-host
        backends (``async_kind`` set) share one service per (loop, kind)
        pair — in-process committees all submit into the same dispatch
        stream; everything else gets a private inline service.

        ``HOTSTUFF_NO_CLAIM_DEDUP=1`` gives every core a PRIVATE device
        service instead: no cross-core claim coalescing or dedup.  This
        is the honesty knob for in-process scale results (VERDICT r4
        weak #2) — a real one-node-per-host deployment gets zero dedup,
        and the per-node capability must be measurable without the
        co-location artifact."""
        import os

        kind = getattr(backend, "async_kind", None)
        if kind is None:
            return cls(backend, device=False)
        if os.environ.get("HOTSTUFF_NO_CLAIM_DEDUP"):
            return cls(backend, device=True)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # constructed outside a loop (direct-construction tests):
            # a private service — coalescing across cores is lost but
            # nothing binds to a wrong loop
            return cls(backend, device=True)
        # prune entries bound to closed loops (repeated benchmark runs /
        # test loops in one process): each would otherwise pin its loop
        # object plus an idle dispatch loop's slot threads forever
        stale = [
            (k, svc)
            for k, (stored, svc) in cls._registry.items()
            if stored.is_closed()
        ]
        for k, svc in stale:
            cls._registry.pop(k, None)
            svc._shutdown_dispatch()
        key = (id(loop), kind)
        hit = cls._registry.get(key)
        # the stored loop is compared by identity and liveness: an id()
        # reused by a new loop (or a closed loop's leftover) must get a
        # fresh service, or submissions would wait on a dead dispatcher
        if hit is not None and hit[0] is loop and not loop.is_closed():
            return hit[1]
        service = cls(backend, device=True)
        cls._registry[key] = (loop, service)
        return service

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for lander in list(self._landers):
            lander.cancel()
        self._landers.clear()
        self._shutdown_dispatch()
        for key, (_, service) in list(self._registry.items()):
            if service is self:
                del self._registry[key]

    def _shutdown_dispatch(self) -> None:
        """Stop this service's dispatch loop (service close / stale-loop
        eviction in for_backend / interpreter exit via the loop's own
        atexit hook)."""
        if self._dispatch is not None:
            self._dispatch.close()
            self._dispatch = None

    # ---- submission --------------------------------------------------------

    async def verify_claims(self, claims: list) -> list[bool]:
        """Verdict per claim.  Inline services evaluate immediately;
        device services enqueue and await the coalesced dispatch."""
        if not claims:
            return []
        if not self.device:
            if self._tel_wave is None:
                return eval_claims_sync(self.backend, claims)
            # inline services have no dedup stage: submitted == unique
            t0 = time.perf_counter()
            out = eval_claims_sync(self.backend, claims)
            self._tel_host_wall.add(time.perf_counter() - t0)
            self._tel_claims_submitted.inc(len(claims))
            self._tel_claims_unique.inc(len(claims))
            self._tel_wave.observe(
                sum(claim_sig_count(c) for c in claims)
            )
            return out

        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((claims, fut))
        if _spans.recorder() is not None:
            self._arrivals.append(time.perf_counter_ns())
        if self._task is None or self._task.done():
            # the dispatcher task drains all pending batches then exits —
            # no long-lived task to leak across loops or shutdowns
            self._task = loop.create_task(
                self._run(), name="verify-dispatcher"
            )
        return await fut

    # ---- the dispatcher ----------------------------------------------------

    def _deadline_s(self) -> float:
        """Per-dispatch deadline: a tunnel stall mid-dispatch must not
        stall the committee.  Backends may raise the floor (BLS: an
        adversarial storm legitimately takes ~0.4 s off-loop;
        re-running it inline would BE the stall)."""
        return max(
            getattr(self.backend, "dispatch_deadline_s", 0.1),
            4 * (self._device_ewma_s or 0.1),
        )

    def _cpu_estimate_s(self, n_sigs: int) -> float:
        # the CPU alternative is the batched equation for large waves
        # (eval_claims_sync flat fast path) — but only when that path
        # actually exists on this host; else the per-sig loop
        from .native_ed25519 import available as _native_available

        if n_sigs >= NATIVE_BATCH_MIN and _native_available():
            return cpu_batch_estimate_s(n_sigs)
        return n_sigs * CPU_US_PER_SIG * 1e-6

    # ---- fixed-shape wave packing (ISSUE 6) --------------------------------

    @property
    def _packing_on(self) -> bool:
        """Padding applies only when buckets are configured AND the
        backend opted in (``supports_wave_padding`` — the real ed25519
        device verifiers).  Aggregate-preferring backends (BLS) and CPU
        fallbacks see exactly the submitted claims."""
        return bool(
            self.wave_buckets
            and getattr(self.backend, "supports_wave_padding", False)
        )

    def _pad_claim_tuple(self) -> tuple:
        """The deterministic filler claim for fixed-shape padding: one
        VALID self-contained ed25519 signature over a reserved digest.
        Claim verdicts are per-claim (``all()`` over each claim's own
        span of the flat arrays), so a valid pad can never flip a real
        claim's verdict — and because it is valid, a packed wave that
        falls back to the CPU batch equation still passes when every
        real signature does.  Shared with the native ingest arenas
        (``make_pad_claim``) so adopted pad rows are byte-identical."""
        if self._pad_claim is None:
            self._pad_claim = make_pad_claim()
        return self._pad_claim

    def _pack_wave(self, claims: list, n_sigs: int) -> list:
        """Pad a device-routed wave to the smallest bucket >= n_sigs
        with copies of the pad claim.  Exact fits and waves past the
        largest bucket pass through unpadded (the backend chunks
        oversized batches on its own grid)."""
        bucket = next((b for b in self.wave_buckets if b >= n_sigs), None)
        if bucket is None or bucket == n_sigs:
            return claims
        pad = self._pad_claim_tuple()
        self.packed_waves += 1
        self.pad_sigs += bucket - n_sigs
        return list(claims) + [pad] * (bucket - n_sigs)

    def warm_buckets(self) -> None:
        """Pre-compile every wave bucket shape (ISSUE 6 warmup): drive
        one pad-only wave per bucket size through the forced-device
        dispatch view, synchronously, so the first real wave of any
        bucket hits a warm jitted callable instead of paying a
        mid-consensus compile.  No-op for inline services, non-padding
        backends, and hosts whose device isn't materialized yet.

        With a mesh-sharded backend the resolved buckets ARE that
        mesh's pad-grid entries (mesh-multiple shapes up to the 4096
        train bucket), so this loop pre-compiles every (bucket x mesh)
        kernel shape the tunnel can dispatch (ISSUE 7)."""
        if not (self.device and self._packing_on):
            return
        if not getattr(self.backend, "device_ready", True):
            return
        target = getattr(self.backend, "async_backend", self.backend)
        pad = self._pad_claim_tuple()
        for bucket in self.wave_buckets:
            eval_claims_sync(target, [pad] * bucket)

    def _route_device(self, n_sigs: int) -> str:
        """Route this batch: "device", "cpu", "probe", or "wait".

        Never the device before its backend is materialized AND warm (a
        cold jax import or Mosaic compile mid-consensus would blow the
        round timeout — the host sets ``device_ready`` at warmup), and
        never while any in-flight dispatch is OVERDUE: queueing waves
        behind a tunnel-stalled dispatch was measured to stall the
        whole committee (32-node run collapsed to 1/3 the CPU rate on
        one stall), so a stall pushes traffic to the CPU exactly like
        the old single-in-flight busy gate did.  Below the depth cap,
        compare the occupancy-discounted device EWMA (waves already in
        flight share the tunnel round trip) against the CPU estimate.
        "wait": the pipeline is full but healthy and the device is
        still the right answer — the dispatcher queues for a slot
        (bounded by the earliest in-flight deadline) instead of
        spilling to the CPU.  "probe": the EWMA says the device loses,
        but it's time to re-measure — the caller dispatches a
        measurement-only copy and serves the batch from the CPU, so
        probing a degraded tunnel never adds wave latency; probes take
        a pipeline slot, so a full pipeline never probes."""
        import os

        if os.environ.get("HOTSTUFF_FORCE_CPU_ROUTE"):
            return "cpu"  # diagnostic: keep jax warm but never dispatch
        if not getattr(self.backend, "device_ready", True):
            return "cpu"
        now = time.monotonic()
        if any(stamp < now for stamp in self._inflight.values()):
            # an in-flight dispatch blew its deadline — the tunnel is
            # stalling; route around it until the stuck wave lands
            return "cpu"
        occupancy = len(self._inflight)
        forced = bool(os.environ.get("HOTSTUFF_FORCE_DEVICE_ROUTE"))
        offload = getattr(self.backend, "always_offload", False)
        if occupancy >= self.pipeline_depth:
            # depth cap: queue when the device is (or is forced to be)
            # the right route, otherwise serve from the CPU.  No probe
            # here — a probe would need the slot we don't have.
            if forced or offload or self._device_ewma_s is None:
                return "wait"
            marginal = self._device_ewma_s * PIPELINE_MARGINAL_COST
            if marginal <= self._cpu_estimate_s(n_sigs):
                return "wait"
            return "cpu"
        if forced:
            # profiling knob (benchmark profile --route device): pin
            # warmed-up waves to the device so the waterfall measures the
            # dispatch pipeline, not the cost-model's mood — gated AFTER
            # the readiness/overdue/depth checks, which stay load-bearing
            return "device"
        if offload:
            # backends whose offload frees the loop unconditionally
            # (BLS native pairings: ctypes releases the GIL) — no
            # cost-model routing needed
            return "device"
        if self._device_ewma_s is None:
            return "device"  # optimistic first dispatch
        marginal = self._device_ewma_s * (
            1.0 if occupancy == 0 else PIPELINE_MARGINAL_COST
        )
        if marginal <= self._cpu_estimate_s(n_sigs):
            return "device"
        if now - self._last_probe >= _PROBE_INTERVAL_S:
            self._last_probe = now
            return "probe"
        return "cpu"

    def _spawn_device(
        self,
        loop,
        claims: list,
        measure_only: bool = False,
        deadline: float | None = None,
        wave: "AdoptedWave | None" = None,
    ):
        """Start a device dispatch on the dedicated dispatch loop and
        register it in the in-flight table (occupancy + deadline stamp
        drive routing).  The slot thread delivers completion back to the
        event loop with ``call_soon_threadsafe``; delivery frees the
        slot, wakes any dispatcher queued in _wait_for_slot, and marks
        exceptions retrieved so abandoned waves (deadline-miss /
        measurement-only) never warn.  Returns ``(completion_future,
        end_holder)``; the slot thread appends its completion stamp to
        ``end_holder`` under the profiler so the lander can charge the
        slot-thread -> loop wakeup gap to verdict.fanout."""
        if self._dispatch is None:
            # one slot thread per pipeline stage: jax.block_until_ready
            # releases the GIL, so while wave N parks on the device,
            # wave N+1 stages on the next slot — that overlap IS the
            # pipeline.  The backends are thread-compatible (table
            # rebuilds publish atomically under their own lock) and pool
            # staging scratch per thread, so each slot reuses its own
            # preallocated buffers wave after wave.
            self._dispatch = _DispatchLoop(self.pipeline_depth)
        self._wave_serial += 1
        serial = self._wave_serial
        # guarded-by: gil -- written here on the event loop, popped by
        # _deliver (loop) and by _on_done's loop-closed fallback (slot
        # thread); every access is a single dict bytecode, atomic under
        # the GIL, and the routing reads tolerate one-wave staleness
        self._inflight[serial] = time.monotonic() + (
            deadline if deadline is not None else self._deadline_s()
        )
        self.peak_inflight = max(self.peak_inflight, len(self._inflight))
        rec = _spans.recorder()
        t_spawn = time.perf_counter_ns() if rec is not None else None
        if rec is not None:
            # occupancy annotation (value encoded in the dur field, not
            # a duration — rendered as a counter on the Perfetto track)
            rec.add("pipeline.occupancy", t_spawn, len(self._inflight))
        end_holder: list[int] = []
        fut: asyncio.Future = loop.create_future()

        def _deliver(result, exc):
            # on the event loop: free the slot, resolve the wave future
            self._inflight.pop(serial, None)
            if self._slot_free is not None:
                self._slot_free.set()
            if fut.cancelled():
                return
            if exc is None:
                fut.set_result(result)
            elif measure_only:
                log.warning("device measurement dispatch failed: %s", exc)
                fut.set_result(None)
            else:
                fut.set_exception(exc)
                # mark retrieved: the lander re-raises via result(), but
                # a deadline-missed wave is abandoned — without this the
                # GC would warn about the never-retrieved exception
                fut.exception()

        def _on_done(result, exc):
            # on the slot thread: hop back to the service's event loop
            try:
                loop.call_soon_threadsafe(_deliver, result, exc)
            except RuntimeError:
                # the loop closed mid-flight (benchmark loop teardown /
                # interpreter exit): free the slot directly so routing
                # never sees a phantom in-flight wave
                self._inflight.pop(serial, None)

        self._dispatch.submit(
            lambda: self._dispatch_sync(claims, t_spawn, end_holder, wave),
            _on_done,
        )
        return fut, end_holder

    def _dispatch_sync(
        self,
        claims: list,
        t_spawn: int | None = None,
        end_holder: list | None = None,
        wave: "AdoptedWave | None" = None,
    ) -> list[bool]:
        """Slot-thread body: evaluate on the forced-device dispatch
        view, timing the dispatch for the routing EWMA.  An adopted
        zero-copy wave stages from its arena columns instead of
        flattening claim tuples (released inside eval_claims_arena)."""
        rec = _spans.recorder()
        if rec is not None:
            t_enter = time.perf_counter_ns()
            if t_spawn is not None:
                # dispatch-loop handoff -> slot thread entry (thread
                # wakeup + any queueing behind a previous dispatch)
                rec.add("stage.slot_wait", t_spawn, t_enter - t_spawn)
        target = getattr(self.backend, "async_backend", self.backend)
        t0 = time.perf_counter()
        if wave is not None:
            out = eval_claims_arena(target, wave, claims)
        else:
            out = eval_claims_sync(target, claims)
        wall = time.perf_counter() - t0
        if rec is not None:
            end_ns = time.perf_counter_ns()
            rec.add("dispatch.wall", t_enter, end_ns - t_enter)
            if end_holder is not None:
                end_holder.append(end_ns)
        if self._tel_device_wall is not None:
            self._tel_device_wall.add(wall)
        ewma = self._device_ewma_s
        # guarded-by: gil -- written on the slot thread, read by the
        # loop-side router (_route_device/_deadline_s); a float rebind
        # is one atomic store and a stale read only skews the EWMA by
        # one sample
        self._device_ewma_s = (
            wall if ewma is None else (1 - _EWMA_ALPHA) * ewma + _EWMA_ALPHA * wall
        )
        return out

    async def _wait_for_slot(self) -> None:
        """Depth-cap backpressure: park until an in-flight wave lands or
        the earliest in-flight deadline expires (the wave went overdue —
        the next routing pass serves from the CPU)."""
        if self._slot_free is None:
            self._slot_free = asyncio.Event()
        self._slot_free.clear()
        if len(self._inflight) < self.pipeline_depth:
            return  # a wave landed between the route decision and here
        earliest = min(self._inflight.values())
        timeout = max(0.005, earliest - time.monotonic() + 0.005)
        try:
            await asyncio.wait_for(self._slot_free.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        # fresh per dispatcher spawn: the event must belong to the loop
        # this dispatcher runs on (services can outlive benchmark loops)
        self._slot_free = asyncio.Event()
        while True:
            # let every task woken by the same network wave enqueue its
            # claims before the batch departs (two passes: receiver ->
            # core handoff, core -> submit)
            await asyncio.sleep(0)
            await asyncio.sleep(0)
            if self.coalesce_window_s > 0.0 and self._pending:
                # QC+TC coalescing (ISSUE 6): hold the wave open for a
                # round window so both certificate kinds produced by
                # the same round merge into ONE tunnel crossing — the
                # verdict table fans each claim back to its own
                # submitters on readback
                await asyncio.sleep(self.coalesce_window_s)
            batch, self._pending = self._pending, []
            arrivals, self._arrivals = self._arrivals, []
            if not batch:
                return  # drained — the next submit respawns the task
            rec = _spans.recorder()
            wave_t0 = min(arrivals) if (rec is not None and arrivals) else None
            if wave_t0 is not None:
                rec.add(
                    "coalesce.wait",
                    wave_t0,
                    time.perf_counter_ns() - wave_t0,
                )
            # Deduplicate identical claims across submissions: a claim's
            # verdict is a PURE function of (digest, pk, sig) bytes, so
            # one evaluation serves every submitter — in a co-located
            # committee one broadcast proposal arrives at every core in
            # the same wave, and without dedup the service would verify
            # the same certificate once per node (n x the work this
            # layer exists to avoid).  Each core still applies its OWN
            # stake/quorum/safety rules to the verdicts; no per-node
            # acceptance state crosses node boundaries.
            unique: dict = {}
            for cs, _ in batch:
                for c in cs:
                    unique.setdefault(c, None)
            claims = list(unique.keys())
            n_sigs = sum(claim_sig_count(c) for c in claims)
            agg_in_wave = [c for c in claims if c[0] == "agg"]
            if agg_in_wave:
                self.agg_claims += len(agg_in_wave)
                self.agg_sigs += sum(len(c[3]) for c in agg_in_wave)
            self.dispatches += 1
            if self._tel_wave is not None:
                self._tel_claims_submitted.inc(sum(len(cs) for cs, _ in batch))
                self._tel_claims_unique.inc(len(claims))
                self._tel_wave.observe(n_sigs)

            # zero-copy adoption (ISSUE 20): if the native transport
            # packed this wave's votes into a staging arena and the
            # claim stream matches the packed prefix exactly, adopt the
            # arena — downstream flatten/prepare become frombuffer
            # views.  Passive accessor: the verify path never triggers
            # a native build; only receivers create the plane.
            adopted = None
            ing = zero_copy_ingest_if_active()
            if ing is not None and ing.active:
                with _spans.span("native.pack"):
                    fb_before = ing.fallback_waves
                    adopted = ing.try_adopt(claims, self.wave_buckets)
                if adopted is not None:
                    self.zero_copy_waves += 1
                    self.zero_copy_sigs += n_sigs
                    if self._tel_zero_copy is not None:
                        self._tel_zero_copy.inc()
                elif ing.fallback_waves != fb_before:
                    self.fallback_waves += 1
                    if self._tel_fallback is not None:
                        self._tel_fallback.inc()
            try:
                with _spans.span("route.decide"):
                    route = self._route_device(n_sigs)
                waited = False
                while route == "wait":
                    # full pipeline, healthy and device-preferred: queue
                    # for a slot (wave K+1 backpressure) instead of
                    # spilling to the CPU, then re-route — a freed slot
                    # goes to the device, an expired deadline to the CPU
                    if not waited:
                        waited = True
                        self.pipeline_waits += 1
                        if self._tel_route is not None:
                            self._tel_route["wait"].inc()
                    t_w = (
                        time.perf_counter_ns() if rec is not None else None
                    )
                    await self._wait_for_slot()
                    if t_w is not None:
                        rec.add(
                            "pipeline.wait",
                            t_w,
                            time.perf_counter_ns() - t_w,
                        )
                    route = self._route_device(n_sigs)
                if self._tel_route is not None:
                    # sharded backends label their device waves "mesh"
                    # so dashboards separate multi-chip dispatches
                    self._tel_route[
                        self._device_route_label
                        if route == "device"
                        else route
                    ].inc()
                dispatch_claims = claims
                if (
                    route in ("device", "probe")
                    and self._packing_on
                    and adopted is None
                ):
                    # fixed-shape wave (ISSUE 6): pad to the bucket so
                    # the dispatch hits a warm jitted callable.  Probes
                    # pack too — they measure the shape real waves use.
                    # Adopted waves skip this: the arena is already
                    # bucket-shaped with native-padded rows.
                    with _spans.span("stage.pack"):
                        dispatch_claims = self._pack_wave(claims, n_sigs)
                if route == "probe":
                    # measurement-only device dispatch: results are
                    # discarded (EWMA updates when it lands); the batch
                    # itself is served from the CPU so a degraded tunnel
                    # never adds wave latency
                    self.probe_dispatches += 1
                    self._spawn_device(
                        loop, dispatch_claims, measure_only=True,
                        wave=adopted,
                    )
                    adopted = None  # released by the probe dispatch
                if route == "device":
                    self.device_dispatches += 1
                    if self._device_route_label == "mesh":
                        self.mesh_dispatches += 1
                    self.device_sigs += n_sigs
                    deadline = self._deadline_s()
                    exec_fut, end_holder = self._spawn_device(
                        loop, dispatch_claims, deadline=deadline,
                        wave=adopted,
                    )
                    adopted = None  # released by the slot thread
                    # async readback (ISSUE 5): the dispatcher does NOT
                    # await the device — a per-wave lander task lands
                    # this wave's verdicts when its completion future
                    # resolves, so waves complete out of order and a
                    # failure poisons only its own batch.  The
                    # dispatcher loops straight back to staging the
                    # next wave.
                    lander = loop.create_task(
                        self._land_device(
                            batch, dispatch_claims, exec_fut, end_holder,
                            wave_t0, deadline,
                        ),
                        name="verify-lander",
                    )
                    self._landers.add(lander)
                    lander.add_done_callback(self._landers.discard)
                    continue
                self.cpu_dispatches += 1
                self.cpu_sigs += n_sigs
                if adopted is not None:
                    wave_held, adopted = adopted, None
                    await self._serve_cpu_arena(batch, claims, wave_held)
                else:
                    await self._serve_cpu(batch)
                if wave_t0 is not None:
                    rec.add(
                        "e2e", wave_t0, time.perf_counter_ns() - wave_t0
                    )
                self._log_stats()
            except asyncio.CancelledError:
                if adopted is not None:
                    adopted.release()
                for _, fut in batch:
                    if not fut.done():
                        fut.cancel()
                raise
            except Exception as e:  # noqa: BLE001 — backend failure must
                # reach every waiter, not kill the dispatcher
                if adopted is not None:
                    adopted.release()
                log.warning("verify dispatch failed: %s", e)
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(
                            RuntimeError(f"verify dispatch failed: {e}")
                        )
                continue

    async def _serve_cpu(self, batch) -> None:
        # CPU serving holds the GIL either way (measured) — run
        # inline, but per SUBMISSION with yields between, so a
        # large coalesced wave doesn't block the loop in one
        # chunk (each core's future resolves as soon as its own
        # claims are done, matching the inline service's latency
        # profile).  The memo carries each unique claim's
        # verdict across the wave's submissions (same purity
        # argument as the batch dedup in _run).
        cpu = getattr(self.backend, "cpu_backend", self.backend)
        memo: dict = {}
        for cs, fut in batch:
            todo = [c for c in cs if c not in memo]
            if todo:
                t0 = time.perf_counter()
                results = eval_claims_sync(cpu, todo)
                if self._tel_host_wall is not None:
                    self._tel_host_wall.add(time.perf_counter() - t0)
                for c, r in zip(todo, results):
                    memo[c] = r
            if not fut.done():
                fut.set_result([memo[c] for c in cs])
            await asyncio.sleep(0)

    async def _serve_cpu_arena(self, batch, claims: list, wave) -> None:
        """CPU serving for an adopted zero-copy wave: ONE native batch
        equation straight from the arena columns covers every unique
        claim (no b"".join flatten, no per-claim re-verify), then
        verdicts fan out per submission exactly like _serve_cpu."""
        cpu = getattr(self.backend, "cpu_backend", self.backend)
        t0 = time.perf_counter()
        results = eval_claims_arena(cpu, wave, claims)
        if self._tel_host_wall is not None:
            self._tel_host_wall.add(time.perf_counter() - t0)
        memo = dict(zip(claims, results))
        for cs, fut in batch:
            if not fut.done():
                fut.set_result([memo[c] for c in cs])
            await asyncio.sleep(0)

    async def _land_device(
        self,
        batch,
        claims: list,
        exec_fut,
        end_holder: list,
        wave_t0: int | None,
        deadline: float,
    ) -> None:
        """Land one in-flight device wave: await its completion future
        (bounded by the dispatch deadline), fan its verdicts out to this
        wave's waiters ONLY.  Deadline overrun serves this batch from
        the CPU and lets the stuck dispatch land as a (bad) EWMA
        measurement; a backend exception poisons this wave's futures and
        nothing else (per-wave error isolation)."""
        rec = _spans.recorder()
        try:
            done, _ = await asyncio.wait({exec_fut}, timeout=deadline)
            if exec_fut not in done:
                self.deadline_misses += 1
                self._last_probe = time.monotonic()
                log.warning(
                    "device verify dispatch overran its %.0f ms "
                    "deadline; serving the batch from the CPU",
                    deadline * 1e3,
                )
                await self._serve_cpu(batch)
                if rec is not None and wave_t0 is not None:
                    rec.add(
                        "e2e", wave_t0, time.perf_counter_ns() - wave_t0
                    )
                self._log_stats()
                return
            results = exec_fut.result()
        except asyncio.CancelledError:
            for _, fut in batch:
                if not fut.done():
                    fut.cancel()
            raise
        except Exception as e:  # noqa: BLE001 — a failed wave must reach
            # its own waiters, and ONLY its own waiters
            log.warning("verify dispatch failed: %s", e)
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        RuntimeError(f"verify dispatch failed: {e}")
                    )
            return
        verdict = dict(zip(claims, results))
        fan_t0 = end_holder[0] if (rec is not None and end_holder) else None
        for cs, fut in batch:
            if not fut.done():
                fut.set_result([verdict[c] for c in cs])
        if rec is not None:
            end_ns = time.perf_counter_ns()
            if fan_t0 is not None:
                # worker completion -> every waiter's future resolved
                # (captures the executor -> loop wakeup gap)
                rec.add("verdict.fanout", fan_t0, end_ns - fan_t0)
            if wave_t0 is not None:
                rec.add("e2e", wave_t0, end_ns - wave_t0)
        self._log_stats()

    def _log_stats(self) -> None:
        now = time.monotonic()
        if self.device and now >= self._next_stats_log:
            # NOTE: this log entry is used to compute performance
            # (benchmark log-scrape contract): device-vs-CPU routing
            # split and the measured dispatch EWMA.
            self._next_stats_log = now + 5.0
            log.info(
                "Verify service stats [%s]: dispatches=%d device=%d "
                "cpu=%d probe=%d device_sigs=%d cpu_sigs=%d "
                "deadline_misses=%d waits=%d depth=%d mesh=%d "
                "agg=%d agg_sigs=%d ewma_ms=%.1f zc=%d fb=%d",
                self._stats_tag,
                self.dispatches,
                self.device_dispatches,
                self.cpu_dispatches,
                self.probe_dispatches,
                self.device_sigs,
                self.cpu_sigs,
                self.deadline_misses,
                self.pipeline_waits,
                self.pipeline_depth,
                self.mesh_dispatches,
                self.agg_claims,
                self.agg_sigs,
                (self._device_ewma_s or 0.0) * 1e3,
                self.zero_copy_waves,
                self.fallback_waves,
            )


__all__ = [
    "AdoptedWave",
    "AsyncVerifyService",
    "ZeroCopyIngest",
    "claim_sig_count",
    "eval_claims_arena",
    "eval_claims_sync",
    "flatten_claims",
    "ingest_arena_rows_from_env",
    "ingest_note_frame",
    "ingest_ring_from_env",
    "make_pad_claim",
    "pipeline_depth_from_env",
    "wave_buckets_from_env",
    "resolve_wave_buckets",
    "coalesce_window_s_from_env",
    "zero_copy_from_env",
    "zero_copy_ingest",
    "zero_copy_ingest_if_active",
    "CPU_US_PER_SIG",
    "DEFAULT_INGEST_RING_DEPTH",
    "DEFAULT_PIPELINE_DEPTH",
    "DEFAULT_WAVE_BUCKETS",
    "PIPELINE_MARGINAL_COST",
]
