"""Hash digests and the Hash protocol.

Parity target: the reference's ``Digest`` / ``Hash`` pair
(reference ``crypto/src/lib.rs:22-69``): a 32-byte value displayed as
base64, produced by SHA-512 truncated to its first 32 bytes.
"""

from __future__ import annotations

import hashlib
import os
from typing import Protocol, runtime_checkable

from ..utils.fixed_bytes import FixedBytes

DIGEST_SIZE = 32


def sha512_trunc(data: bytes) -> bytes:
    """SHA-512 truncated to 32 bytes — the digest function every signable
    message uses (reference ``crypto/src/lib.rs:67-69`` +
    ``consensus/src/messages.rs`` digest impls)."""
    return hashlib.sha512(data).digest()[:DIGEST_SIZE]


class Digest(FixedBytes):
    """A 32-byte hash value. Ordered, hashable, base64-displayed."""

    SIZE = DIGEST_SIZE
    __slots__ = ()

    @classmethod
    def of(cls, data: bytes) -> "Digest":
        return cls(sha512_trunc(data))

    @classmethod
    def random(cls) -> "Digest":
        # Parity: Digest::random (reference crypto/src/lib.rs:32-38).
        return cls(os.urandom(DIGEST_SIZE))


@runtime_checkable
class Hashable(Protocol):
    """Implemented by every signable message (reference's ``Hash`` trait)."""

    def digest(self) -> Digest: ...
