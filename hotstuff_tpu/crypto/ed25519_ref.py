"""Pure-Python RFC 8032 Ed25519 — the framework's arithmetic oracle.

This module is the single source of truth for curve math semantics:
- the JAX TPU batch-verify kernel (``hotstuff_tpu.tpu.ed25519``) is tested
  for bit-exact agreement with it,
- it provides point (de)compression used to precompute committee-member
  points for the TPU kernel,
- and it is the fallback CPU path if neither ``cryptography`` nor libsodium
  is available.

It intentionally uses arbitrary-precision Python ints — slow but obviously
correct, validated against the RFC 8032 test vectors in
``tests/test_crypto.py``.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache as _lru_cache

# --- Field: GF(2^255 - 19) ---------------------------------------------------

P = 2**255 - 19
# Group order L = 2^252 + 27742317777372353535851937790883648493
L = 2**252 + 27742317777372353535851937790883648493
# Edwards curve: -x^2 + y^2 = 1 + d x^2 y^2
D = (-121665 * pow(121666, P - 2, P)) % P
# sqrt(-1) mod p, used in decompression
SQRT_M1 = pow(2, (P - 1) // 4, P)


def inv(x: int) -> int:
    return pow(x, P - 2, P)


# --- Points (extended homogeneous coordinates X:Y:Z:T, x=X/Z, y=Y/Z, T=XY/Z) --

# Base point B: y = 4/5, x recovered with positive... even x convention per RFC.
_By = (4 * inv(5)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """Solve x^2 = (y^2-1)/(d y^2+1); return x with parity ``sign``, or None."""
    if y >= P:
        return None
    x2 = (y * y - 1) * inv(D * y * y + 1) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    # square root via candidate x = x2^((p+3)/8)
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_Bx = _recover_x(_By, 0)
assert _Bx is not None
BASE_AFFINE = (_Bx, _By)
B_POINT = (_Bx, _By, 1, _Bx * _By % P)
IDENTITY = (0, 1, 1, 0)

Point = tuple[int, int, int, int]


def point_add(p: Point, q: Point) -> Point:
    """Unified addition, extended coords (add-2008-hwcd-3)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    Bv = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dv = 2 * Z1 * Z2 % P
    E = Bv - A
    F = Dv - C
    G = Dv + C
    H = Bv + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p: Point) -> Point:
    """Doubling, extended coords (dbl-2008-hwcd)."""
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    Bv = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + Bv
    E = H - (X1 + Y1) * (X1 + Y1) % P
    G = A - Bv
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_mul(s: int, p: Point) -> Point:
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_double(p)
        s >>= 1
    return q


# Fixed-base acceleration: radix-16 comb table over B.  64 digit
# positions x 16 multiples cover any scalar < 2^256, turning a base
# mult into <= 63 additions (vs ~253 doubles + ~127 adds in the generic
# ladder, ~6x measured).  Built lazily: importers that never sign (the
# TPU parity tests, point codecs) pay nothing.
_COMB: list[list[Point]] | None = None


def _comb_table() -> list[list[Point]]:
    global _COMB
    if _COMB is None:
        table = []
        p = B_POINT
        for _ in range(64):
            row = [IDENTITY, p]
            for _w in range(2, 16):
                row.append(point_add(row[-1], p))
            table.append(row)
            p = point_double(point_double(point_double(point_double(p))))
        _COMB = table
    return _COMB


def base_mul(s: int) -> Point:
    """``[s]B`` via the fixed-base comb — bit-exact with
    ``point_mul(s, B_POINT)`` (asserted in tests/test_crypto.py)."""
    table = _comb_table()
    q = IDENTITY
    i = 0
    while s > 0:
        d = s & 15
        if d:
            q = point_add(q, table[i][d])
        s >>= 4
        i += 1
    return q


def point_neg(p: Point) -> Point:
    X, Y, Z, T = p
    return ((P - X) % P, Y, Z, (P - T) % P)


def point_equal(p: Point, q: Point) -> bool:
    # x1/z1 == x2/z2  <=>  x1*z2 == x2*z1 (likewise y)
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def point_compress(p: Point) -> bytes:
    X, Y, Z, _ = p
    zinv = inv(Z)
    x = X * zinv % P
    y = Y * zinv % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(s: bytes) -> Point | None:
    if len(s) != 32:
        return None
    enc = int.from_bytes(s, "little")
    y = enc & ((1 << 255) - 1)
    sign = enc >> 255
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def point_affine(p: Point) -> tuple[int, int]:
    X, Y, Z, _ = p
    zinv = inv(Z)
    return X * zinv % P, Y * zinv % P


def is_on_curve(x: int, y: int) -> bool:
    return (-x * x + y * y - 1 - D * x * x * y * y) % P == 0


# --- Scalars -----------------------------------------------------------------


def _sha512_int(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def secret_expand(seed32: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(seed32).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


@_lru_cache(maxsize=1024)
def _expanded(seed32: bytes) -> tuple[int, bytes, bytes]:
    """(scalar, prefix, compressed public key) per seed.  The expansion
    costs a SHA-512 plus a full base mult; consensus signs thousands of
    times under a handful of committee keys, so caching it halves the
    fallback signing path."""
    a, prefix = secret_expand(seed32)
    return a, prefix, point_compress(base_mul(a))


def public_from_seed(seed32: bytes) -> bytes:
    return _expanded(seed32)[2]


def sign(seed32: bytes, msg: bytes) -> bytes:
    a, prefix, A = _expanded(seed32)
    r = _sha512_int(prefix, msg) % L
    Rs = point_compress(base_mul(r))
    k = _sha512_int(Rs, A, msg) % L
    s = (r + k * a) % L
    return Rs + int.to_bytes(s, 32, "little")


def verify_challenge(sig: bytes, pub: bytes, msg: bytes) -> int:
    """k = SHA-512(R || A || M) mod L — the scalar the TPU kernel consumes."""
    return _sha512_int(sig[:32], pub, msg) % L


def verify(sig: bytes, pub: bytes, msg: bytes) -> bool:
    """RFC 8032 verification: [s]B == R + [k]A, with canonical-s check."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    A = point_decompress(pub)
    if A is None:
        return False
    Rp = point_decompress(sig[:32])
    if Rp is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = verify_challenge(sig, pub, msg)
    sB = base_mul(s)
    kA = point_mul(k, A)
    return point_equal(sB, point_add(Rp, kA))
