"""Crypto layer: digests, ed25519 keys/signatures, signature service.

Parity map (SURVEY.md §2.1): Digest/Hash, PublicKey/SecretKey, keygen,
Signature (+verify_batch), SignatureService — reference crate ``crypto/``.
"""

from .digest import DIGEST_SIZE, Digest, Hashable, sha512_trunc
from .keys import (
    PUBLIC_KEY_SIZE,
    SECRET_KEY_SIZE,
    PublicKey,
    SecretKey,
    generate_keypair,
    generate_production_keypair,
    keypair_stream,
)
from .service import CpuVerifier, SignatureService, VerifierBackend
from .signature import (
    SIGNATURE_SIZE,
    CryptoError,
    Signature,
    batch_verify_arrays,
)

__all__ = [
    "DIGEST_SIZE",
    "Digest",
    "Hashable",
    "sha512_trunc",
    "PUBLIC_KEY_SIZE",
    "SECRET_KEY_SIZE",
    "PublicKey",
    "SecretKey",
    "generate_keypair",
    "generate_production_keypair",
    "keypair_stream",
    "CpuVerifier",
    "SignatureService",
    "VerifierBackend",
    "SIGNATURE_SIZE",
    "CryptoError",
    "Signature",
    "batch_verify_arrays",
]
