"""SignatureService — the actor holding the node's secret key.

Parity target: reference ``SignatureService`` (``crypto/src/lib.rs:232-257``):
callers submit a digest and await the signature through a oneshot. This is
the trait boundary the TPU backend slots behind (BASELINE.json north star):
``VerifierBackend`` decides where *verification* work runs (CPU loop vs
batched TPU kernel); signing stays on CPU (one ~25 µs OpenSSL sign per
vote/block is never the bottleneck — QC verify is).
"""

from __future__ import annotations

import asyncio
from typing import Iterable, Protocol

from .digest import Digest
from .keys import PublicKey, SecretKey
from .signature import CryptoError, Signature


class VerifierBackend(Protocol):
    """Where batched verification work executes."""

    def verify_one(self, digest: Digest, pk: PublicKey, sig: Signature) -> bool: ...

    def verify_shared_msg(
        self, digest: Digest, votes: list[tuple[PublicKey, Signature]]
    ) -> bool:
        """All signatures over one shared digest (QC verify shape)."""
        ...

    def verify_many(
        self,
        digests: list[bytes],
        pks: list[bytes],
        sigs: list[bytes],
    ) -> list[bool]:
        """Per-item validity over distinct messages (TC verify / eviction
        shape)."""
        ...


class CpuVerifier:
    """Default backend: per-signature OpenSSL verification."""

    name = "cpu"

    def verify_one(self, digest: Digest, pk: PublicKey, sig: Signature) -> bool:
        try:
            sig.verify(digest, pk)
            return True
        except CryptoError:
            return False

    def verify_shared_msg(
        self, digest: Digest, votes: list[tuple[PublicKey, Signature]]
    ) -> bool:
        try:
            Signature.verify_batch(digest, votes)
            return True
        except CryptoError:
            return False

    def verify_many(
        self,
        digests: list[bytes],
        pks: list[bytes],
        sigs: list[bytes],
    ) -> list[bool]:
        from .signature import batch_verify_arrays

        return batch_verify_arrays(digests, pks, sigs)


class SignatureService:
    """Asyncio actor owning the secret key; a queue of (digest, future).

    The parsed private key is constructed once and reused across sign
    requests; ``shutdown()`` fails all pending requests, drops the key, and
    wipes the secret, after which further requests raise.
    """

    def __init__(self, secret: SecretKey, channel_capacity: int = 100):
        self._queue: asyncio.Queue[tuple[Digest, asyncio.Future[Signature]]] = (
            asyncio.Queue(maxsize=channel_capacity)
        )
        self._secret = secret
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        self._key: object | None = Ed25519PrivateKey.from_private_bytes(secret.seed)
        self._task: asyncio.Task | None = None
        self._closed = False

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="signature-service"
            )

    async def _run(self) -> None:
        while True:
            digest, fut = await self._queue.get()
            if fut.cancelled():
                continue
            try:
                fut.set_result(self.sign_sync(digest))
            except Exception as e:  # surface the failure to the caller
                fut.set_exception(e)

    async def request_signature(self, digest: Digest) -> Signature:
        if self._closed:
            raise RuntimeError("SignatureService is shut down")
        self._ensure_started()
        fut: asyncio.Future[Signature] = asyncio.get_running_loop().create_future()
        await self._queue.put((digest, fut))
        return await fut

    def sign_sync(self, digest: Digest) -> Signature:
        """Synchronous signing for tests/fixtures (reference ``new_from_key``
        test constructors, consensus/src/tests/common.rs:48-114)."""
        if self._closed or self._key is None:
            raise RuntimeError("SignatureService is shut down")
        return Signature(self._key.sign(digest.to_bytes()))  # type: ignore[attr-defined]

    def shutdown(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        while not self._queue.empty():
            _, fut = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(RuntimeError("SignatureService is shut down"))
        self._key = None
        self._secret.wipe()
