"""SignatureService — the actor holding the node's secret key.

Parity target: reference ``SignatureService`` (``crypto/src/lib.rs:232-257``):
callers submit a digest and await the signature through a oneshot. This is
the trait boundary the TPU backend slots behind (BASELINE.json north star):
``VerifierBackend`` decides where *verification* work runs (CPU loop vs
batched TPU kernel); signing stays on CPU (one ~25 µs OpenSSL sign per
vote/block is never the bottleneck — QC verify is).
"""

from __future__ import annotations

from typing import Iterable, Protocol

from ..telemetry import spans as _spans
from .digest import Digest
from .keys import PublicKey, SecretKey
from .signature import CryptoError, Signature


class VerifierBackend(Protocol):
    """Where batched verification work executes.

    Beyond the three methods, the async dispatch pipeline
    (crypto/async_service.py) consults OPTIONAL capability attributes
    via ``getattr``; a backend advertises only what it supports, and
    absence means the default shown:

    - ``name = "?"`` — backend label for stats/telemetry tags;
    - ``supports_flat_batch = False`` — ``eval_claims_sync`` may
      collapse a whole claim wave into one native batch equation;
    - ``prefers_aggregate = False`` — shared-message claims must route
      through ``verify_shared_msg`` (BLS: one pairing per claim);
    - ``async_kind`` (unset) — advertises the off-loop coalescing claim
      path; one shared service per (event loop, kind);
    - ``always_offload = False`` — worker-thread offload is always
      worthwhile (the backend releases the GIL), skip cost-model routing;
    - ``device_ready = True`` — the device kernel is warm; the service
      never routes to a backend that would cold-compile mid-consensus;
    - ``dispatch_deadline_s = 0.1`` — floor for the per-dispatch
      deadline (raised adaptively from the dispatch EWMA);
    - ``device_key_cache = False`` — committee key tables are staged
      device-resident once per rebuild and gathered by row id per wave
      (tpu/ed25519.BatchVerifier, parallel/mesh.ShardedBatchVerifier);
    - ``supports_wave_padding = False`` — device-routed waves may be
      pre-padded to fixed bucket shapes (``HOTSTUFF_WAVE_BUCKETS``)
      with always-valid filler claims so every dispatch hits a warm
      jitted callable; only backends whose per-claim verdicts are
      independent of the other claims in the batch may opt in (the
      ed25519 device verifiers do; aggregate-preferring backends and
      synthetic test hosts must not);
    - ``wave_bucket_shapes`` (unset) — the backend's own preferred
      bucket ladder for fixed-shape padding, overriding the canonical
      default (but not an explicit ``HOTSTUFF_WAVE_BUCKETS``): the
      mesh-sharded verifier advertises its pad-grid entries here so
      every padded wave is a mesh-multiple pre-compiled kernel shape
      (ISSUE 7); device HOSTS forward it as None until the device
      materializes.
    """

    def verify_one(self, digest: Digest, pk: PublicKey, sig: Signature) -> bool: ...

    def verify_shared_msg(
        self, digest: Digest, votes: list[tuple[PublicKey, Signature]]
    ) -> bool:
        """All signatures over one shared digest (QC verify shape)."""
        ...

    def verify_many(
        self,
        digests: list[bytes],
        pks: list[bytes],
        sigs: list[bytes],
        aggregate_ok: bool = False,
    ) -> list[bool]:
        """Per-item validity over distinct messages (TC verify / eviction
        shape).

        ``aggregate_ok=True`` permits backends to use AGGREGATE
        acceptance within same-digest groups — per-entry results may
        then be certified only collectively (entries that individually
        fail but cancel in the sum pass).  That is sound ONLY for
        certificate verification whose trust base already covers
        aggregation (TC.verify: PoP-checked keys, stake rules run
        first — the same argument as QC aggregation).  Callers that
        make PER-ENTRY decisions (the aggregator's eviction/suspect
        logic) must leave it False."""
        ...


from .native_ed25519 import NATIVE_BATCH_MIN


class CpuVerifier:
    """Default backend: OpenSSL per-signature verification, with the
    native dalek-parity batch equation (crypto/native_ed25519.py) as
    the fast path for large same-digest batches — the QC-verify shape,
    reference crypto/src/lib.rs:213-226."""

    name = "cpu"
    # eval_claims_sync may collapse a whole claim wave into one native
    # batch equation (all-or-nothing, per-item attribution on failure)
    supports_flat_batch = True

    def verify_one(self, digest: Digest, pk: PublicKey, sig: Signature) -> bool:
        try:
            sig.verify(digest, pk)
            return True
        except CryptoError:
            return False

    def precompute(self, pubkeys: list[bytes]) -> None:
        """Warm the native committee-key tables (node boot / epoch
        setup) so QC-shaped batches only pay point decompression for
        the per-signature R points.  No-op without the native lib."""
        from . import native_ed25519

        native_ed25519.precompute(pubkeys)

    def verify_shared_msg(
        self, digest: Digest, votes: list[tuple[PublicKey, Signature]]
    ) -> bool:
        with _spans.span("host.verify"):
            if len(votes) >= NATIVE_BATCH_MIN:
                from . import native_ed25519

                if native_ed25519.available():
                    # cofactored batch acceptance — dalek-batch parity;
                    # the certificate verdict is all-or-nothing, same as
                    # the reference's QC::verify
                    return native_ed25519.batch_verify_shared(
                        digest.to_bytes(),
                        [
                            (pk.to_bytes(), sig.to_bytes())
                            for pk, sig in votes
                        ],
                    )
            try:
                Signature.verify_batch(digest, votes)
                return True
            except CryptoError:
                return False

    def verify_many(
        self,
        digests: list[bytes],
        pks: list[bytes],
        sigs: list[bytes],
        aggregate_ok: bool = False,
    ) -> list[bool]:
        from .signature import batch_verify_arrays

        with _spans.span("host.verify"):
            n = len(digests)
            if aggregate_ok and n >= NATIVE_BATCH_MIN:
                # Certificate-shaped call (TC verify): the all-pass
                # verdict may be established collectively.  One batch
                # equation replaces n verifies; on a failure fall
                # through to the loop for per-item attribution.
                from . import native_ed25519

                if (
                    native_ed25519.available()
                    and all(len(d) == Digest.SIZE for d in digests)
                    and native_ed25519.batch_verify(
                        b"".join(digests),
                        Digest.SIZE,
                        b"".join(pks),
                        b"".join(sigs),
                        n,
                        shared=False,
                    )
                ):
                    return [True] * n
            return batch_verify_arrays(digests, pks, sigs)


class SignatureService:
    """The service owning the node's secret key.

    The reference implements this as an actor (a channel of
    (digest, oneshot) pairs consumed by one task, crypto/src/lib.rs:
    232-257) because tokio tasks run on many threads.  Under asyncio's
    single thread the queue hop would cost two task switches (~45 us
    each, profiled) around a ~20 us OpenSSL sign, so ``request_signature``
    signs inline; the async signature is kept as the API boundary.  The
    parsed private key is constructed once and reused; ``shutdown()``
    drops the key and wipes the secret, after which requests raise.
    """

    def __init__(self, secret: SecretKey):
        self._secret = secret
        try:
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey,
            )

            self._key: object | None = Ed25519PrivateKey.from_private_bytes(
                secret.seed
            )
        except ImportError:  # pure-Python fallback keeps the same surface
            from .ed25519_ref import sign as _ref_sign

            seed = secret.seed

            class _RefKey:
                __slots__ = ()

                @staticmethod
                def sign(msg: bytes) -> bytes:
                    return _ref_sign(seed, msg)

            self._key = _RefKey()
        self._closed = False

    async def request_signature(self, digest: Digest) -> Signature:
        return self.sign_sync(digest)

    def sign_sync(self, digest: Digest) -> Signature:
        """Synchronous signing for tests/fixtures (reference ``new_from_key``
        test constructors, consensus/src/tests/common.rs:48-114)."""
        if self._closed or self._key is None:
            raise RuntimeError("SignatureService is shut down")
        return Signature(self._key.sign(digest.to_bytes()))  # type: ignore[attr-defined]

    def shutdown(self) -> None:
        self._closed = True
        self._key = None
        self._secret.wipe()
