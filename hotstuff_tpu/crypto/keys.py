"""Ed25519 key material and key generation.

Parity target: ``PublicKey`` / ``SecretKey`` / keygen in the reference
(``crypto/src/lib.rs:73-182``): 32-byte public keys with base64
(de)serialization, 64-byte secret keypair bytes wiped on drop, OS-RNG and
seeded deterministic key generation.

Deterministic keygen here is defined language-independently (SURVEY.md §7
"hard parts": cross-language seeded fixtures): key *i* from a 32-byte seed
is the ed25519 seed ``SHA-512(seed || u64_le(i))[:32]``.
"""

from __future__ import annotations

import base64
import hashlib
import os
import struct
from typing import Iterator

try:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pure-Python fallback (ed25519_ref) below
    HAVE_CRYPTOGRAPHY = False

from ..utils.fixed_bytes import FixedBytes

PUBLIC_KEY_SIZE = 32
SECRET_KEY_SIZE = 64  # ed25519 seed (32) || public key (32)


BLS_PUBLIC_KEY_SIZE = 96  # compressed G2 (crypto/bls)


class PublicKey(FixedBytes):
    """An authority identity key, base64-encoded for configs/wire.

    32 bytes under the default Ed25519 scheme, 96 (compressed G2) under
    the BLS12-381 scheme (``crypto/scheme.py``); a committee never mixes
    schemes, and pk fields are length-prefixed on the wire."""

    SIZE = PUBLIC_KEY_SIZE
    SIZES = frozenset({PUBLIC_KEY_SIZE, BLS_PUBLIC_KEY_SIZE})
    __slots__ = ()


class WipeableSecret:
    """Secret bytes with a best-effort wipe contract.

    Python cannot guarantee memory zeroing the way the reference's ``Drop``
    impl does (``crypto/src/lib.rs:160-168``); ``wipe()`` is the best-effort
    equivalent and is called by signing-service teardown. Every accessor
    raises after ``wipe()`` so a zeroed key can never be silently used or
    serialized.  Subclasses set ``SIZE`` (None = any length — opaque
    scheme-specific secrets, crypto/scheme.py)."""

    SIZE: int | None = None
    __slots__ = ("_data", "_wiped")

    def __init__(self, data: bytes):
        if self.SIZE is not None and len(data) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {self.SIZE} bytes"
            )
        self._data = bytearray(data)
        self._wiped = False

    def _check_live(self) -> None:
        if self._wiped:
            raise RuntimeError(f"{type(self).__name__} has been wiped")

    def to_bytes(self) -> bytes:
        self._check_live()
        return bytes(self._data)

    def encode_base64(self) -> str:
        return base64.b64encode(self.to_bytes()).decode()

    @classmethod
    def decode_base64(cls, s: str):
        return cls(base64.b64decode(s))

    def wipe(self) -> None:
        for i in range(len(self._data)):
            self._data[i] = 0
        self._wiped = True

    @property
    def wiped(self) -> bool:
        return self._wiped

    def __repr__(self) -> str:  # never print key material
        return f"{type(self).__name__}(<redacted>)"


class SecretKey(WipeableSecret):
    """64 bytes: ed25519 seed || derived public key."""

    SIZE = SECRET_KEY_SIZE
    __slots__ = ()

    @property
    def seed(self) -> bytes:
        self._check_live()
        return bytes(self._data[:32])

    @property
    def public_bytes(self) -> bytes:
        self._check_live()
        return bytes(self._data[32:])


def _keypair_from_seed(seed32: bytes) -> tuple[PublicKey, SecretKey]:
    if HAVE_CRYPTOGRAPHY:
        sk = Ed25519PrivateKey.from_private_bytes(seed32)
        pub = sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
    else:
        from .ed25519_ref import public_from_seed

        pub = public_from_seed(seed32)
    return PublicKey(pub), SecretKey(seed32 + pub)


def generate_production_keypair() -> tuple[PublicKey, SecretKey]:
    """OS-RNG keypair (reference ``generate_production_keypair``,
    crypto/src/lib.rs:170-173)."""
    return _keypair_from_seed(os.urandom(32))


def generate_keypair(seed: bytes, index: int = 0) -> tuple[PublicKey, SecretKey]:
    """Deterministic keypair *index* from a 32-byte seed (reference
    ``generate_keypair<R: CryptoRng>``, crypto/src/lib.rs:176-182 — here with
    a language-independent derivation instead of Rust's StdRng stream)."""
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    material = hashlib.sha512(seed + struct.pack("<Q", index)).digest()[:32]
    return _keypair_from_seed(material)


def keypair_stream(seed: bytes) -> Iterator[tuple[PublicKey, SecretKey]]:
    """Infinite deterministic keypair stream — test-fixture committees
    (reference ``tests/common.rs:17-20`` seeded-StdRng equivalent)."""
    i = 0
    while True:
        yield generate_keypair(seed, i)
        i += 1
