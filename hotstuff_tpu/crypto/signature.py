"""Ed25519 signatures over digests, with batch verification.

Parity target: the reference ``Signature`` (``crypto/src/lib.rs:186-227``):
sign the 32 digest bytes, verify one signature, and ``verify_batch`` many
(public_key, signature) pairs over one shared digest — the QC-verify hot
kernel (called from ``consensus/src/messages.rs:195``).

The default backend is CPU (OpenSSL via ``cryptography``); the TPU batch
backend plugs in through ``hotstuff_tpu.crypto.service.SignatureService``.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Iterable, Sequence

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pure-Python RFC 8032 fallback (ed25519_ref)
    HAVE_CRYPTOGRAPHY = False

    class InvalidSignature(Exception):  # type: ignore[no-redef]
        pass

from ..utils.fixed_bytes import FixedBytes
from .digest import Digest
from .keys import PublicKey, SecretKey

SIGNATURE_SIZE = 64


class CryptoError(Exception):
    """Signature verification / malformed key errors."""


if HAVE_CRYPTOGRAPHY:

    @lru_cache(maxsize=4096)
    def _parsed_pk(pk_bytes: bytes) -> "Ed25519PublicKey":
        """Parsed-key cache: EVP_PKEY construction costs roughly as much
        as the verify itself, and committees reuse a fixed key set —
        profiled ~2x on the consensus CPU verify path.  Raises ValueError
        on malformed keys (not cached)."""
        return Ed25519PublicKey.from_public_bytes(pk_bytes)

else:

    class _RefParsedPk:
        """ed25519_ref-backed stand-in for a parsed OpenSSL key: same
        ``verify(sig, msg)`` surface, raising InvalidSignature."""

        __slots__ = ("_pk",)

        def __init__(self, pk_bytes: bytes):
            from .ed25519_ref import point_decompress

            if len(pk_bytes) != 32 or point_decompress(pk_bytes) is None:
                raise ValueError("malformed ed25519 public key")
            self._pk = pk_bytes

        def verify(self, sig: bytes, msg: bytes) -> None:
            # fast path: the native batch library's single-verify entry
            # point (cofactored acceptance); pure-Python ladder only
            # when the .so is unavailable
            from . import native_ed25519

            if native_ed25519.available():
                if not native_ed25519.verify_one(msg, self._pk, sig):
                    raise InvalidSignature("signature mismatch")
                return
            from .ed25519_ref import verify as _ref_verify

            if not _ref_verify(sig, self._pk, msg):
                raise InvalidSignature("signature mismatch")

    @lru_cache(maxsize=4096)
    def _parsed_pk(pk_bytes: bytes) -> "_RefParsedPk":  # type: ignore[misc]
        return _RefParsedPk(pk_bytes)


BLS_SIGNATURE_SIZE = 48  # compressed G1 (crypto/bls)


class Signature(FixedBytes):
    """A signature over a digest: 64 bytes (R || s) under Ed25519, 48
    (compressed G1) under the BLS12-381 scheme.  The ed25519-specific
    class methods below are only reached through the Ed25519 scheme's
    signing service / verifier (``crypto/scheme.py``)."""

    SIZE = SIGNATURE_SIZE
    SIZES = frozenset({SIGNATURE_SIZE, BLS_SIGNATURE_SIZE})
    __slots__ = ()

    @classmethod
    def new(cls, digest: Digest, secret: SecretKey) -> "Signature":
        if HAVE_CRYPTOGRAPHY:
            sk = Ed25519PrivateKey.from_private_bytes(secret.seed)
            return cls(sk.sign(digest.to_bytes()))
        from .ed25519_ref import sign as _ref_sign

        return cls(_ref_sign(secret.seed, digest.to_bytes()))

    # R / s halves — the reference serializes the signature as two 32-byte
    # parts (crypto/src/lib.rs:186-189); we expose them for the TPU kernel.
    @property
    def r_bytes(self) -> bytes:
        return self.data[:32]

    @property
    def s_bytes(self) -> bytes:
        return self.data[32:]

    def verify(self, digest: Digest, public_key: PublicKey) -> None:
        """Raise CryptoError unless this signature over ``digest`` is valid."""
        try:
            _parsed_pk(public_key.to_bytes()).verify(
                self.data, digest.to_bytes()
            )
        except (InvalidSignature, ValueError) as e:
            raise CryptoError(f"invalid signature: {e}") from e

    @staticmethod
    def verify_batch(
        digest: Digest, votes: Iterable[tuple[PublicKey, "Signature"]]
    ) -> None:
        """Verify many (pk, sig) pairs over one digest; raise on any failure.

        CPU path: per-signature OpenSSL verifies (OpenSSL has no batch API;
        dalek's batch verification is ~2x a verify loop, and the real batch
        win here is the TPU backend — see tpu/ed25519.py)."""
        msg = digest.to_bytes()
        for pk, sig in votes:
            try:
                _parsed_pk(pk.to_bytes()).verify(sig.data, msg)
            except (InvalidSignature, ValueError) as e:
                raise CryptoError(f"invalid signature in batch: {e}") from e


def batch_verify_arrays(
    digests: Sequence[bytes],
    pks: Sequence[bytes],
    sigs: Sequence[bytes],
) -> list[bool]:
    """Vectorized-API CPU batch verify over *distinct* messages.

    Returns per-item validity instead of raising — the accumulate-then-
    dispatch aggregator (consensus/aggregator.py) uses this shape, and the
    TPU backend implements the same interface on device.
    """
    if not (len(digests) == len(pks) == len(sigs)):
        raise ValueError(
            f"length mismatch: {len(digests)} digests, {len(pks)} pks, "
            f"{len(sigs)} sigs"
        )
    out: list[bool] = []
    for msg, pk, sig in zip(digests, pks, sigs):
        try:
            _parsed_pk(pk).verify(sig, msg)
            out.append(True)
        except (InvalidSignature, ValueError):
            out.append(False)
    return out
