"""ctypes bridge to the native batched Ed25519 verifier
(native/ed25519_batch.cpp).

This is the dalek-parity CPU batch path: the reference's
``Signature::verify_batch`` (crypto/src/lib.rs:213-226) delegates to
ed25519-dalek's random-linear-combination batch verification; this
bridge exposes the same equation implemented in C++ (Pippenger
multiscalar over the 51-bit-limb field).  Measured on this rig it
verifies a 256-vote QC ~3.7x faster than the per-signature OpenSSL
loop — it is both the production fast path for QC-shaped verification
(``CpuVerifier.verify_shared_msg``) and the honest CPU baseline
``bench.py`` compares the TPU kernel against.

The ctypes call releases the GIL for the whole batch, so off-thread
callers (AsyncVerifyService workers) overlap it with event-loop work.

Failure semantics: the batch equation is all-or-nothing — callers
needing per-item attribution fall back to the per-signature loop on a
False.  Acceptance is cofactored (dalek-batch parity); singles remain
on OpenSSL's cofactorless path, the same mix the reference ships.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_LIB_NAME = "libhs_ed25519.so"

# Measured crossover on the dev rig where the batch equation beats the
# per-signature OpenSSL loop.  With the Straus small-batch path in the
# native MSM (r5) the batch wins from n=2 up (n=2: 0.13 vs 0.24 ms;
# n=4: 0.21 vs 0.49; n=11: 0.50 vs 1.46; n=256: 8.5 vs 31.4).  n=1
# stays on OpenSSL: a lone signature gets the cofactorless
# verify_strict-style semantics the reference uses for singles.  The
# single source of truth — the verifier backend and the async router
# both import it.
NATIVE_BATCH_MIN = 2


def _native_dir() -> str:
    return os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        "native",
    )


def _load_lib() -> ctypes.CDLL:
    if os.environ.get("HOTSTUFF_ED25519_NATIVE") == "0":
        raise ImportError("native batch verify disabled via env")
    path = os.path.join(_native_dir(), "build", _LIB_NAME)
    try:
        # ALWAYS run make for the SPECIFIC target (a no-op when the .so
        # is current): loading only-if-absent left a stale prebuilt .so
        # in place across source updates, and a library missing a newly
        # added symbol crashes at bind time below.  A compile failure in
        # an unrelated native TU must not disable this fast path (the
        # Makefile's mktemp+rename keeps concurrent builders from
        # exposing a partially-written .so).
        subprocess.run(
            ["make", "-C", _native_dir(), f"build/{_LIB_NAME}"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        if not os.path.exists(path):
            raise ImportError(f"cannot build {_LIB_NAME}: {e}") from e
        # no toolchain but a prebuilt .so exists: try it — the symbol
        # binding below rejects it if it is too old
    try:
        lib = ctypes.CDLL(path)
        lib.hs_ed25519_batch_verify.restype = ctypes.c_int
        lib.hs_ed25519_batch_verify.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_int,
        ]
        lib.hs_ed25519_precompute.restype = ctypes.c_int
        lib.hs_ed25519_precompute.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    except (OSError, AttributeError) as e:
        # corrupt/truncated/ABI-mismatched/stale .so (AttributeError =
        # missing symbol): degrade to the OpenSSL path instead of
        # letting the error escape into QC verify
        raise ImportError(f"cannot load {_LIB_NAME}: {e}") from e
    return lib


# None = never tried; False = tried and failed (cached — a missing
# compiler must not re-spawn `make` on every QC verify); CDLL = loaded.
_lib: ctypes.CDLL | bool | None = None


def available() -> bool:
    global _lib
    if _lib is None:
        try:
            _lib = _load_lib()
        except ImportError as e:
            import logging

            logging.getLogger(__name__).info(
                "native batch verifier unavailable (%s); using the "
                "per-signature CPU path",
                e,
            )
            _lib = False
    return _lib is not False


def batch_verify(
    msgs: bytes, msg_len: int, pks: bytes, sigs: bytes, n: int, shared: bool
) -> bool:
    """True iff ALL n signatures satisfy the batch equation.

    ``msgs`` is n*msg_len contiguous bytes (or msg_len bytes when
    ``shared``); ``pks`` n*32; ``sigs`` n*64.  Malformed encodings
    (non-canonical points/scalars) verify False.
    """
    if n == 0:
        return True
    assert _lib is not None and _lib is not False, "call available() first"
    # Buffer-length validation BEFORE crossing into C: a short component
    # (e.g. a 48-byte BLS-sized signature smuggled into an ed25519
    # batch) must be an invalid-signature verdict, not an out-of-bounds
    # read.
    if (
        len(msgs) != (msg_len if shared else n * msg_len)
        or len(pks) != n * 32
        or len(sigs) != n * 64
    ):
        return False
    return (
        _lib.hs_ed25519_batch_verify(
            msgs, msg_len, pks, sigs, n, 1 if shared else 0
        )
        == 1
    )


def precompute(pubkeys: list[bytes]) -> int:
    """Build the native committee-key tables (epoch setup): each 32-byte
    key gets its decompressed negated point + Straus window table cached
    in the C library, so every later batch only pays point work for the
    per-signature R points.  Returns the number of keys cached (wrong-
    size or off-curve keys are skipped — they fail at verify time)."""
    if not available():
        return 0
    pks = b"".join(pk for pk in pubkeys if len(pk) == 32)
    n = len(pks) // 32
    if n == 0:
        return 0
    return int(_lib.hs_ed25519_precompute(pks, n))


def verify_one(msg: bytes, pk: bytes, sig: bytes) -> bool:
    """Single-signature verify through ``hs_ed25519_verify_one``.
    Cofactored acceptance (batch-equation semantics) — callers that need
    the cofactorless single-signature path keep OpenSSL; this is the
    fast fallback when ``cryptography`` is absent and the alternative is
    the pure-Python ladder (~30x slower)."""
    if len(pk) != 32 or len(sig) != 64 or not available():
        return False
    return int(_lib.hs_ed25519_verify_one(msg, len(msg), pk, sig)) == 1


def batch_verify_shared(msg: bytes, votes) -> bool:
    """All (pk_bytes, sig_bytes) pairs over one message (QC shape)."""
    n = len(votes)
    if n == 0:
        return True
    pks = b"".join(pk for pk, _ in votes)
    sigs = b"".join(sig for _, sig in votes)
    return batch_verify(msg, len(msg), pks, sigs, n, shared=True)
