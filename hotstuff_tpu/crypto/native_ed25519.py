"""ctypes bridge to the native batched Ed25519 verifier
(native/ed25519_batch.cpp).

This is the dalek-parity CPU batch path: the reference's
``Signature::verify_batch`` (crypto/src/lib.rs:213-226) delegates to
ed25519-dalek's random-linear-combination batch verification; this
bridge exposes the same equation implemented in C++ (Pippenger
multiscalar over the 51-bit-limb field).  Measured on this rig it
verifies a 256-vote QC ~3.7x faster than the per-signature OpenSSL
loop — it is both the production fast path for QC-shaped verification
(``CpuVerifier.verify_shared_msg``) and the honest CPU baseline
``bench.py`` compares the TPU kernel against.

The ctypes call releases the GIL for the whole batch, so off-thread
callers (AsyncVerifyService workers) overlap it with event-loop work.

Failure semantics: the batch equation is all-or-nothing — callers
needing per-item attribution fall back to the per-signature loop on a
False.  Acceptance is cofactored (dalek-batch parity); singles remain
on OpenSSL's cofactorless path, the same mix the reference ships.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_LIB_NAME = "libhs_ed25519.so"

# Measured crossover on the dev rig where the batch equation beats the
# per-signature OpenSSL loop.  With the Straus small-batch path in the
# native MSM (r5) the batch wins from n=2 up (n=2: 0.13 vs 0.24 ms;
# n=4: 0.21 vs 0.49; n=11: 0.50 vs 1.46; n=256: 8.5 vs 31.4).  n=1
# stays on OpenSSL: a lone signature gets the cofactorless
# verify_strict-style semantics the reference uses for singles.  The
# single source of truth — the verifier backend and the async router
# both import it.
NATIVE_BATCH_MIN = 2


def _native_dir() -> str:
    return os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        "native",
    )


def _load_lib() -> ctypes.CDLL:
    if os.environ.get("HOTSTUFF_ED25519_NATIVE") == "0":
        raise ImportError("native batch verify disabled via env")
    path = os.path.join(_native_dir(), "build", _LIB_NAME)
    try:
        # ALWAYS run make for the SPECIFIC target (a no-op when the .so
        # is current): loading only-if-absent left a stale prebuilt .so
        # in place across source updates, and a library missing a newly
        # added symbol crashes at bind time below.  A compile failure in
        # an unrelated native TU must not disable this fast path (the
        # Makefile's mktemp+rename keeps concurrent builders from
        # exposing a partially-written .so).
        subprocess.run(
            ["make", "-C", _native_dir(), f"build/{_LIB_NAME}"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        if not os.path.exists(path):
            raise ImportError(f"cannot build {_LIB_NAME}: {e}") from e
        # no toolchain but a prebuilt .so exists: try it — the symbol
        # binding below rejects it if it is too old
    try:
        lib = ctypes.CDLL(path)
        lib.hs_ed25519_batch_verify.restype = ctypes.c_int
        lib.hs_ed25519_batch_verify.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_int,
        ]
        lib.hs_ed25519_precompute.restype = ctypes.c_int
        lib.hs_ed25519_precompute.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    except (OSError, AttributeError) as e:
        # corrupt/truncated/ABI-mismatched/stale .so (AttributeError =
        # missing symbol): degrade to the OpenSSL path instead of
        # letting the error escape into QC verify
        raise ImportError(f"cannot load {_LIB_NAME}: {e}") from e
    return lib


# None = never tried; False = tried and failed (cached — a missing
# compiler must not re-spawn `make` on every QC verify); CDLL = loaded.
_lib: ctypes.CDLL | bool | None = None


def available() -> bool:
    global _lib
    if _lib is None:
        try:
            _lib = _load_lib()
        except ImportError as e:
            import logging

            logging.getLogger(__name__).info(
                "native batch verifier unavailable (%s); using the "
                "per-signature CPU path",
                e,
            )
            _lib = False
    return _lib is not False


def batch_verify(
    msgs: bytes, msg_len: int, pks: bytes, sigs: bytes, n: int, shared: bool
) -> bool:
    """True iff ALL n signatures satisfy the batch equation.

    ``msgs`` is n*msg_len contiguous bytes (or msg_len bytes when
    ``shared``); ``pks`` n*32; ``sigs`` n*64.  Malformed encodings
    (non-canonical points/scalars) verify False.
    """
    if n == 0:
        return True
    assert _lib is not None and _lib is not False, "call available() first"
    # Buffer-length validation BEFORE crossing into C: a short component
    # (e.g. a 48-byte BLS-sized signature smuggled into an ed25519
    # batch) must be an invalid-signature verdict, not an out-of-bounds
    # read.
    if (
        len(msgs) != (msg_len if shared else n * msg_len)
        or len(pks) != n * 32
        or len(sigs) != n * 64
    ):
        return False
    return (
        _lib.hs_ed25519_batch_verify(
            msgs, msg_len, pks, sigs, n, 1 if shared else 0
        )
        == 1
    )


def precompute(pubkeys: list[bytes]) -> int:
    """Build the native committee-key tables (epoch setup): each 32-byte
    key gets its decompressed negated point + Straus window table cached
    in the C library, so every later batch only pays point work for the
    per-signature R points.  Returns the number of keys cached (wrong-
    size or off-curve keys are skipped — they fail at verify time)."""
    if not available():
        return 0
    pks = b"".join(pk for pk in pubkeys if len(pk) == 32)
    n = len(pks) // 32
    if n == 0:
        return 0
    return int(_lib.hs_ed25519_precompute(pks, n))


def verify_one(msg: bytes, pk: bytes, sig: bytes) -> bool:
    """Single-signature verify through ``hs_ed25519_verify_one``.
    Cofactored acceptance (batch-equation semantics) — callers that need
    the cofactorless single-signature path keep OpenSSL; this is the
    fast fallback when ``cryptography`` is absent and the alternative is
    the pure-Python ladder (~30x slower)."""
    if len(pk) != 32 or len(sig) != 64 or not available():
        return False
    return int(_lib.hs_ed25519_verify_one(msg, len(msg), pk, sig)) == 1


def batch_verify_shared(msg: bytes, votes) -> bool:
    """All (pk_bytes, sig_bytes) pairs over one message (QC shape)."""
    n = len(votes)
    if n == 0:
        return True
    pks = b"".join(pk for pk, _ in votes)
    sigs = b"".join(sig for _, sig in votes)
    return batch_verify(msg, len(msg), pks, sigs, n, shared=True)


def batch_verify_columns(
    dig_addr: int, pks_addr: int, sigs_addr: int, n: int
) -> bool:
    """Batch verify straight from native arena column addresses
    (wave_pack.cpp staging memory) — the zero-copy CPU route: no
    ``b"".join`` flatten, no bytes materialization.  The addresses come
    from ``WavePacker.arena_info`` and stay valid until the arena is
    recycled; the caller owns that lifetime."""
    if n == 0:
        return True
    assert _lib is not None and _lib is not False, "call available() first"
    return (
        _lib.hs_ed25519_batch_verify(
            ctypes.cast(dig_addr, ctypes.c_char_p),
            32,
            ctypes.cast(pks_addr, ctypes.c_char_p),
            ctypes.cast(sigs_addr, ctypes.c_char_p),
            n,
            0,
        )
        == 1
    )


# ---------------------------------------------------------------------------
# Wave-pack arena bindings (native/wave_pack.cpp, ISSUE 20)
#
# The wp_* ABI ships in libhs_transport.so (same dlopen handle as the
# reactor's ht_* surface) — votes parsed at the reactor read path land
# in bucket-shaped staging arenas that the async verify service adopts
# as NumPy frombuffer views instead of flattening Python claim tuples.
# ---------------------------------------------------------------------------

_TRANSPORT_LIB = "libhs_transport.so"

# None = never tried; False = unavailable (cached); CDLL = loaded
_wp_lib: ctypes.CDLL | bool | None = None


def _load_wave_lib() -> ctypes.CDLL:
    path = os.path.join(_native_dir(), "build", _TRANSPORT_LIB)
    try:
        subprocess.run(
            ["make", "-C", _native_dir(), f"build/{_TRANSPORT_LIB}"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        if not os.path.exists(path):
            raise ImportError(f"cannot build {_TRANSPORT_LIB}: {e}") from e
    try:
        lib = ctypes.CDLL(path)
        lib.wp_create.restype = ctypes.c_void_p
        lib.wp_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.wp_destroy.argtypes = [ctypes.c_void_p]
        lib.wp_set_pad.restype = ctypes.c_int
        lib.wp_set_pad.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.wp_probe_vote.restype = ctypes.c_int
        lib.wp_probe_vote.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.wp_pack_vote.restype = ctypes.c_long
        lib.wp_pack_vote.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_char_p,
        ]
        lib.wp_count.restype = ctypes.c_long
        lib.wp_count.argtypes = [ctypes.c_void_p]
        lib.wp_seal.restype = ctypes.c_long
        lib.wp_seal.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.wp_arena_info.restype = ctypes.c_int
        lib.wp_arena_info.argtypes = [
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.wp_recycle.restype = ctypes.c_int
        lib.wp_recycle.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.wp_discard.restype = ctypes.c_int
        lib.wp_discard.argtypes = [ctypes.c_void_p]
        lib.wp_counters.restype = ctypes.c_int
        lib.wp_counters.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
        ]
        lib.wp_parse_producer.restype = ctypes.c_long
        lib.wp_parse_producer.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
    except (OSError, AttributeError) as e:
        raise ImportError(f"cannot load {_TRANSPORT_LIB}: {e}") from e
    return lib


def wave_pack_available() -> bool:
    global _wp_lib
    if _wp_lib is None:
        try:
            _wp_lib = _load_wave_lib()
        except ImportError as e:
            import logging

            logging.getLogger(__name__).info(
                "native wave packer unavailable (%s); ingest stays on the "
                "Python flatten path",
                e,
            )
            _wp_lib = False
    return _wp_lib is not False


MAX_PRODUCER_BATCH = 512


def probe_vote(frame: bytes) -> bool:
    """Stateless Decoder-parity accept/reject for a vote frame (the
    differential fuzz harness drives this against decode_message)."""
    assert _wp_lib is not None and _wp_lib is not False
    return _wp_lib.wp_probe_vote(frame, len(frame)) == 1


def parse_producer(frame: bytes):
    """Decoder-parity producer-v2 parse: ``(digests, spans)`` where
    ``digests`` is the packed 32B digest column and ``spans`` is a list
    of ``(offset, length)`` body windows into ``frame`` — or ``None``
    for any frame the Python Decoder rejects."""
    assert _wp_lib is not None and _wp_lib is not False
    digs = ctypes.create_string_buffer(MAX_PRODUCER_BATCH * 32)
    spans = (ctypes.c_uint64 * (MAX_PRODUCER_BATCH * 2))()
    n = _wp_lib.wp_parse_producer(frame, len(frame), digs, spans)
    if n < 0:
        return None
    return (
        digs.raw[: n * 32],
        [(spans[2 * i], spans[2 * i + 1]) for i in range(n)],
    )


class WavePacker:
    """Owner of one native arena ring.  ``pack_vote`` runs on the event
    loop (reactor drain path); ``recycle`` runs on verifier slot threads
    once the adopted views are consumed — the native side serializes
    both under one mutex."""

    def __init__(self, capacity: int, ring_depth: int = 4):
        if not wave_pack_available():
            raise ImportError("wave packer unavailable")
        assert _wp_lib is not None and _wp_lib is not False
        self._lib = _wp_lib
        self._h = self._lib.wp_create(capacity, ring_depth)
        if not self._h:
            raise MemoryError("wp_create failed")
        self.capacity = capacity
        self.ring_depth = ring_depth
        self._digest_out = ctypes.create_string_buffer(32)

    def close(self) -> None:
        if self._h:
            self._lib.wp_destroy(self._h)
            self._h = None

    def set_pad(self, digest: bytes, pk: bytes, sig: bytes) -> bool:
        return self._lib.wp_set_pad(self._h, digest, pk, sig) == 0

    def pack_vote(self, frame: bytes):
        """``(row_slot, claim_digest32)`` on success, else the negative
        native error code (int): -1 malformed frame, -2 open arena
        full, -3 no pad installed."""
        slot = self._lib.wp_pack_vote(
            self._h, frame, len(frame), self._digest_out
        )
        if slot < 0:
            return int(slot)
        return slot, self._digest_out.raw

    def count(self) -> int:
        return int(self._lib.wp_count(self._h))

    def seal(self, n_take: int) -> int | None:
        """Seal the open arena at ``n_take`` rows (surplus rows carry
        over to the next arena).  Returns the sealed arena index."""
        idx = self._lib.wp_seal(self._h, n_take)
        return None if idx < 0 else int(idx)

    def arena_info(self, arena: int):
        """``(dig_addr, pk_addr, sig_addr, rows, capacity)`` of a sealed
        arena — feed the addresses to ``column_view`` / NumPy."""
        out = (ctypes.c_uint64 * 5)()
        if self._lib.wp_arena_info(self._h, arena, out) != 0:
            return None
        return (
            int(out[0]),
            int(out[1]),
            int(out[2]),
            int(out[3]),
            int(out[4]),
        )

    def recycle(self, arena: int) -> bool:
        return self._lib.wp_recycle(self._h, arena) == 0

    def discard(self) -> bool:
        return self._lib.wp_discard(self._h) == 0

    def counters(self) -> dict:
        out = (ctypes.c_uint64 * 7)()
        n = self._lib.wp_counters(self._h, out, 7)
        names = (
            "packed",
            "reject",
            "full",
            "seal",
            "discard",
            "recycle",
            "moved",
        )
        return {names[i]: int(out[i]) for i in range(n)}


def column_view(addr: int, nbytes: int):
    """Writable buffer over ``nbytes`` of native arena memory at
    ``addr`` (buffer-protocol object — ``np.frombuffer`` accepts it
    directly).  Valid only until the owning arena is recycled."""
    return (ctypes.c_uint8 * nbytes).from_address(addr)
