"""hotstuff_tpu: a TPU-native 2-chain HotStuff BFT consensus framework.

A ground-up rebuild of the capabilities of the reference Rust implementation
(tanZiWen/hotstuff, a fork of asonnino/hotstuff) designed TPU-first:

- the crypto hot path (Ed25519 vote-signature and quorum-certificate batch
  verification) runs as JAX kernels on TPU (``hotstuff_tpu.tpu``), behind a
  pluggable ``SignatureService`` boundary with a CPU default;
- the node runtime (consensus core, proposer, synchronizer, networking,
  store) is an asyncio actor graph mirroring the reference's tokio actor
  topology, with native C++ components under ``native/``;
- a benchmark harness (``benchmark/``) reproduces the reference's
  measurement methodology with a corrected log-schema contract.

Reference layer map: SURVEY.md §1; component parity: SURVEY.md §2.
"""

__version__ = "0.1.0"

# One persistent XLA/Mosaic compilation cache for every process that
# imports the framework (nodes, bench, tests).  The Pallas verify kernel
# costs minutes of Mosaic compile per batch shape; with a shared cache it
# compiles once per machine and loads in seconds ever after.  Must run
# before jax is imported anywhere; an explicit env var wins.
import os as _os

JAX_CACHE_DIR = _os.path.expanduser("~/.cache/hotstuff_tpu/jax")
_os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE_DIR)
