"""hotstuff_tpu: a TPU-native 2-chain HotStuff BFT consensus framework.

A ground-up rebuild of the capabilities of the reference Rust implementation
(tanZiWen/hotstuff, a fork of asonnino/hotstuff) designed TPU-first:

- the crypto hot path (Ed25519 vote-signature and quorum-certificate batch
  verification) runs as JAX kernels on TPU (``hotstuff_tpu.tpu``), behind a
  pluggable ``SignatureService`` boundary with a CPU default;
- the node runtime (consensus core, proposer, synchronizer, networking,
  store) is an asyncio actor graph mirroring the reference's tokio actor
  topology, with native C++ components under ``native/``;
- a benchmark harness (``benchmark/``) reproduces the reference's
  measurement methodology with a corrected log-schema contract.

Reference layer map: SURVEY.md §1; component parity: SURVEY.md §2.
"""

__version__ = "0.1.0"
