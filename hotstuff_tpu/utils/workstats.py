"""Per-node work accounting for the committee-scaling decomposition.

The 1-core dev rig cannot host >=16 node processes, so raw TPS at
large committees measures host starvation, not protocol cost
(VERDICT r2 weak #4).  This module separates the two:

- ``CountingVerifier`` wraps a ``VerifierBackend`` and counts calls and
  signatures per call shape (the protocol's dominant CPU cost);
- ``LoopLagProbe`` measures event-loop scheduling lag — the DIRECT
  starvation signal: an idle loop wakes a 50 ms sleep within ~1 ms,
  a core-starved one wakes it late by the amount the host is
  oversubscribed;
- ``WorkStats`` aggregates both plus message counts and logs one
  parseable line periodically (``Work stats: {json}``) — the scaling
  harness scrapes the LAST line per node log.

Enabled by HOTSTUFF_WORK_STATS=1 (node/node.py); zero cost otherwise.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

log = logging.getLogger(__name__)

LOG_INTERVAL = 5.0
LAG_INTERVAL = 0.05

# The 'Work stats:' scrape contract: every key WorkStats.to_json emits.
# The telemetry snapshot document (telemetry/__init__.py, 'Telemetry
# snapshot:' line) must stay a SUPERSET of these keys at its top level —
# tests/test_telemetry.py pins both sides to this tuple.
WORKSTATS_KEYS = (
    "elapsed_s",
    "verify_calls",
    "verify_sigs",
    "verify_wall_ms",
    "loop_lag_mean_ms",
    "loop_lag_max_ms",
)


class WorkStats:
    __slots__ = (
        "verify_calls",
        "verify_sigs",
        "verify_wall_s",
        "blocks_processed",
        "lag_samples",
        "lag_total_s",
        "lag_max_s",
        "started",
    )

    def __init__(self):
        self.verify_calls = 0
        self.verify_sigs = 0
        self.verify_wall_s = 0.0
        self.blocks_processed = 0
        self.lag_samples = 0
        self.lag_total_s = 0.0
        self.lag_max_s = 0.0
        self.started = time.monotonic()

    def to_json(self) -> dict:
        elapsed = max(time.monotonic() - self.started, 1e-9)
        return {
            "elapsed_s": round(elapsed, 3),
            "verify_calls": self.verify_calls,
            "verify_sigs": self.verify_sigs,
            "verify_wall_ms": round(self.verify_wall_s * 1e3, 3),
            "loop_lag_mean_ms": round(
                (self.lag_total_s / self.lag_samples * 1e3)
                if self.lag_samples
                else 0.0,
                3,
            ),
            "loop_lag_max_ms": round(self.lag_max_s * 1e3, 3),
        }


class CountingVerifier:
    """Delegating VerifierBackend that accounts calls/signatures/wall
    time into a WorkStats."""

    def __init__(self, inner, stats: WorkStats):
        self.inner = inner
        self.stats = stats
        self.name = getattr(inner, "name", "counted")

    def _timed(self, n_sigs: int, fn, *args):
        t0 = time.perf_counter()
        out = fn(*args)
        self.stats.verify_wall_s += time.perf_counter() - t0
        self.stats.verify_calls += 1
        self.stats.verify_sigs += n_sigs
        return out

    def verify_one(self, digest, pk, sig) -> bool:
        return self._timed(1, self.inner.verify_one, digest, pk, sig)

    def verify_shared_msg(self, digest, votes) -> bool:
        return self._timed(
            len(votes), self.inner.verify_shared_msg, digest, votes
        )

    def verify_many(self, digests, pks, sigs, aggregate_ok: bool = False):
        def call(d, p, s):
            return self.inner.verify_many(d, p, s, aggregate_ok=aggregate_ok)

        return self._timed(len(digests), call, digests, pks, sigs)

    def __getattr__(self, item):
        # precompute/warmup/etc. pass through untimed
        return getattr(self.inner, item)


async def run_probe(stats: WorkStats, logger=None) -> None:
    """Periodic loop-lag sampling + stats logging; cancelled at node
    shutdown.  NOTE: the 'Work stats:' line is scraped by the scaling
    harness (benchmark/scaling.py)."""
    logger = logger or log
    loop = asyncio.get_running_loop()
    next_log = loop.time() + LOG_INTERVAL
    while True:
        t0 = loop.time()
        await asyncio.sleep(LAG_INTERVAL)
        lag = max(loop.time() - t0 - LAG_INTERVAL, 0.0)
        stats.lag_samples += 1
        stats.lag_total_s += lag
        stats.lag_max_s = max(stats.lag_max_s, lag)
        if loop.time() >= next_log:
            next_log = loop.time() + LOG_INTERVAL
            logger.info("Work stats: %s", json.dumps(stats.to_json()))
