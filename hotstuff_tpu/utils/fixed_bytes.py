"""Fixed-size immutable byte value types.

One shared implementation of the plumbing the reference repeats per type
(base64 (de)serialization, ordering, hashing, truncated display —
reference ``crypto/src/lib.rs`` Digest/PublicKey/Signature impls).
"""

from __future__ import annotations

import base64


class FixedBytes:
    """Base for 32/64-byte value types. Subclasses set ``SIZE``."""

    SIZE = 0
    __slots__ = ("data",)

    def __init__(self, data: bytes | None = None):
        if data is None:
            data = b"\x00" * self.SIZE
        if len(data) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {self.SIZE} bytes, got {len(data)}"
            )
        object.__setattr__(self, "data", bytes(data))

    def to_bytes(self) -> bytes:
        return self.data

    @property
    def size(self) -> int:
        return self.SIZE

    def encode_base64(self) -> str:
        return base64.b64encode(self.data).decode()

    @classmethod
    def decode_base64(cls, s: str):
        return cls(base64.b64decode(s))

    def __eq__(self, other: object) -> bool:
        return type(other) is type(self) and other.data == self.data  # type: ignore[attr-defined]

    def __lt__(self, other) -> bool:
        self._check_type(other)
        return self.data < other.data

    def __le__(self, other) -> bool:
        self._check_type(other)
        return self.data <= other.data

    def _check_type(self, other) -> None:
        if type(other) is not type(self):
            raise TypeError(
                f"cannot compare {type(self).__name__} with {type(other).__name__}"
            )

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.data))

    def __bool__(self) -> bool:
        return self.data != b"\x00" * self.SIZE

    def __repr__(self) -> str:
        return self.encode_base64()

    def __str__(self) -> str:
        # Display = first 16 chars of base64 (reference crypto/src/lib.rs:46-49).
        return self.encode_base64()[:16]
