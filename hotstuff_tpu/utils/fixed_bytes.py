"""Fixed-size immutable byte value types.

One shared implementation of the plumbing the reference repeats per type
(base64 (de)serialization, ordering, hashing, truncated display —
reference ``crypto/src/lib.rs`` Digest/PublicKey/Signature impls).
"""

from __future__ import annotations

import base64


class FixedBytes:
    """Base for fixed-size byte value types. Subclasses set ``SIZE`` (the
    canonical/default size) and may widen ``SIZES`` to the set of sizes
    valid for the type — e.g. a public key is 32 bytes under Ed25519 but
    96 under the BLS12-381 scheme; one committee only ever mixes one
    scheme, and the wire format length-prefixes these fields.

    The constructor is a deserialization hot spot (a block carries up to
    512 payload digests; profiled at 1.6M constructions over a 12 s
    saturation window), so the per-call work is minimized: the valid-size
    set and the zero default are computed once per SUBCLASS, and byte
    inputs skip the defensive copy (bytes are immutable)."""

    SIZE = 0
    SIZES: frozenset[int] | None = None  # None → exactly {SIZE}
    _VALID: frozenset[int] = frozenset((0,))
    _ZERO = b""
    _SALT = 0x9E3779B9  # per-class hash salt, set in __init_subclass__
    __slots__ = ("data",)

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        cls._VALID = (
            frozenset(cls.SIZES)
            if cls.SIZES is not None
            else frozenset((cls.SIZE,))
        )
        cls._ZERO = b"\x00" * cls.SIZE
        cls._SALT = hash(cls.__name__)

    def __init__(self, data: bytes | None = None):
        if data is None:
            data = self._ZERO
        elif type(data) is not bytes:
            # only byte-like inputs coerce — bytes(int) would silently
            # construct an all-zero value from a caller bug
            if not isinstance(data, (bytearray, memoryview)):
                raise TypeError(
                    f"{type(self).__name__} needs bytes, got "
                    f"{type(data).__name__}"
                )
            data = bytes(data)
        if len(data) not in self._VALID:
            raise ValueError(
                f"{type(self).__name__} must be one of "
                f"{sorted(self._VALID)} bytes, got {len(data)}"
            )
        object.__setattr__(self, "data", data)

    def to_bytes(self) -> bytes:
        return self.data

    @property
    def size(self) -> int:
        return len(self.data)

    def encode_base64(self) -> str:
        return base64.b64encode(self.data).decode()

    @classmethod
    def decode_base64(cls, s: str):
        return cls(base64.b64decode(s))

    def __eq__(self, other: object) -> bool:
        return type(other) is type(self) and other.data == self.data  # type: ignore[attr-defined]

    def __lt__(self, other) -> bool:
        self._check_type(other)
        return self.data < other.data

    def __le__(self, other) -> bool:
        self._check_type(other)
        return self.data <= other.data

    def _check_type(self, other) -> None:
        if type(other) is not type(self):
            raise TypeError(
                f"cannot compare {type(self).__name__} with {type(other).__name__}"
            )

    def __hash__(self) -> int:
        # hot path (dict/set keys throughout consensus): xor with a
        # per-class salt instead of hashing a (name, data) tuple —
        # same type-disambiguation, no tuple allocation per call
        # (CPython caches the bytes hash on the object)
        return hash(self.data) ^ self._SALT

    def __bool__(self) -> bool:
        return self.data != b"\x00" * len(self.data)

    def __repr__(self) -> str:
        return self.encode_base64()

    def __str__(self) -> str:
        # Display = first 16 chars of base64 (reference crypto/src/lib.rs:46-49).
        return self.encode_base64()[:16]
