"""Fixed-size immutable byte value types.

One shared implementation of the plumbing the reference repeats per type
(base64 (de)serialization, ordering, hashing, truncated display —
reference ``crypto/src/lib.rs`` Digest/PublicKey/Signature impls).
"""

from __future__ import annotations

import base64


class FixedBytes:
    """Base for fixed-size byte value types. Subclasses set ``SIZE`` (the
    canonical/default size) and may widen ``SIZES`` to the set of sizes
    valid for the type — e.g. a public key is 32 bytes under Ed25519 but
    96 under the BLS12-381 scheme; one committee only ever mixes one
    scheme, and the wire format length-prefixes these fields."""

    SIZE = 0
    SIZES: frozenset[int] | None = None  # None → exactly {SIZE}
    __slots__ = ("data",)

    def __init__(self, data: bytes | None = None):
        if data is None:
            data = b"\x00" * self.SIZE
        sizes = self.SIZES if self.SIZES is not None else {self.SIZE}
        if len(data) not in sizes:
            raise ValueError(
                f"{type(self).__name__} must be one of {sorted(sizes)} bytes, "
                f"got {len(data)}"
            )
        object.__setattr__(self, "data", bytes(data))

    def to_bytes(self) -> bytes:
        return self.data

    @property
    def size(self) -> int:
        return len(self.data)

    def encode_base64(self) -> str:
        return base64.b64encode(self.data).decode()

    @classmethod
    def decode_base64(cls, s: str):
        return cls(base64.b64decode(s))

    def __eq__(self, other: object) -> bool:
        return type(other) is type(self) and other.data == self.data  # type: ignore[attr-defined]

    def __lt__(self, other) -> bool:
        self._check_type(other)
        return self.data < other.data

    def __le__(self, other) -> bool:
        self._check_type(other)
        return self.data <= other.data

    def _check_type(self, other) -> None:
        if type(other) is not type(self):
            raise TypeError(
                f"cannot compare {type(self).__name__} with {type(other).__name__}"
            )

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.data))

    def __bool__(self) -> bool:
        return self.data != b"\x00" * len(self.data)

    def __repr__(self) -> str:
        return self.encode_base64()

    def __str__(self) -> str:
        # Display = first 16 chars of base64 (reference crypto/src/lib.rs:46-49).
        return self.encode_base64()[:16]
