"""Deterministic binary wire codec.

The reference serializes every protocol message with bincode
(reference ``consensus/src/consensus.rs:30-38`` and friends). This is the
framework's equivalent: a tiny, explicit, deterministic little-endian
codec — fixed-width ints, u32-length-prefixed variable bytes, 1-byte
option flags — so the wire format is fully specified here rather than
inherited from a serialization library.
"""

from __future__ import annotations


class CodecError(Exception):
    """Malformed or truncated wire data."""


class Encoder:
    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "Encoder":
        self._parts.append(v.to_bytes(1, "little"))
        return self

    def u16(self, v: int) -> "Encoder":
        self._parts.append(v.to_bytes(2, "little"))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(v.to_bytes(4, "little"))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(v.to_bytes(8, "little"))
        return self

    def u128(self, v: int) -> "Encoder":
        self._parts.append(v.to_bytes(16, "little"))
        return self

    def raw(self, b: bytes) -> "Encoder":
        """Fixed-size bytes: no length prefix (caller knows the size)."""
        self._parts.append(b)
        return self

    def var_bytes(self, b: bytes) -> "Encoder":
        self.u32(len(b))
        self._parts.append(b)
        return self

    def flag(self, present: bool) -> "Encoder":
        return self.u8(1 if present else 0)

    def finish(self) -> bytes:
        return b"".join(self._parts)


class Decoder:
    # pk_size/sig_size: optional wire-size expectation for key/signature
    # fields, set by the entry point that knows the committee's scheme
    # (wire.decode_message).  None = accept any size the value type
    # allows (trusted/loopback decode paths).  Narrowing this at decode
    # time keeps an ed25519 committee from parsing 96-byte BLS keys off
    # the wire at all (hostile-input surface, ADVICE r2).
    # compact_sig_size/compact_bitmap_max: the same narrowing for the
    # compact (aggregated) certificate form — None = accept (unpinned),
    # a positive size = enforce, 0 = the scheme has no compact form and
    # any compact certificate is a CodecError
    # (wire.SCHEME_COMPACT_SIZES).
    __slots__ = (
        "_data", "_pos", "pk_size", "sig_size",
        "compact_sig_size", "compact_bitmap_max",
    )

    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0
        self.pk_size: int | None = None
        self.sig_size: int | None = None
        self.compact_sig_size: int | None = None
        self.compact_bitmap_max: int | None = None

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise CodecError(
                f"truncated: need {n} bytes at offset {self._pos}, "
                f"have {len(self._data) - self._pos}"
            )
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return int.from_bytes(self._take(2), "little")

    def u32(self) -> int:
        return int.from_bytes(self._take(4), "little")

    def u64(self) -> int:
        return int.from_bytes(self._take(8), "little")

    def u128(self) -> int:
        return int.from_bytes(self._take(16), "little")

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def var_bytes(self, max_len: int = 1 << 24) -> bytes:
        n = self.u32()
        if n > max_len:
            raise CodecError(f"length {n} exceeds cap {max_len}")
        return self._take(n)

    def flag(self) -> bool:
        v = self.u8()
        if v not in (0, 1):
            raise CodecError(f"invalid option flag {v}")
        return v == 1

    def mark(self) -> int:
        """Current position, for ``since`` wire-slice capture."""
        return self._pos

    def since(self, mark: int) -> bytes:
        """The raw bytes consumed since ``mark`` — lets message decoders
        retain their exact wire encoding so a later serialize() is a
        cached-bytes return instead of a re-encode (the store path
        re-serialized every received block)."""
        return self._data[mark : self._pos]

    def finish(self) -> None:
        """Assert the input was fully consumed."""
        if self._pos != len(self._data):
            raise CodecError(
                f"{len(self._data) - self._pos} trailing bytes after decode"
            )
