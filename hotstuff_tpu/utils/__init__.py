"""Shared utilities: fixed-size byte value types, canonical codec, logging."""

from .fixed_bytes import FixedBytes

__all__ = ["FixedBytes"]
