"""Injectable time / randomness / connection seams (docs/SIM.md).

Production code paths never pass a clock explicitly — they call
``default_clock()`` / ``default_rng()`` / ``default_connector()`` at the
point of use and get real wall time, the module-level ``random`` RNG and
``asyncio.open_connection``.  The deterministic simulator
(``hotstuff_tpu/sim``) swaps all three ambient defaults before spawning
the in-process committee so every timer, jitter draw and socket open in
``consensus/``, ``network/`` and ``faults/`` becomes virtual without a
single production signature changing.

The seam is intentionally ambient (a module global, not a context
variable): the simulator runs ONE committee per process on ONE event
loop, and production processes never touch the setters.  Components that
want an explicit override (tests) can still pass ``clock=``/``rng=``
where constructors accept them.
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Any, Awaitable, Callable, Protocol, runtime_checkable

__all__ = [
    "Clock",
    "SYSTEM_CLOCK",
    "default_clock",
    "set_default_clock",
    "default_rng",
    "set_default_rng",
    "default_connector",
    "set_default_connector",
]


@runtime_checkable
class Clock(Protocol):
    """Minimal time surface used by consensus/network/fault code."""

    def time(self) -> float:  # wall clock (unix seconds)
        ...

    def monotonic(self) -> float:  # monotonic seconds
        ...

    def monotonic_ns(self) -> int:  # monotonic nanoseconds
        ...

    async def sleep(self, delay: float) -> None:  # cooperative sleep
        ...


class _SystemClock:
    """Real time: the production default."""

    def time(self) -> float:
        return time.time()  # lint: allow(clock-discipline) -- seam root

    def monotonic(self) -> float:
        return time.monotonic()  # lint: allow(clock-discipline) -- seam root

    def monotonic_ns(self) -> int:
        return time.monotonic_ns()  # lint: allow(clock-discipline) -- seam root

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)  # lint: allow(clock-discipline) -- seam root


SYSTEM_CLOCK: Clock = _SystemClock()

_clock: Clock = SYSTEM_CLOCK
# The module-level ``random`` module itself duck-types as a Random
# instance (random/uniform/gauss/sample/...), so it is the natural
# production default for the rng seam.
_rng: Any = random
_connector: Callable[..., Awaitable[Any]] = asyncio.open_connection


def default_clock() -> Clock:
    """The ambient clock: real time unless the simulator swapped it."""
    return _clock


def set_default_clock(clock: Clock | None) -> Clock:
    """Install ``clock`` as the ambient default (``None`` resets to the
    system clock).  Returns the previous default so callers can
    save/restore."""
    global _clock
    prev = _clock
    _clock = SYSTEM_CLOCK if clock is None else clock
    return prev


def default_rng() -> Any:
    """The ambient RNG (module ``random`` unless the simulator swapped
    in a seeded ``random.Random``)."""
    return _rng


def set_default_rng(rng: Any | None) -> Any:
    """Install ``rng`` as the ambient default (``None`` resets to the
    module-level ``random``).  Returns the previous default."""
    global _rng
    prev = _rng
    _rng = random if rng is None else rng
    return prev


def default_connector() -> Callable[..., Awaitable[Any]]:
    """The ambient stream connector: ``asyncio.open_connection`` unless
    the simulator swapped in its in-memory transport."""
    return _connector


def set_default_connector(
    connector: Callable[..., Awaitable[Any]] | None,
) -> Callable[..., Awaitable[Any]]:
    """Install ``connector`` as the ambient default (``None`` resets to
    ``asyncio.open_connection``).  Returns the previous default."""
    global _connector
    prev = _connector
    _connector = asyncio.open_connection if connector is None else connector
    return prev
