"""Seeded chaos plane: deterministic network fault injection.

See docs/FAULTS.md for the scenario spec format, canned scenarios, and
the safety/liveness invariant definitions checked by
``python -m benchmark chaos``.
"""

from .plane import (
    BARRIER_POLL_S,
    Decision,
    FaultPlane,
    FaultRule,
    LinkFaults,
    PASS,
    corrupt_frame,
    expand_rules,
    run_clock,
)
from .scenarios import SCENARIOS, build, last_heal

__all__ = [
    "BARRIER_POLL_S",
    "Decision",
    "FaultPlane",
    "FaultRule",
    "LinkFaults",
    "PASS",
    "SCENARIOS",
    "build",
    "corrupt_frame",
    "expand_rules",
    "last_heal",
    "run_clock",
]
