"""Seeded chaos plane: deterministic network fault injection.

See docs/FAULTS.md for the scenario spec format, canned scenarios, and
the safety/liveness invariant definitions checked by
``python -m benchmark chaos``.
"""

from .adaptive import (
    ADAPTIVE_POLICIES,
    ADAPTIVE_SHORT,
    ADAPTIVE_TRIGGERS,
    CountingRandom,
    StateView,
)
from .adversary import (
    POLICIES,
    AdversaryPlane,
    AdversaryRule,
    expand_adversary,
    run_adversary_clock,
    run_flood,
)
from .plane import (
    BARRIER_POLL_S,
    Decision,
    FaultPlane,
    FaultRule,
    LinkFaults,
    PASS,
    corrupt_frame,
    expand_rules,
    run_clock,
)
from .scenarios import SCENARIOS, build, last_heal

__all__ = [
    "ADAPTIVE_POLICIES",
    "ADAPTIVE_SHORT",
    "ADAPTIVE_TRIGGERS",
    "AdversaryPlane",
    "AdversaryRule",
    "BARRIER_POLL_S",
    "CountingRandom",
    "StateView",
    "Decision",
    "FaultPlane",
    "FaultRule",
    "LinkFaults",
    "PASS",
    "POLICIES",
    "SCENARIOS",
    "build",
    "corrupt_frame",
    "expand_adversary",
    "expand_rules",
    "last_heal",
    "run_adversary_clock",
    "run_clock",
    "run_flood",
]
