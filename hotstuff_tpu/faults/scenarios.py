"""Canned chaos scenarios for a small local committee.

Each builder returns a complete fault-plane spec dict (see
``plane.FaultPlane``) given the committee size and seed; the chaos
benchmark runner fills in ``nodes`` (address -> index) and
``epoch_unix`` before writing the spec file nodes load.

Timing convention: scenario t=0 is the shared ``epoch_unix``, which the
runner sets to config time plus a boot margin (the spec file must exist
before the first node boots).  Fault windows open a few seconds after
t=0 so every node commits under clean conditions first, and close well
before the bench ends so liveness recovery is observable.
"""

from __future__ import annotations


def split_brain(nodes: int = 4, seed: int = 0, at: float = 6.0,
                until: float = 14.0) -> dict:
    """Partition the committee into two quorum-less halves (f vs f+1
    loses liveness on both sides for n=4: 2/2).  Safety must hold
    throughout; commits must resume after the heal."""
    half = nodes // 2
    return {
        "name": "split-brain",
        "seed": seed,
        "rules": [
            {
                "label": "split-brain",
                "partition": [list(range(half)), list(range(half, nodes))],
                "at": at,
                "until": until,
            }
        ],
        "liveness": {"resume_within_s": 20.0, "max_round_gap": 200},
    }


def leader_isolation(nodes: int = 4, seed: int = 0, at: float = 6.0,
                     until: float = 13.0) -> dict:
    """Cut node 0 (the round-robin leader every ``nodes`` rounds) off
    from the committee AND from clients (inbound cut).  The rest keep
    committing via timeouts/TCs; node 0 catches up after the heal."""
    return {
        "name": "leader-isolation",
        "seed": seed,
        "rules": [
            {"label": "leader-isolation", "isolate": 0, "at": at,
             "until": until}
        ],
        "liveness": {"resume_within_s": 20.0, "max_round_gap": 200},
    }


def flapping_link(nodes: int = 4, seed: int = 0, at: float = 5.0,
                  until: float = 17.0) -> dict:
    """One link (0<->1) hard-drops for 1.5s out of every 3s.  Quorum is
    never lost (n=4 tolerates one bad link) but the reconnect/backoff
    path is exercised repeatedly."""
    return {
        "name": "flapping-link",
        "seed": seed,
        "rules": [
            {"label": "flap-0-1", "from": [0], "to": [1], "drop": 1.0,
             "at": at, "until": until, "every": 3.0, "for": 1.5},
            {"label": "flap-1-0", "from": [1], "to": [0], "drop": 1.0,
             "at": at, "until": until, "every": 3.0, "for": 1.5},
        ],
        "liveness": {"resume_within_s": 20.0, "max_round_gap": 200},
    }


def rolling_crash_restart(nodes: int = 4, seed: int = 0) -> dict:
    """Kill and respawn one node at a time (f=1 for n=4, so the
    committee keeps committing with 3/4 live).  Process-level: executed
    by the chaos runner, not the in-node plane."""
    return {
        "name": "rolling-crash-restart",
        "seed": seed,
        "rules": [],
        "crashes": [
            {"node": 1, "at": 5.0, "restart_at": 9.0},
            {"node": 2, "at": 11.0, "restart_at": 15.0},
        ],
        "liveness": {"resume_within_s": 25.0, "max_round_gap": 200},
    }


def byz_equivocate(nodes: int = 4, seed: int = 0, at: float = 2.0) -> dict:
    """Node 0 signs a second conflicting block whenever it leads.
    Honest safety rules hold (each node votes once per round), so the
    committee keeps committing the main branch: safety must PASS with
    the equivocations attributed to node 0's authority."""
    return {
        "name": "byz-equivocate",
        "seed": seed,
        "rules": [],
        "adversary": [
            {"policy": "equivocate", "node": 0, "at": at, "until": None}
        ],
        "liveness": {"resume_within_s": 20.0, "max_round_gap": 200},
    }


def byz_forge_qc(nodes: int = 4, seed: int = 0, at: float = 2.0) -> dict:
    """Node 0 broadcasts properly-signed timeouts carrying forged QCs
    (real committee authors, garbage aggregate signatures).  Honest
    verification must reject every one; commits continue."""
    return {
        "name": "byz-forge-qc",
        "seed": seed,
        "rules": [],
        "adversary": [
            {"policy": "forge-qc", "node": 0, "at": at, "until": None}
        ],
        "liveness": {"resume_within_s": 20.0, "max_round_gap": 200},
    }


def byz_withhold(nodes: int = 4, seed: int = 0, at: float = 4.0,
                 until: float = 12.0) -> dict:
    """Node 0 receives proposals but never votes while the window is
    open, forcing rounds led by slow quorums/timeouts.  An impairing
    window: liveness must recover after it closes."""
    return {
        "name": "byz-withhold",
        "seed": seed,
        "rules": [],
        "adversary": [
            {"policy": "withhold", "node": 0, "at": at, "until": until}
        ],
        "liveness": {"resume_within_s": 25.0, "max_round_gap": 200},
    }


def byz_collude(nodes: int = 4, seed: int = 0, at: float = 2.0) -> dict:
    """f+1 colluders (nodes 0 and 1 in a 4-committee — one more than
    the f=1 the quorum math tolerates): both equivocate when leading
    and double-vote the shadow branch, and the designated shadow
    committer reports the shadow chain in its commit log.  The result
    is a REAL divergent history: the safety checker must FAIL with the
    conflicting commits attributed to the colluding authorities.  The
    ``trusted-subset`` quorum mode re-checks the same history under the
    TEE-style f+1 regime, where excluding the untrusted colluders
    restores consistency."""
    return {
        "name": "byz-collude",
        "seed": seed,
        "rules": [],
        "adversary": [
            {"policy": "collude", "nodes": [0, 1], "at": at, "until": None}
        ],
        "quorum_mode": "trusted-subset",
        "liveness": {"resume_within_s": 25.0, "max_round_gap": 200},
    }


def reconfig_rotate(nodes: int = 4, seed: int = 0, at: float = 6.0) -> dict:
    """Live committee rotation (docs/RECONFIG.md): at t=``at`` the
    runner submits a sponsored reconfiguration that adds a freshly
    keyed member (node ``nodes``) and drops node 0.  The op is 2-chain
    committed, every node splices the new epoch at commit+margin, the
    joiner state-syncs the certified schedule in and votes in its first
    active round, and node 0 retires after its grace window.  Commits
    must never stall more than the declared handoff gap across the
    boundary."""
    return {
        "name": "reconfig-rotate",
        "seed": seed,
        "rules": [],
        "reconfig": [
            {"at": at, "join": [nodes], "retire": [0], "sponsor": 1},
        ],
        "handoff_gap_rounds": 64,
        "liveness": {"resume_within_s": 25.0, "max_round_gap": 200},
    }


def reconfig_join_under_partition(
    nodes: int = 4, seed: int = 0, at: float = 6.0
) -> dict:
    """Rotation with the joiner's first seconds spent behind a severed
    link to one serving peer: the certified-schedule fetch must fall
    back to the remaining members (manifest collection is a broadcast,
    not a single-peer trust decision)."""
    return {
        "name": "reconfig-join-under-partition",
        "seed": seed,
        "rules": [
            # the joiner (index ``nodes``) cannot reach node 1 while it
            # bootstraps; nodes 2/3 still serve manifests and chunks
            {"label": "join-cut", "from": [nodes], "to": [1], "drop": 1.0,
             "at": at, "until": at + 12.0},
            {"label": "join-cut-rev", "from": [1], "to": [nodes],
             "drop": 1.0, "at": at, "until": at + 12.0},
        ],
        "reconfig": [
            {"at": at, "join": [nodes], "retire": [0], "sponsor": 1},
        ],
        "handoff_gap_rounds": 96,
        "liveness": {"resume_within_s": 30.0, "max_round_gap": 250},
    }


def reconfig_retire_crash(nodes: int = 4, seed: int = 0,
                          at: float = 6.0) -> dict:
    """Rotation with a SIGKILL+rejoin of a SURVIVING member straddling
    the epoch boundary: node 2 dies right after the op is submitted and
    restarts after the new epoch has activated, so its recovery path
    must replay the persisted schedule links (or re-fetch them via
    state-sync) before it can verify new-epoch certificates."""
    return {
        "name": "reconfig-retire-crash",
        "seed": seed,
        "rules": [],
        "reconfig": [
            {"at": at, "join": [nodes], "retire": [0], "sponsor": 1},
        ],
        "crashes": [
            {"node": 2, "at": at + 2.0, "restart_at": at + 12.0},
        ],
        "handoff_gap_rounds": 96,
        "liveness": {"resume_within_s": 30.0, "max_round_gap": 250},
    }


def byz_reconfig(nodes: int = 4, seed: int = 0, at: float = 2.0) -> dict:
    """Node 0 plays reconfiguration games: when leading it proposes
    forged reconfig ops (attacker-only committees under garbage sponsor
    signatures — honest verification must kill every one at admission
    or block verify), and when a REAL rotation commits it logs a skewed
    activation round.  The runner also drives one genuine rotation so
    the shadow claims conflict with honest epoch agreement: full-history
    checking must FAIL epoch agreement with the skew attributed to node
    0, and the ``trusted-subset`` regime (excluding the adversary) must
    PASS."""
    return {
        "name": "byz-reconfig",
        "seed": seed,
        "rules": [],
        "adversary": [
            {"policy": "reconfig", "node": 0, "at": at, "until": None}
        ],
        "reconfig": [
            {"at": 6.0, "join": [nodes], "retire": [], "sponsor": 1},
        ],
        "quorum_mode": "trusted-subset",
        "handoff_gap_rounds": 96,
        "liveness": {"resume_within_s": 25.0, "max_round_gap": 200},
    }


SCENARIOS = {
    "split-brain": split_brain,
    "leader-isolation": leader_isolation,
    "flapping-link": flapping_link,
    "rolling-crash-restart": rolling_crash_restart,
    "byz-equivocate": byz_equivocate,
    "byz-forge-qc": byz_forge_qc,
    "byz-withhold": byz_withhold,
    "byz-collude": byz_collude,
    "reconfig-rotate": reconfig_rotate,
    "reconfig-join-under-partition": reconfig_join_under_partition,
    "reconfig-retire-crash": reconfig_retire_crash,
    "byz-reconfig": byz_reconfig,
}


def build(name: str, nodes: int = 4, seed: int = 0) -> dict:
    try:
        builder = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; choose from {sorted(SCENARIOS)}"
        ) from None
    return builder(nodes=nodes, seed=seed)


def last_heal(spec: dict) -> float:
    """Scenario time after which no fault is ever active again: the max
    of rule ``until`` edges (impairing rules only) and crash restarts.
    Unbounded rules make the scenario never heal (returns +inf)."""
    t = 0.0
    for rule in spec.get("rules", ()):
        impairs = any(
            rule.get(k) for k in ("drop", "delay_ms", "duplicate", "corrupt")
        ) or "partition" in rule or "isolate" in rule
        if not impairs:
            continue
        until = rule.get("until")
        if until is None:
            return float("inf")
        t = max(t, float(until))
    for crash in spec.get("crashes", ()):
        restart = crash.get("restart_at")
        if restart is None:
            return float("inf")
        t = max(t, float(restart))
    for rule in spec.get("adversary", ()):
        # vote withholding — plus the adaptive policies that delay votes
        # (timeout-surfer), starve a bootstrap (sync-predator), or
        # withhold near epoch boundaries (reconfig-sniper) — impairs
        # liveness; equivocation, forged QCs, double votes, and floods
        # are rejected/absorbed while the committee keeps committing
        if rule.get("policy") not in (
            "withhold", "timeout-surfer", "sync-predator", "reconfig-sniper",
        ):
            continue
        until = rule.get("until")
        if until is None:
            return float("inf")
        t = max(t, float(until))
    return t


__all__ = ["SCENARIOS", "build", "last_heal", "split_brain",
           "leader_isolation", "flapping_link", "rolling_crash_restart",
           "byz_equivocate", "byz_forge_qc", "byz_withhold", "byz_collude",
           "reconfig_rotate", "reconfig_join_under_partition",
           "reconfig_retire_crash", "byz_reconfig"]
