"""AdversaryPlane: seeded, deterministic Byzantine behavior injection.

Where :class:`~hotstuff_tpu.faults.plane.FaultPlane` attacks the
*network* (omission faults: drop/delay/duplicate/corrupt), the
adversary plane attacks the *protocol*: a node selected by the spec
runs one or more attack policies on the same seeded scenario schedule.
Attacks are injected at the proposer/core/aggregator seams — NOT the
wire layer — so every adversarial message is a well-formed frame that
exercises the committee's real verification paths.

Policies
  equivocate   as leader, sign and ship a second conflicting block for
               the same round to a subset of peers
  forge-qc     broadcast properly-signed timeouts whose high_qc names
               real committee authors but carries garbage aggregate
               signatures (hits ``_preverify_burst`` / QC verification
               on honest nodes, which must reject)
  withhold     receive proposals but never vote, forcing the committee
               through timeout quorums (liveness pressure; must heal)
  double-vote  vote for the leader's block AND a fabricated conflicting
               digest in the same round (hits the aggregator's
               second-cell parking on the honest next leader)
  flood        sustained bursts of garbage votes / spoofed votes /
               garbage timeouts (the reusable form of the ad-hoc burst
               loop from tests/test_byzantine_e2e.py)
  collude      f+1 coordinated equivocators: colluders equivocate when
               leading, double-vote the shadow branch, and the
               designated shadow committer reports the shadow chain in
               its commit log — producing a REAL divergent history the
               safety checker must catch and attribute
  reconfig     attack the reconfiguration plane from both ends: as
               leader, attach a FORGED epoch change (attacker-only
               committee, garbage sponsor signature) that must die in
               every honest voter's Block.verify; and report epoch
               activations at skewed rounds — a divergent epoch
               history the epoch-agreement invariant must catch

Determinism contract (same bar as the fault plane): every random
choice is drawn from a per-node ``random.Random`` seeded from
``(scenario seed, node index)`` — str seeding hashes through SHA-512,
so the stream is identical across processes and runs regardless of
PYTHONHASHSEED.  Each decision consumes a FIXED number of draws;
wall-clock gates only which policy windows are active, never the draw
stream.  Shadow payloads are a pure function of (seed, round) so
colluders agree on the shadow branch without communicating.

Spec: the adversary rides in the same JSON spec as the fault plane
(``HOTSTUFF_ADVERSARY`` accepts an inline object or a file path, and
the chaos runner points it at the same ``.faults.json``)::

    {"seed": 0, "name": "byz-equivocate",
     "nodes": {"host:port": index, ...}, "epoch_unix": ...,
     "adversary": [
        {"policy": "equivocate", "node": 0, "at": 2.0, "until": null}
     ]}
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os

from ..utils.clock import default_clock
from .adaptive import (
    ADAPTIVE_POLICIES,
    ADAPTIVE_SHORT,
    ADAPTIVE_TRIGGERS,
    CountingRandom,
    StateView,
    flood_batch_cap,
    load_rng_state,
    rng_state_path,
    save_rng_state,
    surf_fraction,
)
from .plane import _addr_key

log = logging.getLogger(__name__)

POLICIES = (
    "equivocate",
    "forge-qc",
    "withhold",
    "double-vote",
    "flood",
    "collude",
    "reconfig",
) + ADAPTIVE_POLICIES

#: flood policy burst cadence (seconds between bursts)
FLOOD_BURST_S = 0.025


class AdversaryRule:
    """One policy window over a set of adversarial node indexes."""

    __slots__ = ("policy", "nodes", "at", "until", "rate", "label")

    def __init__(self, policy: str, nodes, at: float = 0.0,
                 until: float | None = None, rate: float = 1.0,
                 label: str | None = None):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown adversary policy {policy!r}; "
                f"choose from {sorted(POLICIES)}"
            )
        self.policy = policy
        if isinstance(nodes, int):
            nodes = (nodes,)
        self.nodes = frozenset(int(v) for v in nodes)
        self.at = float(at)
        self.until = None if until is None else float(until)
        self.rate = float(rate)
        self.label = label or policy

    def active(self, t: float) -> bool:
        if t < self.at:
            return False
        return self.until is None or t < self.until


def expand_adversary(spec: dict) -> list[AdversaryRule]:
    """Parse the spec's ``adversary`` list into rules."""
    rules = []
    for raw in spec.get("adversary", ()):
        rules.append(
            AdversaryRule(
                raw["policy"],
                raw.get("node", raw.get("nodes", ())),
                at=raw.get("at", 0.0),
                until=raw.get("until"),
                rate=raw.get("rate", 1.0),
                label=raw.get("label"),
            )
        )
    return rules


class AdversaryPlane:
    """One node's view of the Byzantine scenario.

    Constructed on every node (the spec is shared); inert — every
    ``active()`` query returns False — unless the spec names this
    node's index in at least one policy rule.  The consensus stack
    consults it at the attack seams; the plane owns the RNG, counters,
    journal edges, and the deterministic shadow-branch math.
    """

    def __init__(self, spec: dict, self_address, now: float | None = None):
        self.spec = spec
        self.seed = int(spec.get("seed", 0))
        self.name = spec.get("name", "custom")
        self.nodes: dict[str, int] = {
            k: int(v) for k, v in spec.get("nodes", {}).items()
        }
        self.self_id = self.nodes.get(_addr_key(self_address))
        self.rules = expand_adversary(spec)
        self.my_rules = [
            r for r in self.rules
            if self.self_id is not None and self.self_id in r.nodes
        ]
        clock = default_clock()
        wall0 = clock.time()
        mono0 = clock.monotonic()
        boot = wall0 if now is None else now
        epoch = spec.get("epoch_unix")
        self.epoch = float(epoch) if epoch is not None else boot
        if self.epoch < boot - 3600.0:
            log.warning(
                "adversary spec epoch is stale (%.0fs old); using boot time",
                boot - self.epoch,
            )
            self.epoch = boot
        # monotonic anchor: window arithmetic survives NTP steps
        # (same scheme as FaultPlane — see faults/plane.py)
        self._mono_epoch = mono0 - (wall0 - self.epoch)
        self.rng = CountingRandom(f"{self.seed}|adversary|{self.self_id}")
        self.counts = {
            "byz_equivocations": 0,
            "byz_forged_qcs": 0,
            "byz_votes_withheld": 0,
            "byz_double_votes": 0,
            "byz_floods": 0,
            "byz_shadow_commits": 0,
            "byz_forged_reconfigs": 0,
            "byz_shadow_epochs": 0,
            "byz_flood_accepted": 0,
            "byz_flood_shed": 0,
            "byz_adapt_ambush": 0,
            "byz_adapt_sync": 0,
            "byz_adapt_surf": 0,
            "byz_adapt_snipe": 0,
        }
        #: adaptive plane (faults/adaptive.py): the read-only protocol-
        #: state view, installed by Consensus.spawn via bind_view();
        #: None until then (wants() degrades to active())
        self.view: StateView | None = None
        #: peers mid-state-sync against this node (sync-predator prey),
        #: fed by the StateSyncServer's note_syncing hook
        self._syncing: set = set()
        #: credit window last advertised by the flood target's ingest
        #: ACK (None until the first ACK); caps the next flood batch
        self.flood_credit: int | None = None
        # Restart continuity (ISSUE 18 satellite): when the harness
        # points HOTSTUFF_ADAPT_RNG_DIR at the run workdir, the draw
        # stream is checkpointed after every recorded decision and a
        # crash-restarted adversary resumes it instead of replaying
        # from the top.
        self._rng_path = None
        rng_dir = os.environ.get("HOTSTUFF_ADAPT_RNG_DIR")
        if rng_dir and self.self_id is not None:
            os.makedirs(rng_dir, exist_ok=True)
            self._rng_path = rng_state_path(rng_dir, self.self_id)
            restored = load_rng_state(self._rng_path, self.rng)
            if restored is not None:
                log.info(
                    "adversary rng restored: resuming the decision "
                    "stream at draw %d", restored,
                )
        #: colluding node indexes, sorted (collude rules only)
        self.colluders = sorted(
            frozenset().union(
                *(r.nodes for r in self.rules if r.policy == "collude")
            ) if any(r.policy == "collude" for r in self.rules)
            else frozenset()
        )
        #: authority names of colluders, resolved by bind()
        self.colluder_names: set = set()
        self.names_by_index: dict[int, object] = {}
        self.journal = None  # set by Consensus.spawn when journaling

    @classmethod
    def load(cls, spec_or_path: str, self_address, now: float | None = None):
        """Build a plane from an inline JSON object or a spec file path
        (the ``HOTSTUFF_ADVERSARY`` knob accepts both)."""
        text = spec_or_path.strip()
        if text.startswith("{"):
            spec = json.loads(text)
        else:
            with open(spec_or_path) as f:
                spec = json.load(f)
        return cls(spec, self_address, now=now)

    # ------------------------------------------------------------------
    # selection / scheduling

    @property
    def enabled(self) -> bool:
        """True when the spec names this node in any policy rule."""
        return bool(self.my_rules)

    def _t(self, now: float | None = None) -> float:
        if now is None:
            return default_clock().monotonic() - self._mono_epoch
        return now - self.epoch

    def active(self, policy: str, now: float | None = None) -> bool:
        """Is ``policy`` live on THIS node at ``now``?  The collude
        policy implies equivocate and double-vote (colluders run the
        full attack suite while the window is open)."""
        if not self.my_rules:
            return False
        t = self._t(now)
        for r in self.my_rules:
            if not r.active(t):
                continue
            if r.policy == policy:
                return True
            if r.policy == "collude" and policy in (
                "equivocate", "double-vote",
            ):
                return True
        return False

    # ------------------------------------------------------------------
    # adaptive plane (faults/adaptive.py)

    def bind_view(self, providers: dict) -> None:
        """Install the read-only protocol-state view the adaptive
        triggers observe.  Called by Consensus.spawn once the core is
        built; provider callbacks are pure reads of local state
        (round, leader schedule, timer, admission credit, ...).  Takes
        a dict (not kwargs) because ``self`` is a provider key."""
        base = {
            "syncing": lambda s=self._syncing: frozenset(s),
            "incidents": lambda: 0,
        }
        base.update(providers)
        self.view = StateView(base)

    def note_syncing(self, peer) -> None:
        """Protocol hook (StateSyncServer): ``peer`` requested a
        manifest, i.e. began a snapshot bootstrap against this node.
        Entries persist for the process lifetime — sync-predator stalks
        the peer for as long as its policy window stays open; once the
        window closes the chunks flow and the bootstrap completes."""
        self._syncing.add(peer)

    def wants(self, action: str, round_: int | None = None,
              now: float | None = None):
        """Does any live policy want ``action`` in ``round_``?

        Returns ``True`` when a schedule-driven policy window covers
        the action (exactly ``active()``), the adaptive short token —
        a truthy str the seams pass to :meth:`mark_adaptive` — when a
        state-reactive trigger fires, and ``False`` otherwise.
        Trigger evaluation is a pure read of the state view: ZERO rng
        draws, so the fixed-draw determinism contract is untouched.
        """
        if self.active(action, now):
            return True
        if self.view is None or not self.my_rules:
            return False
        t = self._t(now)
        r = self.view.round if round_ is None else int(round_)
        for rule in self.my_rules:
            trig = ADAPTIVE_TRIGGERS.get(rule.policy)
            if trig is None or not rule.active(t):
                continue
            actions, fire = trig
            if action in actions and fire(self.view, r):
                return ADAPTIVE_SHORT[rule.policy]
        return False

    def mark_adaptive(self, fired, round_: int = 0, logger=None,
                      digest=None) -> None:
        """Attribute an adaptive trigger firing: ``fired`` is the token
        :meth:`wants` returned.  Bumps the per-policy counter, journals
        the ``byz.adapt.<token>`` edge and emits the attack log line
        the ``+ BYZ`` activity regex counts.  A non-str ``fired`` (a
        plain schedule-driven True) is a no-op."""
        if not isinstance(fired, str):
            return
        self.count(f"byz_adapt_{fired}")
        self.record(f"adapt.{fired}", round_, digest)
        (logger or log).info("byz adapt-%s round %d", fired, round_)

    def surf_delay_s(self, timeout_s: float) -> float:
        """timeout-surfer vote delay: a fixed fraction of the OBSERVED
        view timer (backoff included), strictly inside the timeout."""
        return surf_fraction() * float(timeout_s)

    def _save_rng(self) -> None:
        if self._rng_path is not None:
            save_rng_state(self._rng_path, self.rng)

    def bind(self, committee, self_name) -> None:
        """Resolve node indexes to authority names against the live
        committee (the spec only knows addresses)."""
        pairs = list(committee.broadcast_addresses(self_name))
        pairs.append((self_name, committee.address(self_name)))
        for nm, addr in pairs:
            if addr is None:
                continue
            idx = self.nodes.get(_addr_key(addr))
            if idx is not None:
                self.names_by_index[idx] = nm
        self.colluder_names = {
            self.names_by_index[i]
            for i in self.colluders
            if i in self.names_by_index
        }

    @property
    def is_shadow_committer(self) -> bool:
        """The highest-indexed colluder reports the shadow chain in its
        commit log (one divergent history is enough for the checker;
        deterministic designation needs no coordination)."""
        return bool(self.colluders) and self.self_id == self.colluders[-1]

    # ------------------------------------------------------------------
    # attack math (shared by the attacking seams)

    def shadow_payloads(self, round_: int) -> tuple:
        """The shadow branch's payload for ``round_`` — a pure function
        of (seed, round) so every colluder derives the same conflicting
        block without communicating."""
        from ..crypto import Digest

        return (Digest.of(f"byz-shadow|{self.seed}|{round_}".encode()),)

    def shadow_block(self, block):
        """The conflicting twin of ``block``: same author/round/qc/tc,
        shadow payloads.  Unsigned — the equivocator signs its own copy;
        observers only need the digest (signatures are not part of it)."""
        from ..consensus.messages import Block

        return Block(
            qc=block.qc,
            tc=block.tc,
            author=block.author,
            round=block.round,
            payloads=self.shadow_payloads(block.round),
        )

    def equivocation_targets(self, names_addresses):
        """The deterministic peer subset that receives the shadow block:
        fellow colluders when colluding (the honest committee keeps
        committing the main branch), otherwise the lexicographically
        first half of the peer set."""
        pairs = sorted(names_addresses, key=lambda p: str(p[0]))
        if self.colluder_names:
            return [p for p in pairs if p[0] in self.colluder_names]
        return pairs[: max(1, len(pairs) // 2)]

    def forged_qc(self, committee, round_: int):
        """A structurally valid QC — real committee authors, quorum-many
        entries, passes ``check_weight`` — whose signatures are seeded
        garbage, so honest verification MUST reject it.  Consumes 64
        draws per signature (fixed per call for a given committee)."""
        from ..consensus.messages import QC
        from ..crypto import Digest, Signature

        authors = sorted(
            (nm for nm, _ in committee.broadcast_addresses(None)),
            key=str,
        )
        need = committee.quorum_threshold()
        votes = [
            (nm, Signature(bytes(self.rng.getrandbits(8) for _ in range(64))))
            for nm in authors[:need]
        ]
        return QC(
            hash=Digest.of(f"byz-forged|{self.seed}|{round_}".encode()),
            round=round_,
            votes=votes,
        )

    def forged_compact_qc(self, committee, round_: int):
        """The compact-form twin of ``forged_qc``: a quorum-popcount
        signer bitmap over the committee's sorted key order plus a
        seeded garbage 48-byte aggregate signature.  Passes decode and
        ``check_weight``; aggregate verification (one pairing) MUST
        reject it.  Consumes 48 draws (fixed per call)."""
        from ..consensus.messages import QC, make_signer_bitmap
        from ..crypto import Digest, Signature

        ordered = committee.sorted_keys()
        need = committee.quorum_threshold()
        bitmap = make_signer_bitmap(ordered[:need], ordered)
        return QC(
            hash=Digest.of(f"byz-forged|{self.seed}|{round_}".encode()),
            round=round_,
            votes=[],
            agg_sig=Signature(
                bytes(self.rng.getrandbits(8) for _ in range(48))
            ),
            signers=bitmap,
        )

    def forged_reconfig(self, committee, round_: int):
        """A well-formed (wire-decodable) reconfiguration op whose
        committee is entirely attacker keys and whose sponsor signature
        is seeded garbage — it passes decode and rides in this leader's
        block, and MUST die in every honest voter's ``Block.verify``
        (the continuity rule: attacker-only members carry zero stake
        from the current epoch).  One seeded draw gates each leader
        slot; 64 further draws build the garbage signature."""
        if self.rng.random() >= 0.5:
            return None
        from ..consensus.config import Authority, Committee
        from ..consensus.reconfig import ReconfigOp, newest_epoch
        from ..crypto import Signature, generate_keypair

        seed32 = hashlib.sha512(
            f"byz-reconfig|{self.seed}".encode()
        ).digest()[:32]
        cur = committee.for_round(round_)
        authorities = {}
        for i in range(max(1, len(cur.authorities))):
            pk, _ = generate_keypair(seed32, i)
            authorities[pk] = Authority(1, ("203.0.113.1", 7000 + i))
        hostile = Committee(
            authorities=authorities,
            epoch=newest_epoch(committee) + 1,
            scheme="ed25519",
        )
        return ReconfigOp(
            new_committee=hostile,
            margin=4,
            sponsor=next(iter(authorities)),
            signature=Signature(
                bytes(self.rng.getrandbits(8) for _ in range(64))
            ),
        )

    # ------------------------------------------------------------------
    # accounting

    def count(self, key: str, n: int = 1) -> None:
        self.counts[key] = self.counts.get(key, 0) + n
        # decision boundary: checkpoint the draw stream so a restarted
        # adversary resumes rather than replays it (faults/adaptive.py)
        self._save_rng()

    def record(self, event: str, round_: int = 0, digest=None,
               peer: str = "") -> None:
        """Journal a ``byz.*`` edge (rendered as the adversary track by
        ``benchmark traces``)."""
        if self.journal is not None:
            self.journal.record(f"byz.{event}", round_, digest, peer)
        self._save_rng()

    def describe(self) -> str:
        mine = ",".join(sorted({r.policy for r in self.my_rules})) or "none"
        return (
            f"scenario {self.name!r} seed {self.seed} "
            f"(node index {self.self_id}, policies [{mine}])"
        )

    def window_edges(self) -> list[tuple[float, str, str]]:
        """THIS node's policy window edges as (t_rel, "open"|"close",
        policy label), sorted — the adversary clock task walks this."""
        edges: set[tuple[float, str, str]] = set()
        for rule in self.my_rules:
            edges.add((rule.at, "open", rule.label))
            if rule.until is not None:
                edges.add((rule.until, "close", rule.label))
        order = {"close": 0, "open": 1}
        return sorted(edges, key=lambda e: (e[0], order[e[1]], e[2]))

    def stats(self) -> dict:
        """Telemetry snapshot section."""
        return {
            "scenario": self.name,
            "seed": self.seed,
            "node": self.self_id,
            "policies": sorted({r.policy for r in self.my_rules}),
            **self.counts,
        }


async def run_adversary_clock(plane: AdversaryPlane, journal=None) -> None:
    """Walk the adversary's policy window edges in real time, logging
    each and journaling ``byz.open`` / ``byz.close`` records so traces
    render an adversary track.  Spawned by Consensus.spawn on attacking
    nodes; cancelled at shutdown."""
    for t_rel, kind, label in plane.window_edges():
        delay = (plane._mono_epoch + t_rel) - default_clock().monotonic()
        if delay > 0:
            await default_clock().sleep(delay)
        log.info("Adversary window %s: %s (t=%.1fs)", kind, label, t_rel)
        if journal is not None:
            journal.record(f"byz.{kind}", 0, None, label)


async def run_flood(plane: AdversaryPlane, committee, name,
                    signature_service=None) -> None:
    """The flood policy: sustained bursts of garbage votes, spoofed
    votes naming honest authorities, and garbage timeouts — every frame
    well-formed at the wire layer, every signature invalid, so honest
    nodes burn real verification work rejecting them.  The reusable
    form of tests/test_byzantine_e2e.py's ad-hoc burst loop."""
    from ..consensus.errors import SerializationError
    from ..consensus.messages import QC, Timeout, Vote
    from ..consensus.wire import (
        decode_ingest_ack,
        encode_producer_batch,
        encode_timeout,
        encode_vote,
    )
    from ..crypto import Digest, Signature
    from ..network import SimpleSender
    from ..network.framing import read_frame, send_frame
    from ..utils.clock import default_connector

    sender = SimpleSender()
    peers = [
        (nm, addr) for nm, addr in committee.broadcast_addresses(name)
    ]
    honest = [nm for nm, _ in peers]
    rng = plane.rng
    # Credit-capped ingest flood (ISSUE 18 satellite): alongside the
    # garbage-signature bursts, hammer ONE deterministic victim's
    # producer port with content-addressed garbage payloads — but never
    # more per batch than the victim's last advertised admission credit
    # window.  The attack exercises the shed path (typed BUSY ACKs)
    # instead of growing the proposer buffer without bound, and the ACK
    # stream gives the + BYZ block its accepted-vs-shed accounting.
    target = min(peers, key=lambda p: str(p[0])) if peers else None
    ingest_conn = None

    async def ingest_flood(rnd: int) -> None:
        nonlocal ingest_conn
        if target is None:
            return
        cap = flood_batch_cap()
        credit = plane.flood_credit
        n = cap if credit is None else max(1, min(cap, credit))
        items = []
        for k in range(n):
            # pure function of (seed, round, k): zero rng draws, and the
            # body hashes to its digest so content addressing admits it
            # and the payload really consumes admission credit
            body = f"byz-flood|{plane.seed}|{rnd}|{k}".encode()
            items.append((Digest.of(body), body))
        frame = encode_producer_batch(items)
        try:
            if ingest_conn is None:
                ingest_conn = await default_connector()(*target[1])
            reader, writer = ingest_conn
            await send_frame(writer, frame)
            ack = decode_ingest_ack(
                await asyncio.wait_for(read_frame(reader), 1.0)
            )
        except (
            ConnectionError,
            OSError,
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
            SerializationError,
        ):
            conn, ingest_conn = ingest_conn, None
            if conn is not None:
                try:
                    conn[1].close()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    pass
            return
        if ack is None:
            return  # legacy v1 Ack: no admission decision to read
        plane.flood_credit = ack.credit
        plane.count("byz_flood_accepted", ack.accepted)
        plane.count("byz_flood_shed", ack.shed)
        plane.record(
            "flood-admission", rnd, None, f"a{ack.accepted}/s{ack.shed}"
        )
        log.info(
            "byz flood admission: accepted %d shed %d credit %d",
            ack.accepted, ack.shed, ack.credit,
        )

    try:
        while True:
            await default_clock().sleep(FLOOD_BURST_S)
            if not plane.active("flood"):
                continue
            rnd = rng.randrange(1, 1 << 20)
            frames = []
            # (a) garbage votes under our own identity
            for _ in range(3):
                frames.append(encode_vote(Vote(
                    hash=Digest.of(bytes(
                        rng.getrandbits(8) for _ in range(16))),
                    round=rnd,
                    author=name,
                    signature=Signature(bytes(
                        rng.getrandbits(8) for _ in range(64))),
                )))
            # (b) spoofed votes naming honest authorities
            for victim in honest[:2]:
                frames.append(encode_vote(Vote(
                    hash=Digest.of(f"byz-spoof|{rnd}".encode()),
                    round=rnd,
                    author=victim,
                    signature=Signature(bytes(
                        rng.getrandbits(8) for _ in range(64))),
                )))
            # (c) a garbage timeout anchored at the genesis QC
            frames.append(encode_timeout(Timeout(
                high_qc=QC.genesis(),
                round=rnd,
                author=name,
                signature=Signature(bytes(
                    rng.getrandbits(8) for _ in range(64))),
            )))
            for _, addr in peers:
                for frame in frames:
                    await sender.send(addr, frame)
            await ingest_flood(rnd)
            plane.count("byz_floods")
            plane.record("flood", rnd, None, f"{len(frames)}x{len(peers)}")
            log.info(
                "byz flood burst: %d frames to %d peers (round %d)",
                len(frames), len(peers), rnd,
            )
    except asyncio.CancelledError:
        raise
    finally:
        if ingest_conn is not None:
            try:
                ingest_conn[1].close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        close = getattr(sender, "close", None)
        if close is not None:
            try:
                res = close()
                if asyncio.iscoroutine(res):
                    await res
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass


__all__ = [
    "POLICIES",
    "AdversaryPlane",
    "AdversaryRule",
    "expand_adversary",
    "run_adversary_clock",
    "run_flood",
]
