"""Adaptive adversary policies: state-reactive Byzantine attacks.

The base :class:`~hotstuff_tpu.faults.adversary.AdversaryPlane` fires
its policies on a seeded wall-clock schedule that cannot see what the
protocol is doing, so attacks that only bite in a specific protocol
state — a leader handoff, a snapshot bootstrap, an epoch boundary —
land by luck.  This module adds policies that *observe* a read-only,
deterministic **protocol-state view** and trigger exactly in the state
they were designed to exploit:

  ambush-leader    equivocate only in rounds where this node leads AND
                   the previous round ended in a TC (the committee is
                   already off-balance; a conflicting block there costs
                   the most)
  sync-predator    withhold exactly the state-sync CHUNKS a crash-
                   recovered peer is bootstrapping from us (manifests
                   are still served, so the victim commits to a sync it
                   cannot finish until the window closes)
  timeout-surfer   delay votes to just inside the observed view-timer
                   (backoff included), stretching every view to near
                   its timeout without ever firing a TC
  reconfig-sniper  forge reconfig ops and withhold votes only inside a
                   margin of rounds around an epoch activation boundary

State-view contract
-------------------
The view is a frozen façade over provider callbacks installed by
``Consensus.spawn`` (``AdversaryPlane.bind_view``).  It is READ-ONLY —
attribute assignment raises — and every provider is a pure read of
local protocol state (current round, leader schedule, last TC round,
view-timer duration, admission credit, peers mid-state-sync, epoch
boundaries, open incidents).  Trigger functions are pure predicates of
``(view, round)`` and consume **zero** rng draws, so the base plane's
fixed-draw determinism contract is untouched: the seeded decision
stream is byte-for-byte the same whether triggers fire or not.

Rng continuity across restarts
------------------------------
:class:`CountingRandom` counts primitive draws; when
``HOTSTUFF_ADAPT_RNG_DIR`` is set (the deterministic sim points it at
the run workdir) the plane checkpoints its rng state after every
recorded decision, and a crash-restarted adversary resumes the SAME
decision stream instead of replaying it from the top.
"""

from __future__ import annotations

import json
import logging
import os
import random

log = logging.getLogger(__name__)

#: the adaptive policy names accepted in adversary specs (rides in the
#: same ``adversary`` rule list as the base policies)
ADAPTIVE_POLICIES = (
    "ambush-leader",
    "sync-predator",
    "timeout-surfer",
    "reconfig-sniper",
)

#: policy -> short token used in counters (``byz_adapt_<token>``), log
#: lines (``byz adapt-<token> round N``, counted by the + BYZ block)
#: and journal edges (``byz.adapt.<token>``)
ADAPTIVE_SHORT = {
    "ambush-leader": "ambush",
    "sync-predator": "sync",
    "timeout-surfer": "surf",
    "reconfig-sniper": "snipe",
}


def surf_fraction() -> float:
    """timeout-surfer vote delay as a fraction of the observed view
    timer; clamped below 1.0 so the delayed vote always lands inside
    the timeout (the whole point is stalling WITHOUT firing a TC)."""
    frac = float(os.environ.get("HOTSTUFF_ADAPT_SURF_FRACTION", "0.55"))
    return max(0.0, min(0.95, frac))


def snipe_margin() -> int:
    """reconfig-sniper activation margin: the attack window spans
    ``boundary ± margin`` rounds around every epoch activation."""
    return int(os.environ.get("HOTSTUFF_ADAPT_SNIPE_MARGIN", "8"))


def flood_batch_cap() -> int:
    """Upper bound on one credit-capped flood producer batch (the
    effective batch is ``min(cap, victim's last advertised credit)``)."""
    return int(os.environ.get("HOTSTUFF_ADAPT_FLOOD_BATCH", "64"))


class StateView:
    """Read-only, deterministic view of the local protocol state.

    Built from provider callbacks (``AdversaryPlane.bind_view``); every
    accessor is a fresh pure read, so policies always see the current
    state without holding any mutable reference to it.  Mutation — of
    attributes or of the provider table — raises ``AttributeError``:
    an adaptive policy can observe the protocol, never steer it except
    through its declared attack seams.
    """

    __slots__ = ("_providers",)

    def __init__(self, providers: dict):
        object.__setattr__(self, "_providers", dict(providers))

    def __setattr__(self, name, value):
        raise AttributeError("StateView is read-only")

    def __delattr__(self, name):
        raise AttributeError("StateView is read-only")

    def _call(self, key: str, default=None):
        fn = self._providers.get(key)
        return default if fn is None else fn()

    @property
    def round(self) -> int:
        """The core's current consensus round."""
        return int(self._call("round", 0))

    def is_leader(self, round_: int) -> bool:
        """Does THIS node lead ``round_`` under the live schedule?"""
        leader = self._providers.get("leader")
        me = self._providers.get("self")
        if leader is None or me is None:
            return False
        return leader(int(round_)) == me()

    @property
    def last_tc_round(self) -> int | None:
        """The most recent round this node advanced past via a TC
        (None until the first TC advance)."""
        return self._call("last_tc_round")

    @property
    def timeout_ms(self) -> float:
        """The observed view-timer duration (backoff included)."""
        return float(self._call("timeout_ms", 0.0))

    @property
    def credit(self) -> int | None:
        """The local admission plane's last advertised credit window."""
        return self._call("credit")

    @property
    def syncing_peers(self) -> frozenset:
        """Peers that requested a state-sync manifest from this node
        (i.e. are mid-bootstrap against us)."""
        return frozenset(self._call("syncing", ()))

    @property
    def epoch_boundaries(self) -> tuple:
        """Rounds at which a non-initial epoch activates (empty for a
        static committee)."""
        return tuple(self._call("boundaries", ()))

    @property
    def incidents(self) -> int:
        """Open health-plane incidents observed locally."""
        return int(self._call("incidents", 0))


# ---------------------------------------------------------------------------
# trigger predicates — pure functions of (view, round), zero rng draws


def ambush_trigger(view: StateView, round_: int) -> bool:
    """Fire when this node leads ``round_`` and the PREVIOUS round was
    entered via a TC: ``_advance_round(r-1, via_tc=True)`` moves the
    committee to round r, so ``last_tc_round == round_ - 1`` means the
    view change that seated us as leader is still fresh."""
    last_tc = view.last_tc_round
    return (
        last_tc is not None
        and last_tc == round_ - 1
        and view.is_leader(round_)
    )


def sync_trigger(view: StateView, round_: int) -> bool:
    """Fire while at least one peer is mid-state-sync against us."""
    return bool(view.syncing_peers)


def surf_trigger(view: StateView, round_: int) -> bool:
    """Fire for votes routed to OTHER collectors: delaying a vote we
    would hand to ourselves stalls nobody but us."""
    return not view.is_leader(round_ + 1)


def snipe_trigger(view: StateView, round_: int) -> bool:
    """Fire within ``snipe_margin()`` rounds of any epoch activation
    boundary the live committee schedule declares."""
    margin = snipe_margin()
    return any(
        abs(int(round_) - int(b)) <= margin for b in view.epoch_boundaries
    )


#: policy -> (base actions it drives, trigger predicate).  The plane's
#: ``wants(action)`` consults this table after the schedule-driven
#: ``active(action)`` check: an adaptive rule whose window is open AND
#: whose trigger fires claims the action.
ADAPTIVE_TRIGGERS = {
    "ambush-leader": (("equivocate",), ambush_trigger),
    "sync-predator": (("sync-withhold",), sync_trigger),
    "timeout-surfer": (("vote-delay",), surf_trigger),
    "reconfig-sniper": (("reconfig", "withhold"), snipe_trigger),
}


# ---------------------------------------------------------------------------
# counted rng + restart continuity


class CountingRandom(random.Random):
    """``random.Random`` that counts primitive draws.

    Every composite method (``randrange``, ``sample``, ``uniform``,
    ...) funnels through ``random()`` or ``getrandbits()`` in CPython,
    so counting the two primitives counts every decision the adversary
    makes.  The count is what the restart-continuity checkpoint
    persists alongside the generator state."""

    def __init__(self, seedval=None):
        self.draws = 0
        super().__init__(seedval)

    def random(self):
        self.draws += 1
        return super().random()

    def getrandbits(self, k):
        self.draws += 1
        return super().getrandbits(k)


def rng_state_path(dir_: str, self_id: int) -> str:
    return os.path.join(dir_, f"adversary-rng-{int(self_id)}.json")


def save_rng_state(path: str, rng: CountingRandom) -> None:
    """Checkpoint the adversary's draw stream.  Atomic (write + rename)
    so a crash mid-save leaves the previous checkpoint intact."""
    version, internal, gauss = rng.getstate()
    doc = {
        "draws": rng.draws,
        "version": version,
        "internal": list(internal),
        "gauss": gauss,
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def load_rng_state(path: str, rng: CountingRandom) -> int | None:
    """Restore a checkpointed draw stream into ``rng``; returns the
    replayed draw count, or None when no checkpoint exists."""
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    rng.setstate((doc["version"], tuple(doc["internal"]), doc["gauss"]))
    rng.draws = int(doc["draws"])
    return rng.draws


__all__ = [
    "ADAPTIVE_POLICIES",
    "ADAPTIVE_SHORT",
    "ADAPTIVE_TRIGGERS",
    "CountingRandom",
    "StateView",
    "ambush_trigger",
    "flood_batch_cap",
    "load_rng_state",
    "rng_state_path",
    "save_rng_state",
    "snipe_margin",
    "snipe_trigger",
    "surf_fraction",
    "surf_trigger",
    "sync_trigger",
]
