"""FaultPlane: seeded, deterministic network fault injection.

The plane is an interception layer threaded through the network stack
the same way ``wan.py``'s ``delay_fn`` is: each sender resolves a
per-directed-link :class:`LinkFaults` view once per connection and
consults it per frame; the receiver consults the plane for inbound
cuts.  Four frame-level faults per directed peer pair — drop, delay,
duplicate, corrupt — gated by a **scenario schedule** (timeline of
partition/heal windows, asymmetric links, flapping links) parsed from a
small JSON spec and replayable from a single RNG seed.

Determinism contract (the seeded-chaos acceptance bar): every random
choice a link ever makes is drawn from a per-link ``random.Random``
seeded from ``(scenario seed, src index, dst index)`` — str seeding
hashes through SHA-512, so the stream is identical across processes and
runs regardless of PYTHONHASHSEED.  ``decide()`` consumes a FIXED
number of draws per call, so the n-th decision on a link is a pure
function of (seed, scenario, n); wall-clock only gates which scenario
windows are active, never the draw stream.

Crash/restart directives (``crashes`` in the spec) are process-level:
the chaos benchmark runner (benchmark/chaos.py) executes them by
killing and respawning node subprocesses; the in-node plane ignores
them.
"""

from __future__ import annotations

import json
import logging
import random
from typing import NamedTuple

from ..utils.clock import default_clock

log = logging.getLogger(__name__)

Address = tuple[str, int]

#: poll interval while a reliable link holds frames through a hard cut
BARRIER_POLL_S = 0.05


class Decision(NamedTuple):
    """One frame's fate.  ``drop`` wins over everything; the others
    compose (a frame can be delayed AND duplicated AND corrupted)."""

    drop: bool = False
    delay_s: float = 0.0
    duplicate: bool = False
    corrupt: bool = False


#: the no-fault decision (shared instance: the common case allocates nothing)
PASS = Decision()


def corrupt_frame(data: bytes) -> bytes:
    """Deterministically flip one byte mid-frame (receivers must treat
    the result as a malformed message and drop it)."""
    if not data:
        return data
    i = len(data) // 2
    return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1 :]


def _addr_key(address) -> str:
    if isinstance(address, str):
        return address
    return f"{address[0]}:{address[1]}"


class FaultRule:
    """One primitive scenario rule: an active window over a set of
    directed links with fault probabilities/parameters."""

    __slots__ = (
        "label",
        "at",
        "until",
        "src",
        "dst",
        "drop",
        "delay_s",
        "jitter_pct",
        "duplicate",
        "corrupt",
        "every",
        "for_",
    )

    def __init__(
        self,
        label: str,
        at: float,
        until: float | None,
        src,  # "*" or frozenset[int]
        dst,
        drop: float = 0.0,
        delay_s: float = 0.0,
        jitter_pct: float = 0.0,
        duplicate: float = 0.0,
        corrupt: float = 0.0,
        every: float | None = None,
        for_: float | None = None,
    ):
        self.label = label
        self.at = float(at)
        self.until = None if until is None else float(until)
        self.src = src
        self.dst = dst
        self.drop = float(drop)
        self.delay_s = float(delay_s)
        self.jitter_pct = float(jitter_pct)
        self.duplicate = float(duplicate)
        self.corrupt = float(corrupt)
        self.every = every
        self.for_ = for_

    def matches(self, src: int, dst: int) -> bool:
        if self.src != "*" and src not in self.src:
            return False
        return self.dst == "*" or dst in self.dst

    def active(self, t: float) -> bool:
        """Is the rule live at scenario time ``t`` (seconds from epoch)?"""
        if t < self.at:
            return False
        if self.until is not None and t >= self.until:
            return False
        if self.every:
            # flapping sugar: within the window, on for `for_` seconds
            # out of every `every`
            return ((t - self.at) % self.every) < (self.for_ or 0.0)
        return True

    def reps(self) -> list[tuple[float, float]]:
        """The rule's on-windows as [(open, close)] in scenario time —
        the journal/clock edge list.  Unbounded rules close at +inf."""
        end = self.until if self.until is not None else float("inf")
        if not self.every:
            return [(self.at, end)]
        out = []
        t = self.at
        while t < end:
            out.append((t, min(t + (self.for_ or 0.0), end)))
            t += self.every
        return out


def _selector(value, n_hint: int | None = None):
    """Parse a from/to selector: "*" or a list of node indexes."""
    if value in ("*", None):
        return "*"
    if isinstance(value, int):
        return frozenset((value,))
    return frozenset(int(v) for v in value)


def expand_rules(spec: dict) -> tuple[list[FaultRule], list[FaultRule]]:
    """Expand the spec's ``rules`` (sugar included) into primitive
    link rules plus inbound-cut rules (``isolate`` only).

    Sugar forms:
      {"partition": [[0,1],[2,3]], "at": 5, "until": 13}
          -> drop=1.0 on every cross-group link, both directions
      {"isolate": 2, "at": 5, "until": 9}
          -> drop=1.0 on k->* and *->k, PLUS an inbound cut on k (so
             frames from senders with no plane — clients — die too)
    """
    link_rules: list[FaultRule] = []
    inbound_rules: list[FaultRule] = []
    for i, raw in enumerate(spec.get("rules", ())):
        label = raw.get("label") or f"rule-{i}"
        window = dict(
            at=raw.get("at", 0.0),
            until=raw.get("until"),
            every=raw.get("every"),
            for_=raw.get("for"),
        )
        if "partition" in raw:
            groups = [frozenset(int(v) for v in g) for g in raw["partition"]]
            for gi, g in enumerate(groups):
                others = frozenset().union(
                    *(h for gj, h in enumerate(groups) if gj != gi)
                ) if len(groups) > 1 else frozenset()
                if others:
                    link_rules.append(
                        FaultRule(label, src=g, dst=others, drop=1.0, **window)
                    )
            continue
        if "isolate" in raw:
            k = frozenset((int(raw["isolate"]),))
            link_rules.append(
                FaultRule(label, src=k, dst="*", drop=1.0, **window)
            )
            link_rules.append(
                FaultRule(label, src="*", dst=k, drop=1.0, **window)
            )
            inbound_rules.append(
                FaultRule(label, src="*", dst=k, drop=1.0, **window)
            )
            continue
        link_rules.append(
            FaultRule(
                label,
                src=_selector(raw.get("from")),
                dst=_selector(raw.get("to")),
                drop=raw.get("drop", 0.0),
                delay_s=raw.get("delay_ms", 0.0) / 1000.0,
                jitter_pct=raw.get("jitter_pct", 0.0),
                duplicate=raw.get("duplicate", 0.0),
                corrupt=raw.get("corrupt", 0.0),
                **window,
            )
        )
    return link_rules, inbound_rules


class LinkFaults:
    """Per directed (self -> dst) view of the plane.  One per sender
    connection, resolved once like wan.py's ``delay_fn``."""

    __slots__ = ("_rng", "_rules", "_plane", "seq", "dropped")

    def __init__(self, plane: "FaultPlane", rules: list[FaultRule], seed_key: str):
        self._plane = plane
        self._rules = rules
        self._rng = random.Random(seed_key)
        self.seq = 0  # decisions drawn on this link
        self.dropped = 0

    def barrier(self, now: float | None = None) -> bool:
        """True while a hard cut (drop >= 1.0 window) is live on this
        link.  Consumes NO draws — reliable senders poll it to hold
        frames through a partition instead of burning loss decisions."""
        t = self._plane._t(now)
        return any(r.drop >= 1.0 and r.active(t) for r in self._rules)

    def decide(self, now: float | None = None) -> Decision:
        """The next frame's fate.  Always consumes exactly 4 draws so
        decision n is a pure function of (seed, scenario, n)."""
        rng = self._rng
        r_drop = rng.random()
        r_dup = rng.random()
        r_cor = rng.random()
        r_jit = rng.random()
        self.seq += 1
        t = self._plane._t(now)
        active = [r for r in self._rules if r.active(t)]
        if not active:
            return PASS
        counts = self._plane.counts
        drop_p = max(r.drop for r in active)
        if drop_p > 0.0 and r_drop < drop_p:
            self.dropped += 1
            counts["dropped"] += 1
            return Decision(drop=True)
        delay_s = 0.0
        for r in active:
            if r.delay_s > 0.0:
                d = r.delay_s
                if r.jitter_pct:
                    d *= 1.0 + (r.jitter_pct / 100.0) * (2.0 * r_jit - 1.0)
                delay_s = max(delay_s, d)
        dup_p = max(r.duplicate for r in active)
        cor_p = max(r.corrupt for r in active)
        duplicate = dup_p > 0.0 and r_dup < dup_p
        corrupt = cor_p > 0.0 and r_cor < cor_p
        if not (delay_s or duplicate or corrupt):
            return PASS
        if delay_s:
            counts["delayed"] += 1
        if duplicate:
            counts["duplicated"] += 1
        if corrupt:
            counts["corrupted"] += 1
        return Decision(False, max(delay_s, 0.0), duplicate, corrupt)


class FaultPlane:
    """One node's view of the scenario: resolves per-link fault views
    for its outbound connections plus the node's inbound cut state.

    ``spec`` keys: ``seed`` (int), ``nodes`` ("host:port" -> index),
    ``rules`` (see :func:`expand_rules`), optional ``epoch_unix``
    (shared scenario t=0 across the committee; defaults to plane
    construction time), optional ``name``/``crashes``/``liveness``
    (runner-side, carried through for the invariant checker).
    """

    def __init__(self, spec: dict, self_address, now: float | None = None):
        self.spec = spec
        self.seed = int(spec.get("seed", 0))
        self.name = spec.get("name", "custom")
        self.nodes: dict[str, int] = {
            k: int(v) for k, v in spec.get("nodes", {}).items()
        }
        self.self_id = self.nodes.get(_addr_key(self_address))
        self.rules, self._inbound_rules = expand_rules(spec)
        clock = default_clock()
        wall0 = clock.time()
        mono0 = clock.monotonic()
        boot = wall0 if now is None else now
        epoch = spec.get("epoch_unix")
        # a stale epoch (config written long before boot, or clock skew)
        # would put the whole timeline in the past; fall back to boot
        self.epoch = float(epoch) if epoch is not None else boot
        if self.epoch < boot - 3600.0:
            log.warning(
                "fault spec epoch is stale (%.0fs old); using boot time",
                boot - self.epoch,
            )
            self.epoch = boot
        # Anchor the window timeline to the MONOTONIC clock: the wall
        # epoch is only used once, here, to compute the monotonic value
        # of scenario t=0.  An NTP step after construction can therefore
        # never shift partition/heal windows mid-run.
        self._mono_epoch = mono0 - (wall0 - self.epoch)
        self.counts = {
            "dropped": 0,
            "delayed": 0,
            "duplicated": 0,
            "corrupted": 0,
            "inbound_dropped": 0,
        }
        self._links: dict[str, LinkFaults | None] = {}
        self._my_inbound = [
            r
            for r in self._inbound_rules
            if self.self_id is not None and r.matches(0, self.self_id)
        ]

    @classmethod
    def load(cls, spec_or_path: str, self_address, now: float | None = None):
        """Build a plane from an inline JSON object or a spec file path
        (the ``HOTSTUFF_FAULTS`` knob accepts both)."""
        text = spec_or_path.strip()
        if text.startswith("{"):
            spec = json.loads(text)
        else:
            with open(spec_or_path) as f:
                spec = json.load(f)
        return cls(spec, self_address, now=now)

    def _t(self, now: float | None = None) -> float:
        if now is None:
            return default_clock().monotonic() - self._mono_epoch
        return now - self.epoch

    def describe(self) -> str:
        return (
            f"scenario {self.name!r} seed {self.seed} "
            f"(node index {self.self_id}, {len(self.rules)} link rules)"
        )

    def link(self, address) -> LinkFaults | None:
        """The fault view of the directed link self -> ``address``, or
        None when no scenario rule can ever touch it (fast path: the
        sender skips all fault logic on that connection)."""
        key = _addr_key(address)
        if key in self._links:
            return self._links[key]
        lf = None
        dst = self.nodes.get(key)
        if self.self_id is not None and dst is not None:
            rules = [r for r in self.rules if r.matches(self.self_id, dst)]
            if rules:
                lf = LinkFaults(
                    self, rules, f"{self.seed}|{self.self_id}->{dst}"
                )
        self._links[key] = lf
        return lf

    def inbound_cut(self, now: float | None = None) -> bool:
        """True while this node is inside an ``isolate`` window: the
        receiver drops every inbound frame (covers senders with no
        plane of their own, e.g. clients)."""
        if not self._my_inbound:
            return False
        t = self._t(now)
        if any(r.active(t) for r in self._my_inbound):
            self.counts["inbound_dropped"] += 1
            return True
        return False

    def window_edges(self) -> list[tuple[float, str, str]]:
        """Every scenario window edge as (t_rel, "open"|"close", label),
        sorted — the journal clock task walks this list.  Deduplicated
        (partition/isolate sugar expands to several rules per label)."""
        edges: set[tuple[float, str, str]] = set()
        for rule in self.rules:
            for t_open, t_close in rule.reps():
                edges.add((t_open, "open", rule.label))
                if t_close != float("inf"):
                    edges.add((t_close, "close", rule.label))
        order = {"close": 0, "open": 1}
        return sorted(edges, key=lambda e: (e[0], order[e[1]], e[2]))

    def stats(self) -> dict:
        """Telemetry snapshot section."""
        return {
            "scenario": self.name,
            "seed": self.seed,
            "node": self.self_id,
            **self.counts,
            "links": {
                key: {"seq": lf.seq, "dropped": lf.dropped}
                for key, lf in self._links.items()
                if lf is not None
            },
        }


async def run_clock(plane: FaultPlane, journal=None) -> None:
    """Walk the scenario's window edges in real time, logging each and
    journaling ``fault.open`` / ``fault.close`` records so Perfetto
    traces (benchmark/traces.py) render partition spans.  Spawned by
    Consensus.spawn when a plane is active; cancelled at shutdown."""
    for t_rel, kind, label in plane.window_edges():
        delay = (plane._mono_epoch + t_rel) - default_clock().monotonic()
        if delay > 0:
            await default_clock().sleep(delay)
        log.info("Fault window %s: %s (t=%.1fs)", kind, label, t_rel)
        if journal is not None:
            journal.record(f"fault.{kind}", 0, None, label)


__all__ = [
    "Address",
    "BARRIER_POLL_S",
    "Decision",
    "FaultPlane",
    "FaultRule",
    "LinkFaults",
    "PASS",
    "corrupt_frame",
    "expand_rules",
    "run_clock",
]
