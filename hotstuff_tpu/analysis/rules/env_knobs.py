"""env-knob-registry: every HOTSTUFF_* knob is documented or the gate
fails.

The check is a freshness diff: re-render ``docs/KNOBS.md`` from the
tree (``analysis/knobgen.py``) and compare against the committed file.
A new ``os.environ`` read — direct or through an ``_env_int``-style
helper — changes the rendered table, so an undocumented knob and a
stale table are the same single finding with the regeneration command
in the message.
"""

from __future__ import annotations

import os

from .. import knobgen
from ..framework import Finding

RULE = "env-knob-registry"


class EnvKnobRegistry:
    name = RULE
    # the rule diffs the whole tree itself; anchor the runner's file
    # iteration on a single always-present file so check() runs once
    targets = ("hotstuff_tpu/__init__.py",)

    def check(self, sf, root) -> list[Finding]:
        if knobgen.is_fresh(root):
            return []
        exists = os.path.exists(
            os.path.join(root, *knobgen.KNOBS_REL.split("/"))
        )
        what = "stale" if exists else "missing"
        return [
            Finding(
                RULE,
                knobgen.KNOBS_REL,
                1,
                what,
                f"{knobgen.KNOBS_REL} is {what}: the HOTSTUFF_* knob "
                f"table no longer matches the tree — regenerate with "
                f"`python -m hotstuff_tpu.analysis gen-knobs`",
            )
        ]
