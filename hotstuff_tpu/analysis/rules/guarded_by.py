"""guarded-by: cross-thread fields carry their synchronization story.

PR 6 introduced dispatch-loop *threads* under the asyncio node: the
verify service's slot threads and completion callbacks run concurrently
with the event loop and share instance fields with it.  CPython's GIL
makes single-bytecode operations atomic, which is why most of these
fields legitimately carry no lock — but that discipline was tribal
knowledge.  This rule makes it explicit:

1. **Thread discovery.**  Inside each class, every callable handed to
   ``threading.Thread(target=...)``, ``<executor>.submit(...)``, or
   ``loop.run_in_executor(...)`` is a thread entry point — ``self.M``
   references, inline lambdas, and nested ``def`` callbacks alike —
   and the closure over ``self.M()`` calls from thread-side code is
   taken transitively.
2. **Shared fields.**  A ``self.<field>`` accessed from both thread-side
   and loop-side code, with at least one write outside ``__init__``,
   is shared state.
3. **Annotation.**  Some access line of a shared field must carry
   ``# guarded-by: <token>``.  When the token names a ``threading.Lock``
   / ``RLock`` attribute of the class, every non-``__init__`` write to
   the field must sit inside ``with self.<token>:`` — a lockset check,
   not just documentation.  Tokens like ``gil`` document a deliberate
   lock-free discipline and are accepted as-is.
4. **Lock-discipline drift** (lock-owning classes without visible
   thread creation, e.g. ``tpu/ed25519.py`` whose callers thread from
   outside): a field ever written under ``with self.<lock>`` must not
   also be written outside it without an annotation.
"""

from __future__ import annotations

import ast

from ..framework import Finding, dotted_name

RULE = "guarded-by"

_LOCK_CTORS = {"Lock", "RLock"}
_MUTATORS = {
    "append", "add", "pop", "clear", "update", "remove", "discard",
    "setdefault", "extend", "insert", "popleft", "appendleft",
    "put_nowait",
}


def _self_field(node) -> str | None:
    """``field`` for a ``self.field`` attribute node."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _Access:
    __slots__ = ("line", "write", "locks")

    def __init__(self, line, write, locks):
        self.line = line
        self.write = write
        self.locks = locks  # frozenset of held self.<lock> names


class GuardedBy:
    name = RULE
    targets = (
        "hotstuff_tpu/crypto/async_service.py",
        "hotstuff_tpu/telemetry/**/*.py",
        "hotstuff_tpu/tpu/**/*.py",
    )

    def check(self, sf, root) -> list[Finding]:
        findings: list[Finding] = []
        for cls in ast.walk(sf.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(sf, cls))
        return findings

    # ---- per-class analysis -------------------------------------------

    def _check_class(self, sf, cls) -> list[Finding]:
        methods = {
            m.name: m
            for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        locks = self._lock_attrs(methods.get("__init__"))
        entries, inline_thread_nodes = self._thread_entries(cls, methods)
        thread_methods = self._closure(entries, methods)

        # field -> side ("thread"/"loop"/"init") -> [_Access]
        accesses: dict = {}

        def collect(body_node, side):
            self._collect_accesses(body_node, side, accesses)

        for name, m in methods.items():
            if name == "__init__":
                collect(m, "init")
            elif name in thread_methods:
                collect(m, "thread")
            else:
                collect(m, "loop")
        for node in inline_thread_nodes:
            collect(node, "thread")

        findings: list[Finding] = []
        flagged = set()
        for field, sides in sorted(accesses.items()):
            thread = sides.get("thread", ())
            loop = sides.get("loop", ())
            init = sides.get("init", ())
            writes = [a for a in (*thread, *loop) if a.write]
            shared = bool(thread) and bool(loop) and bool(writes)
            all_lines = sorted(
                {a.line for a in (*thread, *loop, *init)}
            )
            token = None
            for line in all_lines:
                token = sf.guarded_by(line)
                if token:
                    break
            if shared and token is None:
                key = f"{cls.name}.{field}"
                if key not in flagged:
                    flagged.add(key)
                    line = min(a.line for a in writes)
                    findings.append(
                        Finding(
                            RULE,
                            sf.rel,
                            line,
                            key,
                            f"{cls.name}.{field} is written from a "
                            f"dispatch-loop thread and touched from "
                            f"the event loop with no "
                            f"# guarded-by: <lock> annotation on any "
                            f"access line",
                        )
                    )
                continue
            if token in locks:
                # annotated with a real lock: every non-init write must
                # hold it
                for a in writes:
                    if token not in a.locks:
                        key = f"{cls.name}.{field}:unlocked"
                        if key in flagged:
                            continue
                        flagged.add(key)
                        findings.append(
                            Finding(
                                RULE,
                                sf.rel,
                                a.line,
                                key,
                                f"{cls.name}.{field} is guarded-by "
                                f"{token} but written at line {a.line} "
                                f"without holding with self.{token}",
                            )
                        )
            elif token is None and locks:
                # drift check: written under a lock somewhere, written
                # outside it elsewhere, no annotation explaining why
                under = {
                    lk
                    for a in writes
                    for lk in a.locks
                    if lk in locks
                }
                if under:
                    for a in writes:
                        if not (under & a.locks):
                            key = f"{cls.name}.{field}:drift"
                            if key in flagged:
                                continue
                            flagged.add(key)
                            lock_name = sorted(under)[0]
                            findings.append(
                                Finding(
                                    RULE,
                                    sf.rel,
                                    a.line,
                                    key,
                                    f"{cls.name}.{field} is written "
                                    f"under with self.{lock_name} "
                                    f"elsewhere but written unlocked at "
                                    f"line {a.line} — annotate the "
                                    f"discipline with # guarded-by: or "
                                    f"take the lock",
                                )
                            )
        return findings

    # ---- discovery helpers --------------------------------------------

    def _lock_attrs(self, init) -> set:
        """self attrs assigned threading.Lock()/RLock() in __init__."""
        locks = set()
        if init is None:
            return locks
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                ctor = dotted_name(node.value.func) or ""
                if ctor.split(".")[-1] in _LOCK_CTORS:
                    for t in node.targets:
                        field = _self_field(t)
                        if field:
                            locks.add(field)
        return locks

    def _thread_entries(self, cls, methods):
        """(method names that are thread entry points, inline thread
        callables: Lambda / nested FunctionDef nodes)."""
        entries: set = set()
        inline: list = []
        for m in methods.values():
            nested = {
                n.name: n
                for n in ast.walk(m)
                if isinstance(n, ast.FunctionDef) and n is not m
            }
            for node in ast.walk(m):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                attr = fn.attr if isinstance(fn, ast.Attribute) else None
                cands = []
                if attr == "Thread" or (
                    isinstance(fn, ast.Name) and fn.id == "Thread"
                ):
                    cands = [
                        kw.value for kw in node.keywords
                        if kw.arg == "target"
                    ]
                elif attr == "submit":
                    cands = list(node.args)
                elif attr == "run_in_executor":
                    cands = list(node.args[1:])
                for cand in cands:
                    field = _self_field(cand)
                    if field and field in methods:
                        entries.add(field)
                    elif isinstance(cand, ast.Lambda):
                        inline.append(cand)
                        entries |= self._self_calls(cand, methods)
                    elif (
                        isinstance(cand, ast.Name) and cand.id in nested
                    ):
                        inline.append(nested[cand.id])
                        entries |= self._self_calls(
                            nested[cand.id], methods
                        )
        return entries, inline

    def _self_calls(self, node, methods) -> set:
        """Method names invoked as ``self.M(...)`` inside ``node``."""
        out = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                field = _self_field(n.func)
                if field and field in methods:
                    out.add(field)
        return out

    def _closure(self, entries, methods) -> set:
        """Transitive closure of thread-side methods over self-calls."""
        seen = set()
        frontier = list(entries)
        while frontier:
            name = frontier.pop()
            if name in seen or name not in methods:
                continue
            seen.add(name)
            frontier.extend(self._self_calls(methods[name], methods))
        return seen

    # ---- access collection --------------------------------------------

    def _collect_accesses(self, body, side, accesses) -> None:
        """Record every ``self.<field>`` read/write under ``body`` with
        the set of ``with self.<lock>`` contexts lexically held."""

        def visit(node, held):
            if isinstance(node, ast.With):
                extra = set()
                for item in node.items:
                    field = _self_field(item.context_expr)
                    if field:
                        extra.add(field)
                inner = held | frozenset(extra)
                for item in node.items:
                    visit(item.context_expr, held)
                for child in node.body:
                    visit(child, inner)
                return
            field = _self_field(node)
            if field is not None:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                accesses.setdefault(field, {}).setdefault(
                    side, []
                ).append(_Access(node.lineno, write, held))
            if isinstance(node, ast.Subscript):
                # self.f[k] = v: the Subscript has Store ctx but the
                # inner attribute reads — record the write on the field
                field = _self_field(node.value)
                if field is not None and isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ):
                    accesses.setdefault(field, {}).setdefault(
                        side, []
                    ).append(_Access(node.lineno, True, held))
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                # self.f.pop(...) and friends mutate the container
                field = _self_field(node.func.value)
                if field is not None and node.func.attr in _MUTATORS:
                    accesses.setdefault(field, {}).setdefault(
                        side, []
                    ).append(_Access(node.lineno, True, held))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(body, frozenset())
