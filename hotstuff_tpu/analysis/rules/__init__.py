"""The rule registry: ``ALL_RULES`` is what the CLI and the gate run."""

from .blocking import NoBlockingInAsync
from .clock_discipline import ClockDiscipline
from .env_knobs import EnvKnobRegistry
from .guarded_by import GuardedBy
from .taxonomy_rule import TaxonomyRegistry
from .wire_bounds import WireDecoderBounds

ALL_RULES = (
    NoBlockingInAsync(),
    WireDecoderBounds(),
    TaxonomyRegistry(),
    EnvKnobRegistry(),
    GuardedBy(),
    ClockDiscipline(),
)

__all__ = [
    "ALL_RULES",
    "NoBlockingInAsync",
    "WireDecoderBounds",
    "TaxonomyRegistry",
    "EnvKnobRegistry",
    "GuardedBy",
    "ClockDiscipline",
]
