"""taxonomy-registry: edge and stage names come from one table.

``benchmark/traces.py`` routes journal records by edge name and span
records by stage name.  Before this rule the contract was implicit: a
misspelled ``journal.record("recv.propse", ...)`` produced a valid
JSONL stream and a silently-empty Perfetto track.  Now every literal
edge passed to a journal ``record()`` call and every literal stage
passed to ``span()`` / a recorder ``add()`` must be registered in
``hotstuff_tpu/telemetry/taxonomy.py`` — the same module traces.py
renders from — and dynamic (f-string) edges must start with a
registered prefix (``fault.``, ``byz.``).

The registry is loaded from **source text** of the tree under analysis
(never imported), so the rule works in a bare CI venv and on fixture
trees.
"""

from __future__ import annotations

import ast
import os

from ..framework import Finding, terminal_name

RULE = "taxonomy-registry"

TAXONOMY_REL = "hotstuff_tpu/telemetry/taxonomy.py"

#: receiver names that identify a journal handle at a record() call
_JOURNAL_RECEIVERS = {"journal", "_journal", "j"}

#: receiver names that identify a span recorder at an add() call
_RECORDER_RECEIVERS = {"rec", "recorder"}


_REGISTRY_CACHE: dict = {}


def load_registry(root: str):
    """(edges frozenset, prefixes tuple, stages frozenset) parsed from
    the tree's taxonomy module — literal-evaluated, not imported."""
    cached = _REGISTRY_CACHE.get(root)
    if cached is not None:
        return cached
    path = os.path.join(root, *TAXONOMY_REL.split("/"))
    if not os.path.exists(path):
        # fixture trees carry no registry: fall back to the one shipped
        # next to this rule (the real repo's)
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            "telemetry",
            "taxonomy.py",
        )
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    consts: dict = {}
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                targets = [node.target.id]
            value = node.value
        else:
            continue
        for name in targets:
            try:
                consts[name] = _eval(value, consts)
            except ValueError:
                pass
    edges = frozenset(consts.get("JOURNAL_EDGES", ()))
    prefixes = tuple(consts.get("JOURNAL_EDGE_PREFIXES", ()))
    stages = frozenset(consts.get("SPAN_STAGES", ()))
    if not edges or not stages:
        raise RuntimeError(f"taxonomy registry unreadable: {path}")
    _REGISTRY_CACHE[root] = (edges, prefixes, stages)
    return edges, prefixes, stages


def _eval(node, consts):
    """Literal-eval extended with name lookup, tuple concat, and the
    frozenset(...) call the taxonomy module uses."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in consts:
            return consts[node.id]
        raise ValueError(node.id)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(_eval(e, consts) for e in node.elts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return tuple(_eval(node.left, consts)) + tuple(
            _eval(node.right, consts)
        )
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "frozenset"
        and len(node.args) == 1
    ):
        return frozenset(_eval(node.args[0], consts))
    raise ValueError(ast.dump(node))


class TaxonomyRegistry:
    name = RULE
    targets = ("hotstuff_tpu/**/*.py", "benchmark/**/*.py")

    def check(self, sf, root) -> list[Finding]:
        if sf.rel == TAXONOMY_REL:
            return []
        edges, prefixes, stages = load_registry(root)
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute) or not node.args:
                continue
            first = node.args[0]
            if fn.attr == "record" and (
                terminal_name(fn.value) in _JOURNAL_RECEIVERS
            ):
                findings.extend(
                    self._check_edge(sf, node, first, edges, prefixes)
                )
            elif fn.attr == "span" or (
                fn.attr == "add"
                and terminal_name(fn.value) in _RECORDER_RECEIVERS
                and len(node.args) == 3
            ):
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    stage = first.value
                    if stage not in stages:
                        findings.append(
                            Finding(
                                RULE,
                                sf.rel,
                                node.lineno,
                                f"stage:{stage}",
                                f"span stage '{stage}' is not registered "
                                f"in {TAXONOMY_REL} (SPAN_STAGES) — "
                                f"traces.py and profile.py will drop it",
                            )
                        )
        return findings

    def _check_edge(self, sf, call, first, edges, prefixes):
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            edge = first.value
            if edge not in edges and not edge.startswith(tuple(prefixes)):
                yield Finding(
                    RULE,
                    sf.rel,
                    call.lineno,
                    f"edge:{edge}",
                    f"journal edge '{edge}' is not registered in "
                    f"{TAXONOMY_REL} (JOURNAL_EDGES) — traces.py will "
                    f"drop it as an unknown edge",
                )
        elif isinstance(first, ast.JoinedStr):
            values = first.values
            lead = (
                values[0].value
                if values
                and isinstance(values[0], ast.Constant)
                and isinstance(values[0].value, str)
                else ""
            )
            if not any(lead.startswith(p) for p in prefixes):
                yield Finding(
                    RULE,
                    sf.rel,
                    call.lineno,
                    "edge:<dynamic>",
                    f"dynamic journal edge f-string must start with a "
                    f"registered prefix {tuple(prefixes)} from "
                    f"{TAXONOMY_REL}",
                )
