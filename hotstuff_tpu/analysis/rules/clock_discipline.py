"""clock-discipline: time and randomness must flow through the seam.

The deterministic simulator (hotstuff_tpu/sim, docs/SIM.md) replays a
whole committee in virtual time by swapping the ambient clock/rng seams
in ``hotstuff_tpu.utils.clock``.  That only works if ``consensus/``,
``network/`` and ``faults/`` never reach around the seam: a direct
``time.time()`` leaks wall-clock into fault-window anchors, a direct
``asyncio.sleep()`` is pinned to whatever loop installed it instead of
the injected clock, and a module-level ``random.*`` draw consumes
global RNG state no seed controls — each one silently breaks the
"same seed ⇒ same run" contract that the explorer's repro bundles and
the shrinker depend on.

Flagged in the target trees:

- ``time.time()`` / ``time.monotonic()`` / ``time.monotonic_ns()``
  — use ``default_clock().time()`` (etc.) instead;
- ``asyncio.sleep()`` — use ``await default_clock().sleep()``;
- module-level ``random.<draw>()`` — use ``default_rng().<draw>()``;
  constructing a **seeded** generator (``random.Random(seed)``,
  ``random.SystemRandom()``) stays legal: a locally seeded stream is
  deterministic by construction and does not touch global state.

Boot/one-shot paths that genuinely want real time (process start
stamps, log rotation) carry ``# lint: allow(clock-discipline)`` with a
one-line justification.
"""

from __future__ import annotations

import ast

from ..framework import Finding, dotted_name

RULE = "clock-discipline"

#: direct wall/monotonic reads; the Clock protocol mirrors these names
_TIME_CALLS = {"time.time", "time.monotonic", "time.monotonic_ns"}

#: random.<attr> receivers that CONSTRUCT an independent generator (or
#: inspect the module) rather than draw from the shared global stream
_RNG_EXEMPT = {"Random", "SystemRandom", "getstate", "setstate", "seed"}


class ClockDiscipline:
    name = RULE
    targets = (
        "hotstuff_tpu/consensus/**/*.py",
        "hotstuff_tpu/network/**/*.py",
        "hotstuff_tpu/faults/**/*.py",
    )

    def check(self, sf, root) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted is None:
                continue
            hit = self._classify(dotted)
            if hit is not None:
                code, fix = hit
                findings.append(
                    Finding(
                        RULE,
                        sf.rel,
                        node.lineno,
                        code,
                        f"{code}() bypasses the injected clock/rng seam "
                        f"— {fix}, or justify with # lint: allow({RULE})",
                    )
                )
        return findings

    @staticmethod
    def _classify(dotted: str):
        """(stable code, suggested fix) when ``dotted`` reaches around
        the seam, else None.  Receivers other than the bare ``time`` /
        ``asyncio`` / ``random`` modules (``self._clock.time``,
        ``rng.uniform``) are exactly the seam in use — never flagged."""
        if dotted in _TIME_CALLS:
            method = dotted.split(".", 1)[1]
            return dotted, f"use default_clock().{method}()"
        if dotted == "asyncio.sleep":
            return dotted, "use await default_clock().sleep()"
        if dotted.startswith("random."):
            attr = dotted.split(".", 1)[1]
            if "." not in attr and attr not in _RNG_EXEMPT:
                return dotted, f"use default_rng().{attr}()"
        return None
