"""wire-decoder-bounds: no length/count drives a decode without a bound.

The wire layer reads attacker-controlled frames.  Every ``dec.u8()`` /
``dec.u32()`` / ``dec.u64()`` that later sizes a slice (``dec.raw(n *
SIZE)``) or a decode loop (``for _ in range(n)``) must pass an ordering
comparison (``<``, ``<=``, ``>``, ``>=`` — equality checks don't bound)
between the read and the use; and every ``dec.var_bytes()`` must pass an
explicit cap.  ``utils/codec.py`` already refuses truncated input, so
the residual bug class is the *allocation bomb*: a 4-byte count of
2**32 driving a list comprehension of signature decodes.  The fuzz
corpus (tests/test_wire_fuzz.py) catches these dynamically after the
fact; this rule makes a new unbounded tag a lint error at review time.
"""

from __future__ import annotations

import ast

from ..framework import Finding, terminal_name

RULE = "wire-decoder-bounds"

_INT_READS = {"u8", "u16", "u32", "u64"}
_ORDERING = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class WireDecoderBounds:
    name = RULE
    targets = (
        "hotstuff_tpu/consensus/wire.py",
        "hotstuff_tpu/consensus/messages.py",
    )

    def check(self, sf, root) -> list[Finding]:
        findings: list[Finding] = []
        for func in ast.walk(sf.tree):
            if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(sf, func))
        return findings

    def _check_function(self, sf, func) -> list[Finding]:
        # length vars: name -> sorted list of assignment lines
        assigns: dict[str, list[int]] = {}
        # ordering comparisons touching each name: name -> compare lines
        compares: dict[str, list[int]] = {}
        # uses: (name, line, kind)
        uses: list[tuple[str, int, str]] = []
        findings: list[Finding] = []

        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                value = node.value
                if (
                    isinstance(target, ast.Name)
                    and isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr in _INT_READS
                ):
                    assigns.setdefault(target.id, []).append(node.lineno)
            elif isinstance(node, ast.Compare):
                if any(isinstance(op, _ORDERING) for op in node.ops):
                    for name in _names_in(node):
                        compares.setdefault(name, []).append(node.lineno)
            elif isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Name) and fn.id == "range":
                    for arg in node.args:
                        for name in _names_in(arg):
                            uses.append((name, node.lineno, "range"))
                elif isinstance(fn, ast.Attribute) and fn.attr == "raw":
                    for arg in node.args:
                        for name in _names_in(arg):
                            uses.append((name, node.lineno, "raw"))
                elif isinstance(fn, ast.Attribute) and fn.attr == "var_bytes":
                    if not node.args and not node.keywords:
                        recv = terminal_name(fn.value) or "dec"
                        findings.append(
                            Finding(
                                RULE,
                                sf.rel,
                                node.lineno,
                                f"{func.name}:var_bytes",
                                f"{recv}.var_bytes() without an explicit "
                                f"cap in {func.name}() — pass the tag's "
                                f"maximum payload size",
                            )
                        )

        flagged = set()
        for name, line, kind in uses:
            assign_lines = assigns.get(name)
            if not assign_lines:
                continue  # not a decoder-read length var
            # nearest decoder read lexically preceding this use
            prior = [a for a in assign_lines if a <= line]
            if not prior:
                continue
            assign_line = max(prior)
            bounded = any(
                assign_line <= c <= line for c in compares.get(name, ())
            )
            key = (name, assign_line)
            if not bounded and key not in flagged:
                flagged.add(key)
                what = (
                    "a decode loop" if kind == "range" else "a payload slice"
                )
                findings.append(
                    Finding(
                        RULE,
                        sf.rel,
                        line,
                        f"{func.name}:{name}",
                        f"wire-read count '{name}' (line {assign_line}) "
                        f"drives {what} in {func.name}() without an "
                        f"ordering bound check between read and use",
                    )
                )
        return findings
