"""no-blocking-in-async: the event loop must never block.

The consensus actors (pacemaker, proposer, synchronizer, receivers) are
one asyncio loop per node; a single synchronous ``time.sleep``,
``Future.result()``, ``block_until_ready`` or direct store/socket call
inside an ``async def`` stalls every timer and every in-flight round on
that node.  That is not a perf bug: the Byzantine plane's trusted-subset
verdicts (PR 8/11) assume honest nodes are *timely*, so a blocked loop
is indistinguishable from a withholding attacker.

Scope is **lexical**: code inside nested ``def``/``lambda`` bodies is
excluded (it runs on whatever schedule the nested callable gets, which
the guarded-by rule handles when it's a dispatch-loop thread).

Legitimate sites — ``t.result()`` on a task that ``asyncio.wait`` just
returned as done — carry ``# lint: allow(no-blocking-in-async)`` with a
one-line justification.
"""

from __future__ import annotations

import ast

from ..framework import Finding, dotted_name, terminal_name, walk_no_nested_functions

RULE = "no-blocking-in-async"

#: method names that block when invoked on a store engine (receiver
#: name containing "engine"): the sync Engine protocol of store/
_ENGINE_BLOCKING = {"put", "get", "delete", "keys", "compact"}

#: blocking socket methods (receiver name containing "sock")
_SOCKET_BLOCKING = {"recv", "recv_into", "accept", "connect", "listen", "sendall"}

#: module-level blocking calls, by dotted name
_BLOCKING_DOTTED = {
    "time.sleep",
    "os.system",
    "subprocess.run",
    "subprocess.check_output",
    "subprocess.check_call",
    "subprocess.call",
}


class NoBlockingInAsync:
    name = RULE
    targets = (
        "hotstuff_tpu/consensus/**/*.py",
        "hotstuff_tpu/network/**/*.py",
        "hotstuff_tpu/node/**/*.py",
    )

    def check(self, sf, root) -> list[Finding]:
        findings: list[Finding] = []
        for func in ast.walk(sf.tree):
            if not isinstance(func, ast.AsyncFunctionDef):
                continue
            for node in walk_no_nested_functions(func):
                if isinstance(node, ast.Call):
                    hit = self._classify(node)
                    if hit is not None:
                        code, what = hit
                        findings.append(
                            Finding(
                                RULE,
                                sf.rel,
                                node.lineno,
                                code,
                                f"{what} blocks the event loop inside "
                                f"async def {func.name}() — await it, move "
                                f"it to an executor, or justify with "
                                f"# lint: allow({RULE})",
                            )
                        )
        return findings

    def _classify(self, call: ast.Call):
        """(stable code, human label) when ``call`` blocks, else None."""
        func = call.func
        dotted = dotted_name(func)
        if dotted in _BLOCKING_DOTTED:
            return dotted, f"{dotted}()"
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = dotted_name(func.value) or terminal_name(func.value) or "<expr>"
        if attr == "result" and not call.args and not call.keywords:
            return f"{recv}.result", f"{recv}.result()"
        if attr == "block_until_ready":
            return f"{recv}.block_until_ready", f"{recv}.block_until_ready()"
        low = recv.lower()
        if attr in _ENGINE_BLOCKING and "engine" in low:
            return f"{recv}.{attr}", f"synchronous store call {recv}.{attr}()"
        if attr in _SOCKET_BLOCKING and "sock" in low:
            return f"{recv}.{attr}", f"blocking socket call {recv}.{attr}()"
        return None
