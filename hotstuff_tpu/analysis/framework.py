"""Rule framework: source loading, inline allows, allowlist, runner.

Design choices that keep the plane dependable:

- **Stable finding keys.**  A finding's identity is ``rule:path:code``
  with NO line number — allowlist entries survive unrelated edits to
  the file.  ``code`` is a rule-chosen short token (e.g. the blocked
  call, ``Class.field``, ``function:var``).
- **Comments via tokenize.**  ``ast`` drops comments, but both escape
  hatches (``# lint: allow(rule)``) and the ``# guarded-by: <lock>``
  annotations live in comments, so every :class:`SourceFile` carries a
  ``{line: comment}`` map extracted with :mod:`tokenize`.
- **No package imports at lint time.**  The framework never imports
  the code under analysis — everything is read from source text, so
  the gate runs in a bare venv (CI lint job) where jax is absent.
"""

from __future__ import annotations

import ast
import glob
import io
import os
import re
import tokenize
from dataclasses import dataclass

#: ``# lint: allow(rule-a, rule-b)  -- optional justification``
_ALLOW_RE = re.compile(r"lint:\s*allow\(\s*([a-z0-9_\-, ]+?)\s*\)")

#: ``# guarded-by: <token>  -- optional justification``
GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    code: str  # short stable token; line numbers never appear here
    message: str

    @property
    def key(self) -> str:
        """The allowlist identity: stable across unrelated edits."""
        return f"{self.rule}:{self.path}:{self.code}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed source file: text, AST, and the comment/allow maps."""

    def __init__(self, abspath: str, root: str):
        self.abspath = abspath
        self.rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self._lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        #: line -> raw comment text (including the leading ``#``)
        self.comments: dict[int, str] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            pass  # partial comment map beats no lint at all
        #: line -> frozenset of rule names allowed on that line
        self.allow: dict[int, frozenset] = {}
        for line, comment in self.comments.items():
            m = _ALLOW_RE.search(comment)
            if m:
                self.allow[line] = frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )

    def allows(self, rule: str, line: int) -> bool:
        """Is ``rule`` allowed at ``line``?  The allow marker may sit on
        the flagged line itself or anywhere in the contiguous comment
        block directly above it (so justifications can span lines)."""
        if rule in self.allow.get(line, ()):
            return True
        ln = line - 1
        while ln in self.comments:
            if rule in self.allow.get(ln, ()):
                return True
            if not self._is_comment_line(ln):
                break  # a trailing comment on code ends the block
            ln -= 1
        return False

    def _is_comment_line(self, line: int) -> bool:
        stripped = self._lines[line - 1].lstrip() if (
            0 < line <= len(self._lines)
        ) else ""
        return stripped.startswith("#")

    def guarded_by(self, line: int) -> str | None:
        """The ``# guarded-by:`` token at ``line``, or anywhere in the
        contiguous comment block directly above it."""
        comment = self.comments.get(line)
        if comment:
            m = GUARDED_BY_RE.search(comment)
            if m:
                return m.group(1)
        ln = line - 1
        while ln in self.comments:
            m = GUARDED_BY_RE.search(self.comments[ln])
            if m:
                return m.group(1)
            if not self._is_comment_line(ln):
                break
            ln -= 1
        return None


def iter_sources(root: str, patterns) -> list[SourceFile]:
    """Parsed sources under ``root`` matching any glob in ``patterns``
    (repo-relative, ``**`` supported), deduped, stable order."""
    paths: dict = {}
    for pattern in patterns:
        for path in glob.glob(os.path.join(root, pattern), recursive=True):
            if path.endswith(".py") and os.path.isfile(path):
                paths[os.path.abspath(path)] = True
    out = []
    for path in sorted(paths):
        try:
            out.append(SourceFile(path, root))
        except (SyntaxError, UnicodeDecodeError):
            # unparseable target files are their own finding, raised by
            # the runner below rather than silently skipped
            out.append(path)
    return out


def run_rules(rules, root: str) -> list[Finding]:
    """Run every rule over its targets; inline ``# lint: allow`` already
    applied.  Allowlist filtering is the caller's second stage."""
    findings: list[Finding] = []
    cache: dict[str, list] = {}
    for rule in rules:
        key = "\0".join(rule.targets)
        sources = cache.get(key)
        if sources is None:
            sources = cache[key] = iter_sources(root, rule.targets)
        for sf in sources:
            if isinstance(sf, str):  # failed to parse
                findings.append(
                    Finding(
                        rule.name,
                        os.path.relpath(sf, root).replace(os.sep, "/"),
                        1,
                        "syntax-error",
                        "target file does not parse",
                    )
                )
                continue
            for f in rule.check(sf, root):
                if not sf.allows(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.code))
    return findings


def load_allowlist(path: str) -> set:
    """Committed grandfather list: one ``rule:path:code`` key per line;
    blank lines and ``#`` comments ignored."""
    keys = set()
    if not os.path.exists(path):
        return keys
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                keys.add(line)
    return keys


def apply_allowlist(findings, allow_keys):
    """(kept findings, used keys, stale keys) — stale entries are
    surfaced so the list cannot silently rot."""
    kept, used = [], set()
    for f in findings:
        if f.key in allow_keys:
            used.add(f.key)
        else:
            kept.append(f)
    return kept, used, set(allow_keys) - used


def repo_root() -> str:
    """The repo checkout this package was loaded from."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


# ---- shared AST helpers ----------------------------------------------------


def dotted_name(node) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node) -> str | None:
    """The last segment of a Name/Attribute receiver (``self._journal``
    -> ``_journal``), else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_no_nested_functions(node):
    """Yield ``node``'s descendants without descending into nested
    function/lambda bodies (their code runs on a different schedule)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(child))
