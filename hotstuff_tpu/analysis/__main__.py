"""CLI: ``python -m hotstuff_tpu.analysis {check,gen-knobs}``."""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import knobgen
from .framework import apply_allowlist, load_allowlist, repo_root, run_rules
from .rules import ALL_RULES

ALLOWLIST_REL = os.path.join("hotstuff_tpu", "analysis", "allowlist.txt")


def cmd_check(args) -> int:
    root = os.path.abspath(args.root)
    allowlist_path = args.allowlist or os.path.join(root, ALLOWLIST_REL)
    findings = run_rules(ALL_RULES, root)
    allow_keys = load_allowlist(allowlist_path)
    kept, used, stale = apply_allowlist(findings, allow_keys)
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [
                        {
                            "rule": f.rule,
                            "path": f.path,
                            "line": f.line,
                            "code": f.code,
                            "key": f.key,
                            "message": f.message,
                        }
                        for f in kept
                    ],
                    "allowlisted": sorted(used),
                    "stale_allowlist": sorted(stale),
                },
                indent=2,
            )
        )
    else:
        for f in kept:
            print(f.render())
        if used:
            print(f"({len(used)} finding(s) suppressed by allowlist)")
        for key in sorted(stale):
            print(f"warning: stale allowlist entry (no such finding): {key}")
        if kept:
            print(f"FAIL: {len(kept)} finding(s)")
        else:
            print("OK: no findings")
    return 1 if kept else 0


def cmd_gen_knobs(args) -> int:
    root = os.path.abspath(args.root)
    if args.check:
        if knobgen.is_fresh(root):
            print(f"OK: {knobgen.KNOBS_REL} is fresh")
            return 0
        print(
            f"STALE: {knobgen.KNOBS_REL} does not match the tree — "
            f"run: python -m hotstuff_tpu.analysis gen-knobs"
        )
        return 1
    path = knobgen.write(root)
    print(f"wrote {path}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hotstuff_tpu.analysis",
        description="Consensus-aware static analysis plane",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_check = sub.add_parser("check", help="run every lint rule")
    p_check.add_argument("--root", default=repo_root())
    p_check.add_argument("--allowlist", default=None)
    p_check.add_argument("--json", action="store_true")
    p_check.set_defaults(fn=cmd_check)

    p_knobs = sub.add_parser(
        "gen-knobs", help="regenerate (or --check) docs/KNOBS.md"
    )
    p_knobs.add_argument("--root", default=repo_root())
    p_knobs.add_argument("--check", action="store_true")
    p_knobs.set_defaults(fn=cmd_gen_knobs)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
