"""hotstuff_tpu.analysis — the consensus-aware static analysis plane.

A custom AST lint framework (stdlib ``ast`` + ``tokenize``, zero
third-party deps) whose rules encode this codebase's load-bearing
conventions instead of generic style:

- **no-blocking-in-async** — no ``time.sleep`` / ``Future.result()`` /
  ``block_until_ready`` / synchronous store or socket calls lexically
  inside ``async def`` bodies (``consensus/``, ``network/``, ``node/``):
  a blocking call on the event loop stalls the pacemaker and breaks the
  honest-node timeliness assumption of the trusted-subset regime.
- **wire-decoder-bounds** — every length/count a wire decoder reads must
  pass an ordering comparison before it drives a slice or a decode loop
  (``consensus/wire.py``, ``consensus/messages.py``), so a new frame tag
  cannot ship the allocation-bomb bug class the fuzz corpus only catches
  after the fact.
- **taxonomy-registry** — journal edge names and verify-pipeline span
  stage names must come from ``telemetry/taxonomy.py`` (which
  ``benchmark/traces.py`` also renders from): an unregistered edge is a
  lint error, not a silently-empty Perfetto track.
- **env-knob-registry** — every ``HOTSTUFF_*`` knob the code reads must
  appear in the generated ``docs/KNOBS.md`` (kept fresh by this rule).
- **guarded-by** — fields touched from both a dispatch-loop thread and
  the asyncio loop must carry a ``# guarded-by: <lock>`` annotation; a
  lockset walker checks annotated locks are actually held at writes.

Escape hatches, in preference order: fix the finding; suppress one site
with ``# lint: allow(<rule>)  -- <why>`` on (or directly above) the
flagged line; grandfather it in ``analysis/allowlist.txt`` (one
``rule:path:code`` key per line — the list is committed and expected to
stay empty or justified).

CLI::

    python -m hotstuff_tpu.analysis check [--json]
    python -m hotstuff_tpu.analysis gen-knobs [--check]

The repo gate is ``LINT=1 scripts/trace.sh`` (scripts/analysis_check.py:
all rules + KNOBS freshness + the native sanitizer smoke).
"""

from .framework import Finding, SourceFile, load_allowlist, run_rules

__all__ = ["Finding", "SourceFile", "load_allowlist", "run_rules"]
