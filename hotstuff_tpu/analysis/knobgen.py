"""HOTSTUFF_* knob inventory and the docs/KNOBS.md generator.

Fifty-plus env knobs have accumulated across eleven PRs with no
registry.  This module AST-scans ``hotstuff_tpu/`` and ``benchmark/``
for every string constant matching ``HOTSTUFF_[A-Z0-9_]+`` — direct
``os.environ`` / ``os.getenv`` reads AND literals routed through
helpers like ``_env_int("HOTSTUFF_MAX_PENDING", 512)`` — and renders
one sorted markdown table: knob, observed default(s), owning modules.

``python -m hotstuff_tpu.analysis gen-knobs`` writes the file; the
``env-knob-registry`` rule re-renders in memory and fails the gate when
the committed file is stale, so a new knob cannot merge undocumented.
"""

from __future__ import annotations

import ast
import os
import re

from .framework import iter_sources

KNOB_RE = re.compile(r"^HOTSTUFF_[A-Z0-9_]+$")

SCAN_PATTERNS = ("hotstuff_tpu/**/*.py", "benchmark/**/*.py")

KNOBS_REL = "docs/KNOBS.md"

HEADER = """\
# HOTSTUFF_* environment knobs

<!-- GENERATED FILE — do not edit by hand.
     Regenerate: python -m hotstuff_tpu.analysis gen-knobs
     Freshness is enforced by the env-knob-registry lint rule
     (LINT=1 scripts/trace.sh). -->

Every `HOTSTUFF_*` environment variable the code reads, discovered by
AST scan over `hotstuff_tpu/` and `benchmark/`.  *Default* is the
fallback expression observed at the read site (`—` when the knob is a
bare presence/truthiness check); *read by* lists every module that
consults the knob.

| Knob | Default | Read by |
|------|---------|---------|
"""


def _default_from_call(call: ast.Call, index: int) -> str | None:
    """The fallback expression when the knob literal is argument
    ``index`` of a call with a following positional argument — covers
    ``os.environ.get(K, d)``, ``os.getenv(K, d)`` and project helpers
    (``_env_int(K, d)``, ``_env_flag(K, d)``, ...)."""
    if len(call.args) > index + 1:
        return ast.unparse(call.args[index + 1])
    return None


def scan(root: str) -> dict:
    """knob -> {"defaults": [unique expr strings], "modules": [rel]}"""
    knobs: dict = {}
    for sf in iter_sources(root, SCAN_PATTERNS):
        if isinstance(sf, str):
            continue  # unparseable: the lint runner reports it
        if sf.rel.startswith("hotstuff_tpu/analysis/"):
            continue  # the scanner's own patterns are not reads
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            for i, arg in enumerate(node.args):
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and KNOB_RE.match(arg.value)
                ):
                    entry = knobs.setdefault(
                        arg.value, {"defaults": [], "modules": []}
                    )
                    if sf.rel not in entry["modules"]:
                        entry["modules"].append(sf.rel)
                    default = _default_from_call(node, i)
                    if default and default not in entry["defaults"]:
                        entry["defaults"].append(default)
        # subscript / membership reads: os.environ["K"], "K" in environ
        for node in ast.walk(sf.tree):
            key = None
            if isinstance(node, ast.Subscript) and isinstance(
                node.slice, ast.Constant
            ):
                key = node.slice.value
            elif isinstance(node, ast.Compare) and isinstance(
                node.left, ast.Constant
            ):
                key = node.left.value
            if (
                isinstance(key, str)
                and KNOB_RE.match(key)
            ):
                entry = knobs.setdefault(
                    key, {"defaults": [], "modules": []}
                )
                if sf.rel not in entry["modules"]:
                    entry["modules"].append(sf.rel)
    return knobs


def render(root: str) -> str:
    knobs = scan(root)
    lines = [HEADER]
    for knob in sorted(knobs):
        entry = knobs[knob]
        defaults = " / ".join(f"`{d}`" for d in entry["defaults"]) or "—"
        modules = ", ".join(f"`{m}`" for m in sorted(entry["modules"]))
        lines.append(f"| `{knob}` | {defaults} | {modules} |\n")
    lines.append(f"\n{len(knobs)} knobs registered.\n")
    return "".join(lines)


def write(root: str) -> str:
    path = os.path.join(root, *KNOBS_REL.split("/"))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(render(root))
    return path


def is_fresh(root: str) -> bool:
    path = os.path.join(root, *KNOBS_REL.split("/"))
    if not os.path.exists(path):
        return False
    with open(path, encoding="utf-8") as f:
        return f.read() == render(root)
