"""Store: a single-writer actor serializing all storage access.

Parity target: the reference ``store`` crate (store/src/lib.rs:15-92):
one task owns the database; clients talk to it through a channel of
Write/Read/NotifyRead commands. ``notify_read`` is the blocking-read
primitive the synchronizer's "wait for a missing parent block" is built on
(reference store/src/lib.rs:29,80-92): if the key is missing, the caller's
future is parked in an obligations map and resolved by a later write of
that key.
"""

from __future__ import annotations

import asyncio
from collections import deque

from .engine import Engine, WalEngine


def open_engine(
    path: str, prefer_native: bool = True, fsync_mode: int = 0
) -> Engine:
    """Open the best available engine at ``path`` (C++ if built, else the
    pure-Python WAL).  Both speak the same on-disk format.  fsync_mode:
    0 = flush per put, 1 = fsync per put, 2 = fsync on close."""
    if prefer_native:
        try:
            from .native import NativeEngine  # noqa: PLC0415

            return NativeEngine(path, fsync_mode)
        except (ImportError, OSError):
            pass
    return WalEngine(path, fsync_mode)


class Store:
    """Single-writer store with the reference's command semantics,
    executed INLINE on the event loop.

    The reference funnels Write/Read/NotifyRead through a channel to one
    owning task (store/src/lib.rs:27-62) because tokio tasks run on many
    threads.  Under asyncio there is exactly one thread, so the loop
    itself already provides the single-writer discipline — routing every
    operation through a queue would only add two task switches (~45 us
    each, profiled) per access on the consensus hot path.  Operations
    therefore execute synchronously in the caller's coroutine, in call
    order, which is the same total order a queue would impose.  The
    ``notify_read`` obligations map (park a future until a later write
    of that key) is preserved unchanged — it is the primitive the
    synchronizer's missing-parent wait is built on.
    """

    def __init__(self, path: str, engine: Engine | None = None):
        self.engine = engine if engine is not None else open_engine(path)
        self._obligations: dict[bytes, deque[asyncio.Future]] = {}
        self._closed = False

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError("Store is closed")

    async def write(self, key: bytes, value: bytes) -> None:
        self._check_open()
        self.engine.put(key, value)
        waiters = self._obligations.pop(key, None)
        if waiters:
            for fut in waiters:
                if not fut.done():
                    fut.set_result(value)

    async def read(self, key: bytes) -> bytes | None:
        self._check_open()
        return self.engine.get(key)

    async def delete(self, key: bytes) -> None:
        """Remove a key (no obligation wake-up — deletes never resolve a
        parked notify_read).  Used by the payload-body budget's eviction
        of uncommitted producer bodies."""
        self._check_open()
        self.engine.delete(key)

    async def notify_read(self, key: bytes) -> bytes:
        """Read that resolves when the key exists (possibly immediately)."""
        self._check_open()
        value = self.engine.get(key)
        if value is not None:
            return value
        fut = asyncio.get_running_loop().create_future()
        self._obligations.setdefault(key, deque()).append(fut)
        return await fut

    def cancel_notify(self, key: bytes) -> None:
        """Cancel and drop every future parked on ``key``.  The
        synchronizer calls this when it gives up on a missing parent:
        waiter tasks cancelled from outside leave their (cancelled)
        futures in the obligations deque, and absent a later write of
        that exact key the entry would pin memory forever."""
        waiters = self._obligations.pop(key, None)
        if waiters:
            for fut in waiters:
                if not fut.done():
                    fut.cancel()

    def close(self) -> None:
        self._closed = True
        for waiters in self._obligations.values():
            for fut in waiters:
                if not fut.done():
                    fut.cancel()
        self._obligations.clear()
        self.engine.close()


__all__ = ["Store", "Engine", "WalEngine", "open_engine"]
