"""Store: a single-writer actor serializing all storage access.

Parity target: the reference ``store`` crate (store/src/lib.rs:15-92):
one task owns the database; clients talk to it through a channel of
Write/Read/NotifyRead commands. ``notify_read`` is the blocking-read
primitive the synchronizer's "wait for a missing parent block" is built on
(reference store/src/lib.rs:29,80-92): if the key is missing, the caller's
future is parked in an obligations map and resolved by a later write of
that key.
"""

from __future__ import annotations

import asyncio
from collections import deque

from .engine import Engine, WalEngine


def open_engine(
    path: str, prefer_native: bool = True, fsync_mode: int = 0
) -> Engine:
    """Open the best available engine at ``path`` (C++ if built, else the
    pure-Python WAL).  Both speak the same on-disk format.  fsync_mode:
    0 = flush per put, 1 = fsync per put, 2 = fsync on close."""
    if prefer_native:
        try:
            from .native import NativeEngine  # noqa: PLC0415

            return NativeEngine(path, fsync_mode)
        except (ImportError, OSError):
            pass
    return WalEngine(path, fsync_mode)


class Store:
    """Asyncio actor API over an Engine.

    write() is fire-and-forget from the caller's view but fully ordered:
    all mutations and reads flow through one queue consumed by one task,
    the reference's single-writer discipline (store/src/lib.rs:27-62).
    """

    def __init__(self, path: str, engine: Engine | None = None):
        self.engine = engine if engine is not None else open_engine(path)
        self._queue: asyncio.Queue = asyncio.Queue()
        self._obligations: dict[bytes, deque[asyncio.Future]] = {}
        self._task: asyncio.Task | None = None
        self._closed = False

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            if self._closed:
                raise RuntimeError("Store is closed")
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="store"
            )

    async def _run(self) -> None:
        while True:
            cmd = await self._queue.get()
            op = cmd[0]
            if op == "write":
                _, key, value = cmd
                self.engine.put(key, value)
                waiters = self._obligations.pop(key, None)
                if waiters:
                    for fut in waiters:
                        if not fut.done():
                            fut.set_result(value)
            elif op == "read":
                _, key, fut = cmd
                if not fut.done():
                    fut.set_result(self.engine.get(key))
            else:  # notify_read
                _, key, fut = cmd
                value = self.engine.get(key)
                if value is not None:
                    if not fut.done():
                        fut.set_result(value)
                else:
                    self._obligations.setdefault(key, deque()).append(fut)

    async def write(self, key: bytes, value: bytes) -> None:
        self._ensure_started()
        await self._queue.put(("write", key, value))

    async def read(self, key: bytes) -> bytes | None:
        self._ensure_started()
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("read", key, fut))
        return await fut

    async def notify_read(self, key: bytes) -> bytes:
        """Read that resolves when the key exists (possibly immediately)."""
        self._ensure_started()
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("notify_read", key, fut))
        return await fut

    def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        # drain the queue: apply writes (they were acknowledged as ordered),
        # fail reads so no caller hangs
        while not self._queue.empty():
            cmd = self._queue.get_nowait()
            if cmd[0] == "write":
                self.engine.put(cmd[1], cmd[2])
            else:
                fut = cmd[2]
                if not fut.done():
                    fut.cancel()
        for waiters in self._obligations.values():
            for fut in waiters:
                if not fut.done():
                    fut.cancel()
        self._obligations.clear()
        self.engine.close()


__all__ = ["Store", "Engine", "WalEngine", "open_engine"]
