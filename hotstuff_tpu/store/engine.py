"""Persistent key-value engines backing the Store actor.

The reference uses RocksDB (reference store/Cargo.toml:9). RocksDB isn't in
this image, so the framework ships its own engines behind one interface:

- ``WalEngine`` (this module, pure Python): in-memory index + append-only
  write-ahead log, replayed on open. Crash recovery = reopen the same path
  (the reference's resume semantics, SURVEY.md §5 "the store IS the
  checkpoint").
- ``NativeEngine`` (native/store_engine.cpp via ctypes): the C++ engine
  with the same WAL format, used when the shared library is built.

WAL record format (little-endian): u32 klen | u32 vlen | key | value.
A record with vlen == 0xFFFFFFFF is a tombstone (delete).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Protocol

_HDR = struct.Struct("<II")
TOMBSTONE = 0xFFFFFFFF


class Engine(Protocol):
    def put(self, key: bytes, value: bytes) -> None: ...

    def get(self, key: bytes) -> bytes | None: ...

    def delete(self, key: bytes) -> None: ...

    def keys(self) -> Iterator[bytes]: ...

    def close(self) -> None: ...


class WalEngine:
    """Append-only WAL + in-memory hash index.

    ``fsync_mode``: 0 = flush to the OS page cache per put (survives
    process death — the default, matching the benchmark configuration),
    1 = fsync per put (survives OS/power loss), 2 = fsync on close only.
    On open, a log carrying more than ``COMPACT_RATIO`` x its live bytes
    (and at least ``COMPACT_MIN`` bytes) is rewritten to bound disk
    growth across restarts.
    """

    COMPACT_RATIO = 2.0
    COMPACT_MIN = 1 << 20  # 1 MiB

    def __init__(self, path: str, fsync_mode: int = 0):
        self.path = path
        self.fsync_mode = fsync_mode
        os.makedirs(path, exist_ok=True)
        self._wal_path = os.path.join(path, "wal.log")
        self._index: dict[bytes, bytes] = {}
        self._replay()
        self._maybe_compact()
        self._wal = open(self._wal_path, "ab")

    def _replay(self) -> None:
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "rb") as f:
            data = f.read()
        off, n = 0, len(data)
        valid_end = 0  # end offset of the last complete record
        while off + _HDR.size <= n:
            klen, vlen = _HDR.unpack_from(data, off)
            off += _HDR.size
            if vlen == TOMBSTONE:
                if off + klen > n:
                    break  # torn tail record — discard
                key = data[off : off + klen]
                off += klen
                self._index.pop(key, None)
            else:
                if off + klen + vlen > n:
                    break  # torn tail record — discard
                key = data[off : off + klen]
                off += klen
                self._index[key] = data[off : off + vlen]
                off += vlen
            valid_end = off
        if valid_end < n:
            # truncate the torn tail so post-recovery appends don't get
            # stranded behind unparseable garbage on the next replay
            with open(self._wal_path, "r+b") as f:
                f.truncate(valid_end)

    def _maybe_compact(self) -> None:
        try:
            size = os.path.getsize(self._wal_path)
        except OSError:
            return
        live = sum(
            _HDR.size + len(k) + len(v) for k, v in self._index.items()
        )
        if size < self.COMPACT_MIN or size <= self.COMPACT_RATIO * live:
            return
        tmp = self._wal_path + ".compact"
        with open(tmp, "wb") as f:
            for k, v in self._index.items():
                f.write(_HDR.pack(len(k), len(v)))
                f.write(k)
                f.write(v)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._wal_path)

    def _sync(self) -> None:
        self._wal.flush()
        if self.fsync_mode == 1:
            os.fsync(self._wal.fileno())

    def put(self, key: bytes, value: bytes) -> None:
        self._wal.write(_HDR.pack(len(key), len(value)))
        self._wal.write(key)
        self._wal.write(value)
        self._sync()
        self._index[key] = value

    def get(self, key: bytes) -> bytes | None:
        return self._index.get(key)

    def delete(self, key: bytes) -> None:
        self._wal.write(_HDR.pack(len(key), TOMBSTONE))
        self._wal.write(key)
        self._sync()
        self._index.pop(key, None)

    def keys(self) -> Iterator[bytes]:
        return iter(list(self._index.keys()))

    def __len__(self) -> int:
        return len(self._index)

    def close(self) -> None:
        if not self._wal.closed:
            self._wal.flush()
            if self.fsync_mode != 0:
                os.fsync(self._wal.fileno())
            self._wal.close()
