"""ctypes bridge to the native C++ WAL engine (native/store_engine.cpp).

Same on-disk WAL format as the pure-Python ``WalEngine``
(hotstuff_tpu/store/engine.py) — either implementation can recover the
other's files.  The shared library is built with ``make -C native`` (or
automatically on first import when a compiler is available); set
``HOTSTUFF_STORE_NATIVE=0`` to force the Python engine.

Durability: ``fsync_mode`` 0 = flush per put (process-crash safe),
1 = fdatasync per put (power-loss safe), 2 = fdatasync on close.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator

_LIB_NAME = "libhs_store.so"


def _native_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "native",
    )


def _load_lib() -> ctypes.CDLL:
    if os.environ.get("HOTSTUFF_STORE_NATIVE") == "0":
        raise ImportError("native engine disabled via HOTSTUFF_STORE_NATIVE=0")
    path = os.path.join(_native_dir(), "build", _LIB_NAME)
    if not os.path.exists(path):
        # one best-effort build; races are harmless (make is idempotent)
        try:
            subprocess.run(
                ["make", "-C", _native_dir()],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.SubprocessError) as e:
            raise ImportError(f"cannot build {_LIB_NAME}: {e}") from e
    lib = ctypes.CDLL(path)
    lib.hs_open.restype = ctypes.c_void_p
    lib.hs_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.hs_put.restype = ctypes.c_int
    lib.hs_put.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.c_char_p,
        ctypes.c_uint32,
    ]
    lib.hs_get.restype = ctypes.c_int
    lib.hs_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.hs_delete.restype = ctypes.c_int
    lib.hs_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.hs_keys_blob.restype = ctypes.c_int
    lib.hs_keys_blob.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.hs_count.restype = ctypes.c_uint64
    lib.hs_count.argtypes = [ctypes.c_void_p]
    lib.hs_wal_bytes.restype = ctypes.c_uint64
    lib.hs_wal_bytes.argtypes = [ctypes.c_void_p]
    lib.hs_compact.restype = ctypes.c_int
    lib.hs_compact.argtypes = [ctypes.c_void_p]
    lib.hs_free.restype = None
    lib.hs_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.hs_close.restype = None
    lib.hs_close.argtypes = [ctypes.c_void_p]
    return lib


_lib: ctypes.CDLL | None = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


class NativeEngine:
    """Engine-protocol adapter over the C++ WAL engine."""

    def __init__(self, path: str, fsync_mode: int = 0):
        self._lib = _get_lib()
        self._h = self._lib.hs_open(path.encode(), fsync_mode)
        if not self._h:
            raise OSError(f"hs_open failed for {path!r}")
        self.path = path

    def put(self, key: bytes, value: bytes) -> None:
        if self._lib.hs_put(self._h, key, len(key), value, len(value)) != 0:
            raise OSError("hs_put failed")

    def get(self, key: bytes) -> bytes | None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        outlen = ctypes.c_uint32()
        rc = self._lib.hs_get(
            self._h, key, len(key), ctypes.byref(out), ctypes.byref(outlen)
        )
        if rc == -1:
            return None
        if rc != 0:
            raise OSError("hs_get failed")
        try:
            return ctypes.string_at(out, outlen.value)
        finally:
            self._lib.hs_free(out)

    def delete(self, key: bytes) -> None:
        if self._lib.hs_delete(self._h, key, len(key)) != 0:
            raise OSError("hs_delete failed")

    def keys(self) -> Iterator[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        outlen = ctypes.c_uint64()
        if self._lib.hs_keys_blob(self._h, ctypes.byref(out), ctypes.byref(outlen)):
            raise OSError("hs_keys_blob failed")
        try:
            blob = ctypes.string_at(out, outlen.value)
        finally:
            self._lib.hs_free(out)
        (count,) = __import__("struct").unpack_from("<I", blob, 0)
        off = 4
        result = []
        for _ in range(count):
            (klen,) = __import__("struct").unpack_from("<I", blob, off)
            off += 4
            result.append(blob[off : off + klen])
            off += klen
        return iter(result)

    def __len__(self) -> int:
        return int(self._lib.hs_count(self._h))

    def wal_bytes(self) -> int:
        return int(self._lib.hs_wal_bytes(self._h))

    def compact(self) -> None:
        if self._lib.hs_compact(self._h) != 0:
            raise OSError("hs_compact failed")

    def close(self) -> None:
        if self._h:
            self._lib.hs_close(self._h)
            self._h = None
