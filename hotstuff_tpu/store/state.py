"""Deterministic replicated execution layer applied at commit.

Commits used to stop at payload digests — nothing was ever *applied* —
so crash recovery and the chaos/byz planes could only check digest-log
agreement.  This module is the missing state machine: a versioned
KV/ledger deterministically derived from the committed block stream and
summarized per commit by an incremental **state root**, the strictly
stronger safety invariant the invariant layer asserts across nodes.

Determinism boundary.  Payload *bodies* are node-local: the producer
plane stores a body only on the node(s) the client submitted it to
(``--payload-homes``, default 1), while every committee member sees only
the payload *digests* carried by committed blocks.  The replicated core
therefore folds exactly the data all honest nodes share at commit time:

- per committed block: one ledger entry per payload digest
  (``s/l<digest>`` -> commit round + position), and
- the chained root ``root' = H(root || round || block_digest ||
  payload_digests...)`` — since a payload digest is the content address
  of its body, folding digests is equivalent to folding bodies.

Bodies that ARE locally present and decode as typed operations
(``encode_ops``/``decode_ops``) additionally materialize a user-KV view
(``s/u<key>``) served by the read path with read-your-writes semantics
at the ingest node; that view rides the same WAL and snapshots but is a
local materialization, not part of the root.

All keys live under the ``s/`` prefix, disjoint from every consensus
namespace (``consensus_state``, ``latest_round``, 8-byte round keys,
32-byte block digests, ``p<digest>`` payload bodies).

Value layouts (little-endian):
- meta   ``s/meta``      : u64 version | u64 last_round | root[32] |
                           u64 applied_payloads
- ledger ``s/l<digest>`` : u64 round | u32 seq
- user   ``s/u<key>``    : u64 round | u8 alive | value bytes

The ``round`` prefix on every entry is what makes delta state-sync a
pure value filter, and ``alive=0`` keeps deletions visible to both
snapshots and deltas (a bare engine delete would silently vanish from a
delta log).
"""

from __future__ import annotations

import struct

from ..crypto import Digest
from ..crypto.digest import sha512_trunc

META_KEY = b"s/meta"
LEDGER_PREFIX = b"s/l"
USER_PREFIX = b"s/u"
STATE_PREFIX = b"s/"

#: root before any block is applied (all-zero, version 0)
GENESIS_ROOT = b"\x00" * 32

#: typed-operation body framing: bodies the execution layer decodes
#: into put/del operations start with this magic after the producer
#: plane's 8-byte uniqueness counter
OP_MAGIC = b"SOP1"
OP_PUT = 0
OP_DEL = 1
#: producer bodies carry an 8-byte uniqueness counter first; typed ops
#: start right after it
OP_BODY_OFFSET = 8
MAX_OP_KEY = 256
MAX_OPS_PER_BODY = 64

#: entries per snapshot chunk frame (bounds frame size: worst-case user
#: values are producer-body sized)
SNAPSHOT_CHUNK_ENTRIES = 256

_META = struct.Struct("<QQ32sQ")
_LEDGER_VAL = struct.Struct("<QI")
_USER_HDR = struct.Struct("<QB")
_OP_HDR = struct.Struct("<BHI")


class StateError(Exception):
    pass


def encode_ops(ops) -> bytes:
    """Typed-op body payload (appended after the producer counter):
    ``OP_MAGIC`` then per op ``u8 kind | u16 klen | u32 vlen | key |
    value``.  ``ops`` is a list of ("put", key, value) / ("del", key)."""
    out = [OP_MAGIC]
    for op in ops:
        if op[0] == "put":
            _, key, value = op
            out.append(_OP_HDR.pack(OP_PUT, len(key), len(value)))
            out.append(key)
            out.append(value)
        elif op[0] == "del":
            key = op[1]
            out.append(_OP_HDR.pack(OP_DEL, len(key), 0))
            out.append(key)
        else:
            raise StateError(f"unknown op kind {op[0]!r}")
    return b"".join(out)


def decode_ops(body: bytes):
    """Decode the typed operations of a payload body, or None when the
    body is not a typed-op body (no magic — opaque payloads are legal).
    Malformed typed bodies also decode to None: commit-time apply must
    never raise on attacker-controlled payload content."""
    blob = body[OP_BODY_OFFSET:]
    if not blob.startswith(OP_MAGIC):
        return None
    ops = []
    off = len(OP_MAGIC)
    n = len(blob)
    try:
        while off < n:
            if len(ops) >= MAX_OPS_PER_BODY:
                return None
            kind, klen, vlen = _OP_HDR.unpack_from(blob, off)
            off += _OP_HDR.size
            if klen == 0 or klen > MAX_OP_KEY:
                return None
            if off + klen > n:
                return None
            key = blob[off : off + klen]
            off += klen
            if kind == OP_PUT:
                if off + vlen > n:
                    return None
                ops.append(("put", key, blob[off : off + vlen]))
                off += vlen
            elif kind == OP_DEL:
                if vlen:
                    return None
                ops.append(("del", key))
            else:
                return None
    except struct.error:
        return None
    return ops


def fold_root(root: bytes, round_: int, block_digest: bytes,
              payload_digests) -> bytes:
    """One incremental root step — shared by the apply path and the
    shadow-reporting path so a colluder's claimed root chains exactly
    like an honest one (just over the shadow digests)."""
    h = [root, round_.to_bytes(8, "little"), block_digest]
    h.extend(d if isinstance(d, bytes) else d.to_bytes()
             for d in payload_digests)
    return sha512_trunc(b"".join(h))


class SnapshotManifest:
    """The QC-anchored header of a snapshot: what version/root the
    server's state is at and how many chunks carry it.  The wire layer
    (consensus/wire.py) serializes this next to the server's high QC."""

    __slots__ = ("version", "root", "last_round", "applied_payloads",
                 "chunk_count")

    def __init__(self, version: int, root: bytes, last_round: int,
                 applied_payloads: int, chunk_count: int):
        self.version = version
        self.root = root
        self.last_round = last_round
        self.applied_payloads = applied_payloads
        self.chunk_count = chunk_count

    def __repr__(self) -> str:
        return (f"SnapshotManifest(v{self.version} @ r{self.last_round}"
                f" root={Digest(self.root)} chunks={self.chunk_count})")


class StateMachine:
    """The deterministic execution layer over one node's store engine.

    Single-writer discipline: every mutation happens inline on the event
    loop from the commit path (the same discipline the Store actor
    documents), so plain engine access needs no locking."""

    def __init__(self, store, committee_size: int = 0):
        self.store = store
        self.committee_size = committee_size
        self.version = 0
        self.root = GENESIS_ROOT
        #: what this node CLAIMS its root is — identical to ``root``
        #: except under the collude adversary's shadow committer, where
        #: it chains over the reported (shadow) digests instead
        self.reported_root = GENESIS_ROOT
        self.last_round = 0
        self.applied_payloads = 0
        self.applied_blocks = 0
        self.typed_ops = 0
        self.snapshots_served = 0
        self.synced_from_snapshot = False
        self._load_meta()

    # ---- meta cursor ----------------------------------------------------

    def _load_meta(self) -> None:
        raw = self.store.engine.get(META_KEY)
        if raw is None or len(raw) != _META.size + 32:
            return
        self.version, self.last_round, self.root, self.applied_payloads = (
            _META.unpack(raw[: _META.size])
        )
        self.reported_root = raw[_META.size :]

    def _persist_meta(self) -> None:
        self.store.engine.put(
            META_KEY,
            _META.pack(self.version, self.last_round, self.root,
                       self.applied_payloads) + self.reported_root,
        )

    # ---- apply ----------------------------------------------------------

    def apply_block(self, block, reported_digest=None) -> bytes | None:
        """Apply one committed block (called in commit order).  Returns
        the root this node REPORTS for the commit — equal to the real
        root unless ``reported_digest`` (the collude adversary's shadow
        digest) diverges, in which case the claimed root chains over the
        shadow history while the real state stays honest.  Returns None
        (nothing applied, nothing to report) for an already-applied
        round."""
        if block.round <= self.last_round:
            # crash-recovery overlap: the consensus cursor can trail the
            # state cursor by one commit (state writes land in the WAL
            # before the end-of-loop consensus_state persist)
            return None
        engine = self.store.engine
        real_digest = block.digest()
        round_ = block.round
        for seq, digest in enumerate(block.payloads):
            raw = digest.to_bytes()
            engine.put(LEDGER_PREFIX + raw, _LEDGER_VAL.pack(round_, seq))
            self.applied_payloads += 1
            body = engine.get(b"p" + raw)
            if body is not None:
                ops = decode_ops(body)
                if ops:
                    self._apply_ops(round_, ops)
        self.version += 1
        self.applied_blocks += 1
        self.last_round = round_
        self.root = fold_root(self.root, round_, real_digest.to_bytes(),
                              block.payloads)
        if reported_digest is None or reported_digest == real_digest:
            reported = real_digest.to_bytes()
        else:
            reported = reported_digest.to_bytes()
        self.reported_root = fold_root(self.reported_root, round_,
                                       reported, block.payloads)
        self._persist_meta()
        return self.reported_root

    def _apply_ops(self, round_: int, ops) -> None:
        engine = self.store.engine
        for op in ops:
            if op[0] == "put":
                _, key, value = op
                engine.put(USER_PREFIX + key,
                           _USER_HDR.pack(round_, 1) + value)
            else:
                engine.put(USER_PREFIX + op[1], _USER_HDR.pack(round_, 0))
            self.typed_ops += 1

    # ---- read path ------------------------------------------------------

    def anchor(self) -> tuple[int, bytes, int]:
        """(version, root, last_round) — the stale-read anchor a lagging
        node serves at while it catches up."""
        return self.version, self.root, self.last_round

    def read_user(self, key: bytes):
        raw = self.store.engine.get(USER_PREFIX + key)
        if raw is None or len(raw) < _USER_HDR.size:
            return None
        round_, alive = _USER_HDR.unpack_from(raw)
        if not alive:
            return None
        return round_, raw[_USER_HDR.size :]

    def read_ledger(self, digest: bytes):
        raw = self.store.engine.get(LEDGER_PREFIX + digest)
        if raw is None or len(raw) != _LEDGER_VAL.size:
            return None
        return _LEDGER_VAL.unpack(raw)  # (round, seq)

    # ---- snapshots ------------------------------------------------------

    def _entries(self, from_round: int = 0):
        """Deterministically ordered (key, value) state entries newer
        than ``from_round`` (0 = full snapshot).  Meta is excluded — the
        manifest carries the cursor."""
        engine = self.store.engine
        out = []
        for key in engine.keys():
            if not key.startswith(STATE_PREFIX) or key == META_KEY:
                continue
            value = engine.get(key)
            if value is None or len(value) < 8:
                continue
            if int.from_bytes(value[:8], "little") > from_round:
                out.append((key, value))
        out.sort()
        return out

    def manifest(self, from_round: int = 0) -> SnapshotManifest:
        entries = self._entries(from_round)
        chunks = -(-len(entries) // SNAPSHOT_CHUNK_ENTRIES) if entries else 0
        return SnapshotManifest(self.version, self.root, self.last_round,
                                self.applied_payloads, chunks)

    def chunk(self, index: int, from_round: int = 0):
        """Entries of snapshot chunk ``index`` (deterministic ordering,
        recomputed per request — snapshot serving is a recovery path,
        not a hot path)."""
        entries = self._entries(from_round)
        lo = index * SNAPSHOT_CHUNK_ENTRIES
        return entries[lo : lo + SNAPSHOT_CHUNK_ENTRIES]

    def adopt(self, manifest: SnapshotManifest, entries) -> None:
        """Install a fetched snapshot: write every entry, then jump the
        cursor to the manifest's (version, root, round).  The root is
        adopted, not recomputed — a chained root summarizes history the
        snapshot deliberately omits; trust comes from the QC anchor and
        manifest quorum the sync client verified before calling this."""
        engine = self.store.engine
        for key, value in entries:
            if not key.startswith(STATE_PREFIX) or key == META_KEY:
                raise StateError(f"snapshot entry outside state namespace: "
                                 f"{key[:16]!r}")
            engine.put(key, value)
        self.version = manifest.version
        self.root = manifest.root
        self.reported_root = manifest.root
        self.last_round = manifest.last_round
        self.applied_payloads = manifest.applied_payloads
        self.synced_from_snapshot = True
        self._persist_meta()

    # ---- telemetry ------------------------------------------------------

    def stats(self) -> dict:
        return {
            "version": self.version,
            "last_round": self.last_round,
            "root": str(Digest(self.root)),
            "applied_blocks": self.applied_blocks,
            "applied_payloads": self.applied_payloads,
            "typed_ops": self.typed_ops,
            "snapshots_served": self.snapshots_served,
            "synced_from_snapshot": self.synced_from_snapshot,
        }


__all__ = [
    "GENESIS_ROOT", "OP_MAGIC", "SNAPSHOT_CHUNK_ENTRIES",
    "SnapshotManifest", "StateError", "StateMachine",
    "decode_ops", "encode_ops", "fold_root",
]
