"""Ingest plane: per-node admission control for the producer path.

The admission controller (admission.py) sits between the network
receiver's producer channel and the proposer: it derives a credit
window from proposer buffer occupancy and recent commit throughput,
piggybacks it on producer ACK frames (consensus/wire.py ingest ACK),
and sheds overload with a typed BUSY + retry-after instead of letting
the proposer silently drop the newest payload (docs/LOAD.md).
"""

from .admission import AdmissionController, Decision

__all__ = ["AdmissionController", "Decision"]
