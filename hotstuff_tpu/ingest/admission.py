"""Admission controller: credit-window backpressure for the producer path.

The proposer's payload buffer is the system's front-door queue.  Before
this controller existed the queue had exactly one overload behavior:
silently drop the newest payload at ``MAX_PENDING``
(consensus/proposer.py) — the client kept paying for transactions that
were never going to commit and had no signal to slow down.  The
controller turns that cliff into a control loop:

- **Occupancy** comes from the proposer's live buffer (bound after the
  proposer is constructed — the receiver boots first in
  Consensus.spawn).
- **Drain rate** is a time-decayed EWMA of committed payloads, fed from
  the proposer's Cleanup messages (every commit carries the committed
  digest set).
- **admit(n)** is a pure function of (occupancy, drain rate, n): accept
  up to the high-watermark headroom, shed the rest with a typed BUSY,
  and quote a retry-after derived from how long the drain rate needs to
  clear the excess.  The credit window quoted back to the client is
  ``min(headroom, drain_rate x horizon)`` — enough inventory to keep
  the proposer busy for one credit horizon, never more than the buffer
  can hold below the watermark.

Determinism: admit() consults an injectable clock only through the
EWMA, and the decision itself depends only on the three inputs above —
the shed/accept split for a given state is exactly reproducible (the
unit tests drive it with a fake clock).

Env knobs (read once at construction, env-first like every other knob):
  HOTSTUFF_INGEST_WATERMARK   fraction of capacity where shedding
                              starts (default 0.75)
  HOTSTUFF_INGEST_HORIZON_MS  credit horizon (default 500 ms)
"""

from __future__ import annotations

import os
import time
from typing import Callable, NamedTuple

#: floor of the credit window: with no commit history yet (cold boot)
#: clients may still submit this many payloads per ACK round trip
MIN_CREDIT = 64
#: retry-after clamp (ms): never tell a client to hammer faster than
#: RETRY_MIN, never park it longer than RETRY_MAX
RETRY_MIN_MS = 10
RETRY_MAX_MS = 5_000
#: commit-rate EWMA time constant (s)
RATE_TAU_S = 2.0
#: journal sampling: one ingest.credit record per this many decisions
CREDIT_SAMPLE_EVERY = 64


class Decision(NamedTuple):
    """Outcome of one admit() call — mirrored onto the ingest ACK."""

    accepted: int
    shed: int
    credit: int
    retry_after_ms: int

    @property
    def busy(self) -> bool:
        return self.shed > 0


class AdmissionController:
    def __init__(
        self,
        capacity: int = 100_000,
        watermark: float | None = None,
        horizon_ms: float | None = None,
        time_fn: Callable[[], float] = time.monotonic,
        journal=None,
    ):
        if watermark is None:
            watermark = _env_float("HOTSTUFF_INGEST_WATERMARK", 0.75)
        if horizon_ms is None:
            horizon_ms = _env_float("HOTSTUFF_INGEST_HORIZON_MS", 500.0)
        self.capacity = max(1, capacity)
        self.watermark = min(1.0, max(0.01, watermark))
        self.horizon_s = max(0.001, horizon_ms / 1e3)
        self._time = time_fn
        self.journal = journal
        self._occupancy: Callable[[], int] | None = None
        # commit-drain EWMA (payloads/s) + its last feed time
        self.commit_rate = 0.0
        self._rate_at: float | None = None
        # counters (telemetry gauges read these; stats() snapshots them)
        self.accepted_total = 0
        self.shed_total = 0
        self.busy_frames = 0
        self.decisions = 0
        self.last_credit = 0

    def bind(
        self, occupancy_fn: Callable[[], int], capacity: int | None = None
    ) -> None:
        """Attach the proposer's live buffer once it exists (the
        receiver — and with it this controller — boots first)."""
        self._occupancy = occupancy_fn
        if capacity is not None:
            self.capacity = max(1, capacity)

    # ---- drain-rate estimation --------------------------------------------

    def on_committed(self, n: int, now: float | None = None) -> None:
        """Feed ``n`` freshly committed payloads into the drain EWMA."""
        if n <= 0:
            return
        if now is None:
            now = self._time()
        if self._rate_at is None:
            self._rate_at = now
            self.commit_rate = 0.0
            return
        dt = now - self._rate_at
        self._rate_at = now
        if dt <= 0:
            return
        inst = n / dt
        alpha = min(1.0, dt / RATE_TAU_S)
        self.commit_rate += alpha * (inst - self.commit_rate)

    # ---- the decision ------------------------------------------------------

    def admit(self, requested: int) -> Decision:
        """Admit up to the watermark headroom; shed the rest with a
        retry-after sized to the drain rate.  Pure in (occupancy,
        commit_rate, requested)."""
        occupancy = self._occupancy() if self._occupancy is not None else 0
        limit = int(self.watermark * self.capacity)
        headroom = max(0, limit - occupancy)
        accepted = min(max(0, requested), headroom)
        shed = max(0, requested) - accepted
        # credit window: one horizon of drain, floored for cold boots,
        # never past the watermark headroom left AFTER this batch
        window = max(MIN_CREDIT, int(self.commit_rate * self.horizon_s))
        credit = min(max(0, headroom - accepted), window)
        retry_after_ms = 0
        if shed:
            excess = occupancy + requested - limit
            if self.commit_rate > 0:
                retry_after_ms = int(excess / self.commit_rate * 1e3)
            else:
                retry_after_ms = RETRY_MAX_MS
            retry_after_ms = min(RETRY_MAX_MS, max(RETRY_MIN_MS, retry_after_ms))
        self.decisions += 1
        self.accepted_total += accepted
        self.shed_total += shed
        self.last_credit = credit
        if shed:
            self.busy_frames += 1
        j = self.journal
        if j is not None:
            if shed:
                j.record("ingest.shed", dur_ns=shed)
            if self.decisions % CREDIT_SAMPLE_EVERY == 1:
                j.record("ingest.credit", dur_ns=credit)
        return Decision(accepted, shed, credit, retry_after_ms)

    def stats(self) -> dict:
        """Telemetry snapshot section (pull model)."""
        occ = self._occupancy() if self._occupancy is not None else 0
        return {
            "capacity": self.capacity,
            "watermark": self.watermark,
            "occupancy": occ,
            "commit_rate": round(self.commit_rate, 1),
            "accepted_total": self.accepted_total,
            "shed_total": self.shed_total,
            "busy_frames": self.busy_frames,
            "last_credit": self.last_credit,
        }


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default
