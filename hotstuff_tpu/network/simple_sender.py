"""Best-effort sender with persistent per-peer connections.

Parity target: reference ``SimpleSender`` (network/src/simple_sender.rs:
22-143): one long-lived connection task per peer address holding a
persistent TCP connection and a bounded queue (capacity 1000); sending is
pushing onto that queue; messages are dropped on connection failure; ACK
frames arriving from the peer are read and discarded.
"""

from __future__ import annotations

import asyncio
import logging
import random

from .errors import classify
from .framing import read_frame, send_frame, set_nodelay
from .wan import LinkScheduler

log = logging.getLogger(__name__)

CHANNEL_CAPACITY = 1000

Address = tuple[str, int]


class _Connection:
    """Owns one persistent best-effort TCP connection.

    ``delay_fn`` (WAN emulation, network/wan.py): each queued message
    carries a deliver-at time; the send loop waits until then before
    writing — per-message propagation delay, pipelined (never a
    head-of-line rate limit)."""

    def __init__(self, address: Address, delay_fn=None):
        self.address = address
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=CHANNEL_CAPACITY)
        self._scheduler = (
            None if delay_fn is None else LinkScheduler(delay_fn)
        )
        self.task = asyncio.get_running_loop().create_task(
            self._run(), name=f"simple-conn-{address}"
        )

    def put_nowait(self, data: bytes) -> None:
        at = 0.0 if self._scheduler is None else self._scheduler.deliver_at()
        self.queue.put_nowait((at, data))

    async def _wait(self, at: float) -> None:
        if at:
            await LinkScheduler.wait_until(at)

    async def _run(self) -> None:
        while True:
            at, data = await self.queue.get()
            try:
                reader, writer = await asyncio.open_connection(*self.address)
            except OSError as e:
                log.warning("%s", classify(e, "connect", self.address))
                continue  # drop this message, wait for the next
            set_nodelay(writer)
            log.debug("Outgoing connection established with %s", self.address)
            sink = asyncio.get_running_loop().create_task(self._sink_acks(reader))
            try:
                while True:
                    await self._wait(at)
                    await send_frame(writer, data)
                    at, data = await self.queue.get()
            except (ConnectionError, OSError) as e:
                log.warning("%s", classify(e, "send", self.address))
            finally:
                sink.cancel()
                writer.close()

    @staticmethod
    async def _sink_acks(reader: asyncio.StreamReader) -> None:
        # Peers ACK on the same socket; this sender ignores them
        # (reference simple_sender.rs:120-131).
        try:
            while True:
                await read_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass

    def close(self) -> None:
        self.task.cancel()


class SimpleSender:
    """Fire-and-forget sends; keeps one connection per peer.

    ``link_delay``: optional WAN-emulation hook — a callable
    ``(address) -> (() -> float)`` returning the per-link delay sampler
    (None for an undelayed link)."""

    def __init__(self, link_delay=None):
        self._connections: dict[Address, _Connection] = {}
        self._link_delay = link_delay

    def _connection(self, address: Address) -> _Connection:
        conn = self._connections.get(address)
        if conn is None or conn.task.done():
            delay_fn = (
                self._link_delay(address) if self._link_delay else None
            )
            conn = _Connection(address, delay_fn=delay_fn)
            self._connections[address] = conn
        return conn

    async def send(self, address: Address, data: bytes) -> None:
        conn = self._connection(address)
        try:
            conn.put_nowait(data)
        except asyncio.QueueFull:
            log.warning("Dropping message to %s: channel full", address)

    async def broadcast(self, addresses: list[Address], data: bytes) -> None:
        for addr in addresses:
            await self.send(addr, data)

    async def lucky_broadcast(
        self, addresses: list[Address], data: bytes, nodes: int
    ) -> None:
        """Send to ``nodes`` randomly-picked peers (reference
        simple_sender.rs lucky_broadcast)."""
        picks = random.sample(addresses, min(nodes, len(addresses)))
        await self.broadcast(picks, data)

    def close(self) -> None:
        for conn in self._connections.values():
            conn.close()
        self._connections.clear()
