"""Best-effort sender with persistent per-peer connections.

Parity target: reference ``SimpleSender`` (network/src/simple_sender.rs:
22-143): one long-lived connection task per peer address holding a
persistent TCP connection and a bounded queue (capacity 1000); sending is
pushing onto that queue; messages are dropped on connection failure; ACK
frames arriving from the peer are read and discarded.
"""

from __future__ import annotations

import asyncio
import logging

from ..faults.plane import corrupt_frame
from ..utils.clock import default_clock, default_connector, default_rng
from .errors import classify
from .framing import read_frame, send_frame, set_nodelay
from .pool import BoundedPoolMixin, abort_writer
from .wan import LinkScheduler

log = logging.getLogger(__name__)

CHANNEL_CAPACITY = 1000

Address = tuple[str, int]


class _Connection:
    """Owns one persistent best-effort TCP connection.

    ``delay_fn`` (WAN emulation, network/wan.py): each queued message
    carries a deliver-at time; the send loop waits until then before
    writing — per-message propagation delay, pipelined (never a
    head-of-line rate limit).

    ``faults`` (chaos plane, faults/plane.py): the per-link fault view;
    each frame about to go out consults ``faults.decide()`` — dropped
    frames are simply not written (best-effort semantics make that
    exactly message loss), delays sleep inline, corruption flips a byte,
    duplication writes the frame twice."""

    def __init__(self, address: Address, delay_fn=None, faults=None, flows=None):
        self.address = address
        self._faults = faults
        self._flows = flows
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=CHANNEL_CAPACITY)
        self._scheduler = (
            None if delay_fn is None else LinkScheduler(delay_fn)
        )
        self._waiting = False  # parked on an empty queue (see idle)
        self._writer: asyncio.StreamWriter | None = None
        self.connect_failures = 0
        self.task = asyncio.get_running_loop().create_task(
            self._run(), name=f"simple-conn-{address}"
        )

    @property
    def idle(self) -> bool:
        """Nothing queued and nothing in flight — safe to evict without
        losing a message (best-effort semantics allow losing FUTURE
        messages on eviction; in-flight ones must still go out).  "In
        flight" includes the transport write buffer: send_frame returns
        while bytes may still sit unflushed below the high-water mark,
        and eviction aborts without flushing."""
        if not (self._waiting and self.queue.empty()):
            return False
        if self._writer is None:
            return True  # never connected: nothing can be in flight
        try:
            return self._writer.transport.get_write_buffer_size() == 0
        except (RuntimeError, AttributeError):
            return True  # transport already closed/closing

    def put_nowait(self, data: bytes) -> None:
        at = 0.0 if self._scheduler is None else self._scheduler.deliver_at()
        self.queue.put_nowait((at, data))

    async def _next(self):
        self._waiting = True
        try:
            return await self.queue.get()
        finally:
            self._waiting = False

    async def _wait(self, at: float) -> None:
        if at:
            await LinkScheduler.wait_until(at)

    async def _run(self) -> None:
        while True:
            at, data = await self._next()
            try:
                reader, writer = await default_connector()(*self.address)
            except OSError as e:
                self.connect_failures += 1
                log.warning("%s", classify(e, "connect", self.address))
                continue  # drop this message, wait for the next
            set_nodelay(writer)
            self._writer = writer
            log.debug("Outgoing connection established with %s", self.address)
            sink = asyncio.get_running_loop().create_task(self._sink_acks(reader))
            try:
                while True:
                    await self._wait(at)
                    await self._transmit(writer, data)
                    at, data = await self._next()
            except (ConnectionError, OSError) as e:
                log.warning("%s", classify(e, "send", self.address))
            finally:
                sink.cancel()
                writer.close()
                self._writer = None  # disconnected: back to retry state

    async def _transmit(self, writer: asyncio.StreamWriter, data: bytes) -> None:
        # flow accounting charges at THIS site — after the fault
        # decision — so dropped frames are never charged and duplicated
        # ones are charged twice: accounted bytes == bytes written
        if self._faults is None:
            if self._flows is not None:
                self._flows.tx(self.address, data)
            await send_frame(writer, data)
            return
        decision = self._faults.decide()
        if decision.drop:
            return
        if decision.delay_s:
            await default_clock().sleep(decision.delay_s)
        payload = corrupt_frame(data) if decision.corrupt else data
        if self._flows is not None:
            self._flows.tx(self.address, payload)
        await send_frame(writer, payload)
        if decision.duplicate:
            if self._flows is not None:
                self._flows.tx(self.address, payload)
            await send_frame(writer, payload)

    @staticmethod
    async def _sink_acks(reader: asyncio.StreamReader) -> None:
        # Peers ACK on the same socket; this sender ignores them
        # (reference simple_sender.rs:120-131).
        try:
            while True:
                await read_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass

    def close(self) -> None:
        self.task.cancel()
        abort_writer(self._writer)
        self._writer = None


class SimpleSender(BoundedPoolMixin):
    """Fire-and-forget sends; keeps one connection per peer.

    ``link_delay``: optional WAN-emulation hook — a callable
    ``(address) -> (() -> float)`` returning the per-link delay sampler
    (None for an undelayed link).

    ``fault_plane``: optional chaos plane (faults/plane.py) — each new
    connection resolves its directed-link fault view once, mirroring
    how ``link_delay`` resolves the WAN delay sampler.

    ``max_conns``: bounded connection pool (None = reference parity:
    one persistent connection per peer forever).  Big co-located
    committees need the bound — at 256 nodes every (sender, peer) pair
    persisting means a single committee-wide timeout broadcast crosses
    the process fd limit (measured: the 256-node run deterministically
    wedged at round ~19 as per-round leader/vote connections
    accumulated to 20k fds).  Eviction is LRU over IDLE connections
    only, so no queued or in-flight message is ever dropped by the
    bound."""

    #: broadcast chunks that waited for pool drain (telemetry reads
    #: this; class attr so unpaced senders pay no per-instance slot)
    pacing_stalls = 0

    def __init__(
        self,
        link_delay=None,
        max_conns: int | None = None,
        fault_plane=None,
        flows=None,
    ):
        self._connections: dict[Address, _Connection] = {}
        self._link_delay = link_delay
        self._max_conns = max_conns
        self._fault_plane = fault_plane
        self._flows = flows
        self._sweeper: asyncio.Task | None = None

    def _connection(self, address: Address) -> _Connection:
        conn = self._lru_hit(address)
        if conn is not None:
            return conn
        delay_fn = self._link_delay(address) if self._link_delay else None
        faults = (
            self._fault_plane.link(address) if self._fault_plane else None
        )
        conn = _Connection(
            address, delay_fn=delay_fn, faults=faults, flows=self._flows
        )
        self._admit(address, conn)
        return conn

    def _enqueue(self, address: Address, data: bytes) -> None:
        conn = self._connection(address)
        try:
            conn.put_nowait(data)
        except asyncio.QueueFull:
            log.warning("Dropping message to %s: channel full", address)

    async def send(self, address: Address, data: bytes) -> None:
        if self._flows is not None:
            self._flows.logical(data)
        self._enqueue(address, data)

    async def broadcast(self, addresses: list[Address], data: bytes) -> None:
        # ONE logical charge per broadcast call regardless of fan-out —
        # the wire/logical ratio per class is the amplification factor
        if self._flows is not None and addresses:
            self._flows.logical(data)
        if self._max_conns is None or len(addresses) <= self._max_conns:
            for addr in addresses:
                self._enqueue(addr, data)
            return
        # Bounded pool: pace the fan-out so the working set stays near
        # the cap — without this, a committee-wide broadcast creates
        # every connection before the loop can drain ANY of them (send
        # never yields), busting the pool in one burst.  Each chunk gets
        # its OWN drain deadline (one shared deadline let the first slow
        # chunk eat the whole budget and the rest blast out unpaced),
        # and only THIS broadcast's connections count against the cap —
        # unrelated busy peers (other traffic on a shared sender) must
        # not stall a fan-out that is itself under budget.  The wait is
        # time-bounded; delivery remains best-effort.
        loop = asyncio.get_running_loop()
        sent: list[Address] = []
        for start in range(0, len(addresses), self._max_conns):
            chunk = addresses[start : start + self._max_conns]
            for addr in chunk:
                self._enqueue(addr, data)
            sent.extend(chunk)
            deadline = loop.time() + 2.0
            stalled = False
            while (
                sum(
                    1
                    for addr in sent
                    if (c := self._connections.get(addr)) is not None
                    and not c.idle
                )
                > self._max_conns
                and loop.time() < deadline
            ):
                stalled = True
                await default_clock().sleep(0.002)
            if stalled:
                self.pacing_stalls += 1

    async def lucky_broadcast(
        self, addresses: list[Address], data: bytes, nodes: int
    ) -> None:
        """Send to ``nodes`` randomly-picked peers (reference
        simple_sender.rs lucky_broadcast)."""
        picks = default_rng().sample(addresses, min(nodes, len(addresses)))
        await self.broadcast(picks, data)

    def close(self) -> None:
        self._close_pool()
