"""Reliable sender: per-message ACK futures, reconnect with backoff,
retransmission of un-ACKed messages.

Parity target: reference ``ReliableSender`` (network/src/reliable_sender.rs:
25-248). Semantics reproduced exactly (SURVEY.md §5 requires them
bit-for-bit at the protocol level — the proposer's 2f+1-ACK back-pressure
depends on them):

- every ``send`` returns a CancelHandler (here: an asyncio Future) resolved
  with the peer's ACK payload for that message;
- each peer has one connection task pairing sent frames with ACK frames
  FIFO;
- on connection failure, un-ACKed messages are retransmitted after
  reconnecting with exponential backoff (200 ms doubling, capped at 60 s —
  reference reliable_sender.rs:131,166) with FULL JITTER: each retry
  sleeps uniform(0, delay) so the whole committee doesn't reconnect-
  stampede the instant a partition heals (the deterministic schedule
  synchronised every peer's retry clock);
- messages whose future was cancelled by the caller are dropped instead of
  retransmitted (the reference drops messages whose CancelHandler receiver
  was dropped).

Chaos-plane semantics on reliable links (faults/plane.py): the FIFO
ACK pairing constrains what each fault can mean here. A hard partition
(drop >= 1.0 window) HOLDS frames at the head of the line via
``barrier()`` — no loss decision is consumed, frames flow when the
window closes. A probabilistic drop tears the connection with a
synthetic ConnectionError instead (the frame stays un-ACKed and rides
the reconnect/retransmit path — exactly what a lost frame causes on a
reliable link). Corruption sends the mangled bytes then tears the
connection so the pairing resets and the clean frame is retransmitted.
Duplication is a no-op: a duplicated frame would draw a second ACK and
desync the FIFO pairing.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque

from ..faults.plane import BARRIER_POLL_S, corrupt_frame
from ..utils.clock import default_clock, default_connector, default_rng
from .errors import UnexpectedAckError, classify
from .framing import FramingError, read_frame, send_frame, set_nodelay
from .pool import BoundedPoolMixin, abort_writer
from .wan import LinkScheduler

log = logging.getLogger(__name__)

CHANNEL_CAPACITY = 1000
RETRY_DELAY_S = 0.2
RETRY_CAP_S = 60.0

Address = tuple[str, int]
CancelHandler = asyncio.Future  # resolves to the ACK payload (bytes)


class FaultDisconnect(ConnectionError):
    """Synthetic disconnect injected by the chaos plane: rides the
    normal reconnect/retransmit path (loss-on-a-reliable-link)."""


class _Connection:
    def __init__(self, address: Address, delay_fn=None, faults=None, flows=None):
        self.address = address
        self._faults = faults
        self._flows = flows
        #: retries whose backoff sleep was jittered (telemetry reads
        #: this: stampede-avoided reconnect attempts)
        self.jittered_retries = 0
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=CHANNEL_CAPACITY)
        # un-ACKed in-flight messages, FIFO-paired with incoming ACKs
        self.pending: deque[tuple[bytes, CancelHandler]] = deque()
        self._waiting = False  # writer_loop parked on an empty queue
        self._writer: asyncio.StreamWriter | None = None
        self.connect_failures = 0
        # WAN emulation (network/wan.py): outbound frames wait for their
        # deliver-at time; ACK futures resolve one return-leg later, so
        # the proposer's quorum-ACK back-pressure sees full RTTs.
        self._delay_fn = delay_fn
        self._scheduler = (
            None if delay_fn is None else LinkScheduler(delay_fn)
        )
        self.task = asyncio.get_running_loop().create_task(
            self._run(), name=f"reliable-conn-{address}"
        )

    def deliver_at(self) -> float:
        return 0.0 if self._scheduler is None else self._scheduler.deliver_at()

    @property
    def idle(self) -> bool:
        """Nothing queued AND every sent frame ACKed — eviction loses
        no message and cancels no caller's ACK future.

        A connection stuck in connect-retry (``_writer`` unset: never
        established, or between reconnect attempts) has no writer_loop
        to park, so ``_waiting`` never becomes True — without the first
        branch a dead peer would pin its pool slot forever, un-evictable
        while it backs off toward the 60 s retry cap."""
        if self._writer is None:
            return self.queue.empty() and not self.pending
        return self._waiting and self.queue.empty() and not self.pending

    async def _run(self) -> None:
        delay = RETRY_DELAY_S
        while True:
            try:
                reader, writer = await default_connector()(*self.address)
            except OSError as e:
                self.connect_failures += 1
                log.debug("%s", classify(e, "connect", self.address))
                # full jitter: sleep uniform(0, delay) while the CEILING
                # doubles — peers that lost the same partition at the
                # same instant spread their reconnects across the window
                # instead of stampeding the healed link in lockstep
                if delay > RETRY_DELAY_S:
                    self.jittered_retries += 1
                    await default_clock().sleep(default_rng().uniform(0, delay))
                else:
                    await default_clock().sleep(delay)
                delay = min(delay * 2, RETRY_CAP_S)
                continue
            set_nodelay(writer)
            self._writer = writer
            log.debug("Outgoing connection established with %s", self.address)
            delay = RETRY_DELAY_S  # reset on success
            try:
                await self._keep_alive(reader, writer)
            except (
                ConnectionError,
                OSError,
                asyncio.IncompleteReadError,
                FramingError,
            ) as e:
                # classify by what broke: the ACK pairing (un-ACKed
                # frames in flight -> retransmitted on reconnect) vs a
                # plain receive failure
                op = "ack" if self.pending else "receive"
                log.warning("%s", classify(e, op, self.address))
            finally:
                writer.close()
                self._writer = None  # disconnected: back to retry state

    async def _keep_alive(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # retransmit un-ACKed messages first (skip cancelled),
        # reference reliable_sender.rs:187-199; a live partition window
        # holds the retransmit burst too (head-of-line, like writer_loop)
        self.pending = deque(
            (d, f) for d, f in self.pending if not f.cancelled()
        )
        if self._faults is not None and self.pending:
            while self._faults.barrier():
                await default_clock().sleep(BARRIER_POLL_S)
        for data, _ in self.pending:
            # charged as a RETRANSMIT at the actual re-send instant —
            # never at enqueue time — so net_retx_bytes counts bytes
            # that really crossed the healed link a second time
            if self._flows is not None:
                self._flows.tx(self.address, data, retx=True)
            await send_frame(writer, data)

        async def writer_loop():
            while True:
                self._waiting = True
                try:
                    at, data, fut = await self.queue.get()
                finally:
                    self._waiting = False
                if fut.cancelled():
                    continue
                # join `pending` BEFORE any await: a connection drop
                # during the WAN wait must leave the message where the
                # reconnect path retransmits it (and close() cancels
                # its future) — never in limbo with a forever-pending
                # ACK future.  Retransmits after a reconnect skip the
                # emulated delay; the reconnect backoff (>= 200 ms)
                # already exceeds any link delay.
                self.pending.append((data, fut))
                if at:
                    await LinkScheduler.wait_until(at)
                await self._transmit(writer, data)

        def _resolve(fut, ack):
            if not fut.cancelled():
                fut.set_result(ack)

        async def reader_loop():
            while True:
                ack = await read_frame(reader)
                # each ACK pairs FIFO with exactly one sent frame; a frame
                # whose caller cancelled still consumed this ACK slot
                if self.pending:
                    _, fut = self.pending.popleft()
                    if self._delay_fn is not None:
                        # the ACK's return leg crosses the same link
                        asyncio.get_running_loop().call_later(
                            self._delay_fn(), _resolve, fut, ack
                        )
                    elif not fut.cancelled():
                        fut.set_result(ack)
                else:
                    # protocol desync the reference surfaces as
                    # UnexpectedAck (error.rs): keep the connection (the
                    # peer may just have double-ACKed) but say so
                    log.warning(
                        "%s", UnexpectedAckError(self.address, "no frame in flight")
                    )

        wtask = asyncio.ensure_future(writer_loop())
        rtask = asyncio.ensure_future(reader_loop())
        return await self._supervise(wtask, rtask)

    async def _transmit(self, writer: asyncio.StreamWriter, data: bytes) -> None:
        """Send one frame through the chaos plane (module docstring has
        the reliable-link fault semantics)."""
        faults = self._faults
        if faults is None:
            if self._flows is not None:
                self._flows.tx(self.address, data)
            await send_frame(writer, data)
            return
        while faults.barrier():
            await default_clock().sleep(BARRIER_POLL_S)
        decision = faults.decide()
        if decision.drop:
            # never written: never charged (accounted == bytes written)
            raise FaultDisconnect(f"fault plane dropped frame to {self.address}")
        if decision.delay_s:
            await default_clock().sleep(decision.delay_s)
        if decision.corrupt:
            mangled = corrupt_frame(data)
            if self._flows is not None:
                self._flows.tx(self.address, mangled)
            await send_frame(writer, mangled)
            raise FaultDisconnect(f"fault plane corrupted frame to {self.address}")
        if self._flows is not None:
            self._flows.tx(self.address, data)
        await send_frame(writer, data)

    @staticmethod
    async def _supervise(wtask: asyncio.Task, rtask: asyncio.Task) -> None:
        try:
            done, _ = await asyncio.wait(
                {wtask, rtask}, return_when=asyncio.FIRST_EXCEPTION
            )
            for t in done:
                exc = t.exception()
                if exc is not None:
                    raise exc
        finally:
            wtask.cancel()
            rtask.cancel()

    def close(self) -> None:
        self.task.cancel()
        # release the socket immediately (pool.abort_writer docstring);
        # eviction only targets fully-ACKed idle connections
        abort_writer(self._writer)
        self._writer = None
        # fail every outstanding ACK future so no caller hangs
        while not self.queue.empty():
            _, _, fut = self.queue.get_nowait()
            if not fut.done():
                fut.cancel()
        for _, fut in self.pending:
            if not fut.done():
                fut.cancel()
        self.pending.clear()


class ReliableSender(BoundedPoolMixin):
    """``max_conns``: bounded connection pool (None = reference parity).
    Only IDLE connections — empty queue, every frame ACKed — are LRU
    evicted, so reliability semantics (retransmit, ACK futures) are
    untouched; a proposer's broadcast may transiently exceed the cap
    and the pool shrinks back as ACKs drain.  Pool machinery shared
    with SimpleSender (network/pool.py)."""

    def __init__(
        self,
        link_delay=None,
        max_conns: int | None = None,
        fault_plane=None,
        flows=None,
    ):
        self._connections: dict[Address, _Connection] = {}
        self._link_delay = link_delay
        self._max_conns = max_conns
        self._fault_plane = fault_plane
        self._flows = flows
        self._sweeper: asyncio.Task | None = None

    def _connection(self, address: Address) -> _Connection:
        conn = self._lru_hit(address)
        if conn is not None:
            return conn
        delay_fn = self._link_delay(address) if self._link_delay else None
        faults = (
            self._fault_plane.link(address) if self._fault_plane else None
        )
        conn = _Connection(
            address, delay_fn=delay_fn, faults=faults, flows=self._flows
        )
        self._admit(address, conn)
        return conn

    async def _enqueue(self, address: Address, data: bytes) -> CancelHandler:
        fut: CancelHandler = asyncio.get_running_loop().create_future()
        conn = self._connection(address)
        await conn.queue.put((conn.deliver_at(), data, fut))
        return fut

    async def send(self, address: Address, data: bytes) -> CancelHandler:
        """Queue ``data`` for reliable delivery; the returned future resolves
        with the peer's ACK payload."""
        if self._flows is not None:
            self._flows.logical(data)
        return await self._enqueue(address, data)

    async def broadcast(
        self, addresses: list[Address], data: bytes
    ) -> list[CancelHandler]:
        # ONE logical charge per broadcast call regardless of fan-out —
        # the wire/logical ratio per class is the amplification factor
        if self._flows is not None and addresses:
            self._flows.logical(data)
        return [await self._enqueue(addr, data) for addr in addresses]

    async def lucky_broadcast(
        self, addresses: list[Address], data: bytes, nodes: int
    ) -> list[CancelHandler]:
        picks = default_rng().sample(addresses, min(nodes, len(addresses)))
        return await self.broadcast(picks, data)

    def close(self) -> None:
        self._close_pool()
