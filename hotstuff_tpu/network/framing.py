"""Length-delimited TCP framing.

Parity target: the reference frames every message with a 4-byte length
prefix via tokio's ``LengthDelimitedCodec`` (reference
network/src/receiver.rs:70). Same wire format here: u32 big-endian length,
then the payload.
"""

from __future__ import annotations

import asyncio
import socket
import struct

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


def set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on the stream's socket.  Consensus frames are
    kilobyte-scale and latency-bound; letting the kernel coalesce them
    costs milliseconds per protocol hop."""
    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # e.g. unix sockets in tests
            pass


class FramingError(Exception):
    pass


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise FramingError(f"frame of {length} bytes exceeds limit")
    return await reader.readexactly(length)


def write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)) + payload)


async def send_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    write_frame(writer, payload)
    await writer.drain()
