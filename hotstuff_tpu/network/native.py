"""ctypes bridge to the native C++ transport reactor (native/transport.cpp).

The reference's network layer is native (tokio TCP); this is the
framework's native equivalent — an epoll reactor thread owning every
socket, bridged into asyncio through a notify pipe: the loop registers
the pipe fd with ``add_reader`` and drains the reactor's event queue
without ever blocking.  API mirrors of the asyncio classes:

- ``NativeReceiver(host, port, handler)``  — like network.receiver.Receiver:
  every inbound frame is dispatched to ``handler.dispatch(writer, bytes)``
  where the writer replies (ACKs) on the same connection.
- ``NativeSimpleSender()`` — like network.simple_sender.SimpleSender:
  persistent best-effort per-peer connections, frames dropped while the
  peer is down, reconnect attempted on the next send; peer ACK frames
  are read and discarded.

Build with ``make -C native`` (auto-attempted on first import);
``HOTSTUFF_TRANSPORT_NATIVE=0`` forces the asyncio implementations.

When to use: the reactor offloads all socket syscalls, framing, and
reconnect bookkeeping to a dedicated OS thread, so it pays off when a
core is available for it (real deployments: one node per host).  On a
single-core host running a whole co-located committee the extra thread
per process just adds context switches — measured ~2x consensus
latency on the 1-core dev rig — so the asyncio transport stays the
default.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import random
import subprocess
from collections import deque

from ..faults.plane import BARRIER_POLL_S, corrupt_frame
from .receiver import dispatch_ingest

log = logging.getLogger(__name__)

_LIB_NAME = "libhs_transport.so"
_MAX_FRAME = 64 * 1024 * 1024

KIND_FRAME_ACCEPTED = 1
KIND_FRAME_PEER = 2
KIND_ACCEPTED_CLOSED = 3
KIND_PEER_CLOSED = 4

Address = tuple[str, int]

# Module-level probe: building/loading the shared library at import time
# makes `pytest.importorskip` (and any caller's try/except ImportError)
# behave as documented — without it the module imports fine on a host
# with no compiler and then explodes at first use.
_LIB: "ctypes.CDLL"


def _native_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "native",
    )


def _load_lib() -> ctypes.CDLL:
    if os.environ.get("HOTSTUFF_TRANSPORT_NATIVE") == "0":
        raise ImportError("native transport disabled")
    path = os.path.join(_native_dir(), "build", _LIB_NAME)
    # Run make unconditionally BEFORE the first dlopen: it is an mtime
    # no-op when the library is fresh, and it rebuilds a stale prebuilt
    # .so (e.g. one predating an added entry point) — rebuilding after
    # dlopen wouldn't help, since dlopen dedups by pathname and would
    # keep returning the old mapping.
    try:
        subprocess.run(
            ["make", "-C", _native_dir(), f"build/{_LIB_NAME}"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        if not os.path.exists(path):
            raise ImportError(f"cannot build {_LIB_NAME}: {e}") from e
        # no toolchain but a prebuilt library exists: try it
    lib = ctypes.CDLL(path)
    if not hasattr(lib, "wp_pack_vote"):
        # probe the NEWEST entry point so a stale prebuilt .so keeps
        # the documented contract (ImportError, so importorskip /
        # try-except fallbacks behave instead of AttributeError at bind)
        raise ImportError(
            f"stale {_LIB_NAME}: missing wp_pack_vote; "
            f"rebuild with `make -C native`"
        )
    lib.ht_start.restype = ctypes.c_void_p
    lib.ht_notify_fd.restype = ctypes.c_int
    lib.ht_notify_fd.argtypes = [ctypes.c_void_p]
    lib.ht_set_read_paused.restype = ctypes.c_int
    lib.ht_set_read_paused.argtypes = [
        ctypes.c_void_p,
        ctypes.c_long,
        ctypes.c_int,
    ]
    lib.ht_listen.restype = ctypes.c_long
    lib.ht_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.ht_connect.restype = ctypes.c_long
    lib.ht_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.ht_send.restype = ctypes.c_int
    lib.ht_send.argtypes = [
        ctypes.c_void_p,
        ctypes.c_long,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.ht_reply.restype = ctypes.c_int
    lib.ht_reply.argtypes = [
        ctypes.c_void_p,
        ctypes.c_long,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.ht_next.restype = ctypes.c_int
    lib.ht_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.ht_conn_listener.restype = ctypes.c_long
    lib.ht_conn_listener.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.ht_close_listener.restype = ctypes.c_int
    lib.ht_close_listener.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.ht_close_conn.restype = ctypes.c_int
    lib.ht_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.ht_counters.restype = None
    lib.ht_counters.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_ulonglong),
    ]
    lib.ht_stop.restype = None
    lib.ht_stop.argtypes = [ctypes.c_void_p]
    return lib


_LIB = _load_lib()


class Reactor:
    """One reactor thread per process, shared by every native receiver
    and sender on the running asyncio loop."""

    _instance: "Reactor | None" = None

    def __init__(self):
        self.lib = _LIB
        self.handle = self.lib.ht_start()
        if not self.handle:
            raise RuntimeError("ht_start failed")
        self.notify_fd = self.lib.ht_notify_fd(self.handle)
        self._buf = ctypes.create_string_buffer(1 << 20)  # grown on demand
        # listener id -> router callback (one per NativeReceiver; many
        # receivers share this process-wide reactor, e.g. an in-process
        # testbed runs a whole committee on it)
        self._routers: dict[int, object] = {}
        # accepted conn id -> listener id (cached ht_conn_listener)
        self._conn_listener: dict[int, int] = {}
        # outbound peer id -> handler(kind, payload) — used by the
        # reliable sender for ACK pairing; absent = ACKs discarded
        # (best-effort senders, reference simple_sender.rs:120-131)
        self._peer_handlers: dict[int, object] = {}
        self._reader_loop: asyncio.AbstractEventLoop | None = None

    @classmethod
    def shared(cls) -> "Reactor":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def counters(self) -> dict:
        """Cumulative reactor wire counters (ISSUE 19): the C++-side
        ground truth the Python flow accounting is cross-checked
        against (tests/test_flows.py loopback round-trip)."""
        out = (ctypes.c_ulonglong * 4)()
        self.lib.ht_counters(self.handle, out)
        return {
            "tx_bytes": int(out[0]),
            "tx_frames": int(out[1]),
            "rx_bytes": int(out[2]),
            "rx_frames": int(out[3]),
        }

    def ensure_reader(self) -> None:
        """Register the notify-fd reader with the RUNNING loop.  The
        reactor is a process singleton but loops come and go (each
        asyncio.run creates one), so registration is tracked per loop —
        a stale registration died with its loop."""
        loop = asyncio.get_running_loop()
        if self._reader_loop is not loop or loop.is_closed():
            loop.add_reader(self.notify_fd, self._drain)
            self._reader_loop = loop
            self._drain()  # deliver anything queued while unregistered

    def _drain(self) -> None:
        src = ctypes.c_long()
        kind = ctypes.c_int()
        while True:
            n = self.lib.ht_next(
                self.handle, ctypes.byref(src), ctypes.byref(kind),
                self._buf, len(self._buf),
            )
            if n == -1:
                return
            if n == -2:
                self._buf = ctypes.create_string_buffer(
                    min(len(self._buf) * 4, _MAX_FRAME + 4)
                )
                continue
            payload = self._buf.raw[:n]
            k = kind.value
            if k in (KIND_FRAME_ACCEPTED, KIND_ACCEPTED_CLOSED):
                conn = src.value
                lid = self._conn_listener.get(conn)
                if lid is None:
                    lid = self.lib.ht_conn_listener(self.handle, conn)
                    self._conn_listener[conn] = lid
                router = self._routers.get(lid)
                if router is not None:
                    router(conn, k, payload)
                if k == KIND_ACCEPTED_CLOSED:
                    self._conn_listener.pop(conn, None)
            elif k in (KIND_FRAME_PEER, KIND_PEER_CLOSED):
                handler = self._peer_handlers.get(src.value)
                if handler is not None:
                    handler(k, payload)

    def close(self) -> None:
        if self._reader_loop is not None and not self._reader_loop.is_closed():
            try:
                self._reader_loop.remove_reader(self.notify_fd)
            except RuntimeError:
                pass
        self._reader_loop = None
        self.lib.ht_stop(self.handle)
        self.handle = None
        Reactor._instance = None


class NativeWriter:
    """Reply channel handed to MessageHandler.dispatch."""

    def __init__(self, reactor: Reactor, conn_id: int, flows=None):
        self._reactor = reactor
        self._conn = conn_id
        self._flows = flows

    async def send(self, payload: bytes) -> None:
        rc = self._reactor.lib.ht_reply(
            self._reactor.handle, self._conn, payload, len(payload)
        )
        # replies leave on the accepted connection; a refused reply
        # (outbox full -> connection closed) never hits the wire
        if rc == 0 and self._flows is not None:
            self._flows.tx(self.peer, payload)

    @property
    def peer(self):
        return ("native", self._conn)


class NativeReceiver:
    """Native drop-in for network.receiver.Receiver.

    Frames are dispatched by ONE persistent worker task per accepted
    connection consuming an ordered queue — the same serial-per-
    connection discipline as the asyncio Receiver's runner loop (a task
    per frame would churn the loop under bursts and allow reordering).

    Flow control: the asyncio receiver gets backpressure for free (its
    reader task blocks on a full handler queue, closing the TCP
    window); here the reactor reads frames regardless, so the dispatch
    queue is watermarked — past HIGH_WATER the connection's reads are
    PAUSED in the reactor (ht_set_read_paused) and resumed below
    LOW_WATER.  Without this, an overload run (8k tx/s at 4 nodes)
    buffered everything in unbounded queues and collapsed throughput
    30x vs asyncio."""

    HIGH_WATER = 256
    LOW_WATER = 64

    def __init__(
        self, host: str, port: int, handler, fault_plane=None, flows=None
    ):
        self.host = host
        self.port = port
        self.handler = handler
        self._faults = fault_plane
        self._flows = flows
        self.reactor = Reactor.shared()
        self._listener = -1
        self._queues: dict[int, asyncio.Queue] = {}
        self._workers: dict[int, asyncio.Task] = {}
        self._paused: set[int] = set()

    async def spawn(self) -> None:
        self.reactor.ensure_reader()
        host = _resolve(self.host) if self.host != "0.0.0.0" else self.host
        self._listener = self.reactor.lib.ht_listen(
            self.reactor.handle, host.encode(), self.port
        )
        if self._listener < 0:
            from .errors import ListenError

            raise ListenError((host, self.port), "native listen failed")
        self.reactor._routers[self._listener] = self._route
        log.debug("Native listener on %s:%d", host, self.port)

    def _route(self, conn_id: int, kind: int, payload: bytes) -> None:
        if kind == KIND_ACCEPTED_CLOSED:
            q = self._queues.pop(conn_id, None)
            worker = self._workers.pop(conn_id, None)
            self._paused.discard(conn_id)
            if q is not None:
                q.put_nowait(None)  # drain sentinel; worker exits
            del worker  # cancelled implicitly by the sentinel
            return
        if kind != KIND_FRAME_ACCEPTED:
            return
        # charge receive flows at delivery from the reactor (accepted
        # conns carry no committee identity: attributed to "native")
        if self._flows is not None:
            self._flows.rx(("native", conn_id), payload)
        q = self._queues.get(conn_id)
        if q is None:
            q = asyncio.Queue()
            self._queues[conn_id] = q
            self._workers[conn_id] = asyncio.get_running_loop().create_task(
                self._worker(conn_id, q), name=f"native-conn-{conn_id}"
            )
        q.put_nowait(payload)
        if q.qsize() >= self.HIGH_WATER and conn_id not in self._paused:
            self._paused.add(conn_id)
            self.reactor.lib.ht_set_read_paused(
                self.reactor.handle, conn_id, 1
            )

    async def _worker(self, conn_id: int, q: asyncio.Queue) -> None:
        writer = NativeWriter(self.reactor, conn_id, flows=self._flows)
        while True:
            payload = await q.get()
            if payload is None:
                return
            if self._faults is not None and self._faults.inbound_cut():
                payload = b""  # isolate window: swallow the frame unACKed
            try:
                if payload:
                    await dispatch_ingest(self.handler, writer, payload)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — a handler bug must not
                # leave the connection read-paused forever (a silent,
                # reconnect-less stall): close it like the asyncio
                # Receiver does when dispatch raises, so the peer's
                # reconnect logic recovers.  The close event cleans up
                # _queues/_paused via _route.
                log.exception(
                    "handler.dispatch failed on native conn %d; closing",
                    conn_id,
                )
                if self.reactor.handle:
                    self.reactor.lib.ht_close_conn(
                        self.reactor.handle, conn_id
                    )
                return
            if (
                conn_id in self._paused
                and q.qsize() <= self.LOW_WATER
                and self.reactor.handle
            ):
                self._paused.discard(conn_id)
                self.reactor.lib.ht_set_read_paused(
                    self.reactor.handle, conn_id, 0
                )

    async def shutdown(self) -> None:
        for t in list(self._workers.values()):
            t.cancel()
        if self.reactor.handle:
            for conn_id in self._queues:
                self.reactor.lib.ht_close_conn(self.reactor.handle, conn_id)
        self._workers.clear()
        self._queues.clear()
        self.reactor._routers.pop(self._listener, None)
        if self._listener >= 0 and self.reactor.handle:
            self.reactor.lib.ht_close_listener(
                self.reactor.handle, self._listener
            )
            self._listener = -1


_RESOLVE_CACHE: dict[str, tuple[str | None, float]] = {}
_RESOLVE_NEG_TTL = 30.0  # retry failed lookups after this many seconds


def _resolve(host: str) -> str | None:
    """Host-side name resolution — the C++ reactor takes dotted quads
    only (inet_pton), while the asyncio transport resolves names.
    Returns None on failure: callers log and DROP (matching the asyncio
    senders, which catch OSError in their connection tasks — a DNS blip
    must not crash a consensus actor).  Lookups are cached — successes
    forever, failures for a short TTL — so the blocking gethostbyname
    cannot run on the event loop for every send to a dead name."""
    import ipaddress
    import socket
    import time

    if host in ("localhost",):
        return "127.0.0.1"
    try:
        ipaddress.ip_address(host)
        return host
    except ValueError:
        pass
    hit = _RESOLVE_CACHE.get(host)
    # lint: allow(clock-discipline) -- DNS-cache TTL on the native
    # transport path; the simulator only drives transport="sim"
    now = time.monotonic()
    if hit is not None and (hit[0] is not None or now < hit[1]):
        return hit[0]
    try:
        resolved = socket.gethostbyname(host)
        _RESOLVE_CACHE[host] = (resolved, 0.0)
    except OSError as e:
        log.warning("cannot resolve %s: %s", host, e)
        _RESOLVE_CACHE[host] = (None, now + _RESOLVE_NEG_TTL)
        return None
    return resolved


class NativeSimpleSender:
    """Native drop-in for network.simple_sender.SimpleSender.

    ``fault_plane`` (chaos plane, faults/plane.py): best-effort links
    support the full fault matrix — drop skips the send, delay defers
    the ``ht_send`` via ``call_later`` (later undelayed frames may
    overtake it: reordering is fair game on a lossy best-effort link),
    corrupt mangles the bytes, duplicate hands the frame over twice."""

    def __init__(self, fault_plane=None, flows=None):
        self.reactor = Reactor.shared()
        self._fault_plane = fault_plane
        self._flows = flows
        self._links: dict[Address, object] = {}
        self._peers: dict[Address, int] = {}

    def _link(self, address: Address):
        if self._fault_plane is None:
            return None
        if address not in self._links:
            self._links[address] = self._fault_plane.link(address)
        return self._links[address]

    def _peer(self, address: Address) -> int | None:
        peer = self._peers.get(address)
        if peer is None:
            host = _resolve(address[0])
            if host is None:
                return None  # unresolvable: drop (best-effort semantics)
            peer = self.reactor.lib.ht_connect(
                self.reactor.handle, host.encode(), address[1]
            )
            self._peers[address] = peer
        return peer

    async def send(self, address: Address, payload: bytes) -> None:
        if self._flows is not None:
            self._flows.logical(payload)
        await self._dispatch(address, payload)

    async def _dispatch(self, address: Address, payload: bytes) -> None:
        self.reactor.ensure_reader()
        peer = self._peer(address)
        if peer is None:
            return
        faults = self._link(address)
        if faults is not None:
            decision = faults.decide()
            if decision.drop:
                return
            if decision.corrupt:
                payload = corrupt_frame(payload)
            if decision.delay_s:
                asyncio.get_running_loop().call_later(
                    decision.delay_s, self._send_now, address, peer,
                    payload, decision.duplicate,
                )
                return
            if decision.duplicate:
                self._send_now(address, peer, payload, True)
                return
        rc = self.reactor.lib.ht_send(
            self.reactor.handle, peer, payload, len(payload)
        )
        if rc == 0 and self._flows is not None:
            self._flows.tx(address, payload)

    def _send_now(
        self, address: Address, peer: int, payload: bytes, duplicate: bool
    ) -> None:
        if not self.reactor.handle:
            return  # reactor stopped while the frame sat in its delay
        rc = self.reactor.lib.ht_send(
            self.reactor.handle, peer, payload, len(payload)
        )
        if rc == 0 and self._flows is not None:
            self._flows.tx(address, payload)
        if duplicate:
            rc = self.reactor.lib.ht_send(
                self.reactor.handle, peer, payload, len(payload)
            )
            if rc == 0 and self._flows is not None:
                self._flows.tx(address, payload)

    async def broadcast(self, addresses: list[Address], payload: bytes) -> None:
        # ONE logical charge per broadcast call regardless of fan-out
        # (wire/logical per class == amplification factor)
        if self._flows is not None and addresses:
            self._flows.logical(payload)
        for address in addresses:
            await self._dispatch(address, payload)

    async def lucky_broadcast(
        self, addresses: list[Address], payload: bytes, nodes: int
    ) -> None:
        import random

        # lint: allow(clock-discipline) -- native-transport-only helper;
        # the sim's lucky_broadcast runs the asyncio sender via the seam
        picks = random.sample(addresses, min(nodes, len(addresses)))
        if self._flows is not None and picks:
            self._flows.logical(payload)
        for address in picks:
            await self._dispatch(address, payload)

    def close(self) -> None:
        if self.reactor.handle:
            for pid in self._peers.values():
                self.reactor.lib.ht_close_conn(self.reactor.handle, pid)
        self._peers.clear()


class NativeReliableSender:
    """Native drop-in for network.reliable_sender.ReliableSender.

    Semantics (reference reliable_sender.rs:25-248): every ``send``
    returns a future resolved with the peer's ACK payload for that
    message; ACKs pair FIFO with frames the peer actually received; on
    connection failure every un-ACKed, un-cancelled message is
    retransmitted once the reactor reconnects, with exponential backoff
    (200 ms doubling, 60 s cap).  The C++ layer transmits and
    reconnects; the pairing/retransmit state machine lives here.

    Pairing correctness: per peer, ``queue`` holds (payload, future) in
    send order and ``sent`` counts its prefix that has been handed to
    the reactor on the CURRENT connection.  ACKs pop the front (the
    oldest sent frame).  A reactor-outbox-full failure leaves the frame
    unsent — and every later frame queues behind it so transmission
    order always equals queue order.  On disconnect, ``sent`` resets to
    zero: stale ACKs died with the socket, and the whole queue is
    retransmitted (at-least-once until ACKed, like the reference).

    ``fault_plane`` (chaos plane, faults/plane.py): the FIFO pairing
    allows only order-preserving faults here — a barrier (hard
    partition window) or a drawn drop defers the flush exactly like an
    outbox-full refusal (head-of-line hold, frames flow when the window
    closes / on the next attempt); delay/corrupt/duplicate are skipped
    on native reliable links.  Reconnect backoff gets the same full
    jitter as the asyncio ReliableSender (``jittered_retries``)."""

    RETRY_DELAY_S = 0.2
    RETRY_CAP_S = 60.0

    #: retries whose backoff sleep was jittered (telemetry aggregate)
    jittered_retries = 0

    def __init__(self, fault_plane=None, flows=None):
        self.reactor = Reactor.shared()
        self._fault_plane = fault_plane
        self._flows = flows
        self._links: dict[int, object] = {}  # pid -> LinkFaults | None
        self._peers: dict[Address, int] = {}
        self._addrs: dict[int, Address] = {}  # pid -> address (flow peer)
        # pid -> deque[[payload, fut, transmitted]]: the third slot
        # flips once the frame first reaches the reactor, so a
        # post-disconnect re-send is charged as a RETRANSMIT at the
        # actual re-send time (sent resets to 0 on KIND_PEER_CLOSED)
        self._queue: dict[int, deque] = {}
        self._sent: dict[int, int] = {}  # pid -> sent prefix length
        self._delay: dict[int, float] = {}
        self._retry_handle: dict[int, object] = {}
        # futures returned for unresolvable peers: never transmitted,
        # but close() must still cancel them so no caller hangs
        self._orphans: list[asyncio.Future] = []

    def _peer(self, address: Address) -> int | None:
        pid = self._peers.get(address)
        if pid is None:
            host = _resolve(address[0])
            if host is None:
                return None  # unresolvable: the future stays pending
            pid = self.reactor.lib.ht_connect(
                self.reactor.handle, host.encode(), address[1]
            )
            self._peers[address] = pid
            self._addrs[pid] = address
            self._queue[pid] = deque()
            self._sent[pid] = 0
            if self._fault_plane is not None:
                self._links[pid] = self._fault_plane.link(address)
            self.reactor._peer_handlers[pid] = (
                lambda kind, payload, pid=pid: self._on_peer_event(
                    pid, kind, payload
                )
            )
        return pid

    async def send(self, address: Address, payload: bytes) -> asyncio.Future:
        if self._flows is not None:
            self._flows.logical(payload)
        return await self._enqueue(address, payload)

    async def _enqueue(self, address: Address, payload: bytes) -> asyncio.Future:
        self.reactor.ensure_reader()
        pid = self._peer(address)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if pid is None:
            # like a peer that never comes up: the caller's quorum wait
            # proceeds on the other handles (it cancels this one); the
            # orphan list lets close() cancel it if nobody does
            self._orphans.append(fut)
            return fut
        self._queue[pid].append([payload, fut, False])
        self._flush(pid)
        return fut

    async def broadcast(
        self, addresses: list[Address], payload: bytes
    ) -> list[asyncio.Future]:
        # ONE logical charge per broadcast call regardless of fan-out
        if self._flows is not None and addresses:
            self._flows.logical(payload)
        return [await self._enqueue(a, payload) for a in addresses]

    def _flush(self, pid: int) -> None:
        """Hand unsent queue suffix to the reactor, in order, stopping
        at the first refusal (outbox full) — a short retry keeps order
        without busy-waiting."""
        q = self._queue[pid]
        faults = self._links.get(pid)
        while self._sent[pid] < len(q):
            entry = q[self._sent[pid]]
            payload, fut = entry[0], entry[1]
            if fut.cancelled():
                # still occupies a pairing slot only if already sent;
                # unsent cancelled frames can simply be dropped
                del q[self._sent[pid]]
                continue
            if faults is not None and (faults.barrier() or faults.decide().drop):
                # hold the head of the line like an outbox-full refusal:
                # order and ACK pairing survive, frames flow on retry
                if self._retry_handle.get(pid) is None:
                    self._retry_handle[pid] = (
                        asyncio.get_running_loop().call_later(
                            BARRIER_POLL_S, self._retry_flush, pid
                        )
                    )
                return
            rc = self.reactor.lib.ht_send(
                self.reactor.handle, pid, payload, len(payload)
            )
            if rc != 0:
                if self._retry_handle.get(pid) is None:
                    self._retry_handle[pid] = (
                        asyncio.get_running_loop().call_later(
                            0.05, self._retry_flush, pid
                        )
                    )
                return
            if self._flows is not None:
                # a frame handed to the reactor a second time (sent
                # reset by a disconnect) is a retransmit, charged NOW
                self._flows.tx(
                    self._addrs.get(pid, ("native", pid)),
                    payload,
                    retx=entry[2],
                )
            entry[2] = True
            self._sent[pid] += 1

    def _retry_flush(self, pid: int) -> None:
        self._retry_handle.pop(pid, None)
        if pid in self._queue:
            self._flush(pid)

    def _on_peer_event(self, pid: int, kind: int, payload: bytes) -> None:
        q = self._queue.get(pid)
        if q is None:
            return
        if kind == KIND_FRAME_PEER:
            self._delay[pid] = self.RETRY_DELAY_S  # traffic: reset backoff
            # pop the oldest SENT frame (cancelled futures still consumed
            # an ACK slot on the wire — the peer ACKed the frame)
            if self._sent[pid] > 0:
                fut = q.popleft()[1]
                self._sent[pid] -= 1
                if not fut.cancelled():
                    fut.set_result(payload)
        elif kind == KIND_PEER_CLOSED:
            # connection died: nothing is in flight any more; retransmit
            # the whole queue after a backoff (reconnect happens on the
            # next ht_send)
            self._sent[pid] = 0
            delay = self._delay.get(pid, self.RETRY_DELAY_S)
            self._delay[pid] = min(delay * 2, self.RETRY_CAP_S)
            # full jitter past the first retry (see asyncio
            # ReliableSender._run): spread post-heal reconnects
            if delay > self.RETRY_DELAY_S:
                self.jittered_retries += 1
                # lint: allow(clock-discipline) -- reconnect jitter on
                # the native reactor; never runs under the simulator
                delay = random.uniform(0, delay)
            if self._retry_handle.get(pid) is None:
                self._retry_handle[pid] = asyncio.get_running_loop().call_later(
                    delay, self._retry_flush, pid
                )

    def close(self) -> None:
        for pid in self._peers.values():
            self.reactor._peer_handlers.pop(pid, None)
            handle = self._retry_handle.pop(pid, None)
            if handle is not None:
                handle.cancel()
            if self.reactor.handle:
                self.reactor.lib.ht_close_conn(self.reactor.handle, pid)
        for q in self._queue.values():
            for entry in q:
                if not entry[1].done():
                    entry[1].cancel()  # no caller may hang on a dead sender
        for fut in self._orphans:
            if not fut.done():
                fut.cancel()
        self._orphans.clear()
        self._peers.clear()
        self._addrs.clear()
        self._queue.clear()
        self._sent.clear()


__all__ = [
    "NativeReceiver",
    "NativeReliableSender",
    "NativeSimpleSender",
    "NativeWriter",
    "Reactor",
]
