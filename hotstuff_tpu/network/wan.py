"""WAN link-delay emulation for local benchmarks.

Every reference baseline number is a 5-region AWS WAN run
(reference benchmark/settings.json:18-26), while local runs see sub-ms
RTTs — an apples-to-oranges comparison (VERDICT r3 item 3).  This module
injects per-link propagation delay + jitter at the SENDER layer so a
localhost committee experiences the reference's topology:

- a spec file maps each committee address to a region and carries a
  symmetric ONE-WAY delay matrix between regions (defaults model the
  reference's us-east-1 / eu-north-1 / ap-southeast-2 / us-west-1 /
  ap-northeast-1 spread);
- senders delay each outbound message independently (deliver-at
  scheduling, FIFO-clamped per link — pipelined like real propagation,
  never head-of-line rate-limited);
- the reliable sender also delays ACK future *resolution* by the return
  leg, so the proposer's 2f+1-ACK back-pressure sees full RTTs.

Modeling notes (honest limitations): bandwidth is not modeled (consensus
messages are KB-scale — latency-bound, not bandwidth-bound, SURVEY §2.7);
receiver-side ACK writes to SimpleSender peers are not delayed (those
ACKs are sunk unread); the benchmark client is co-located with its nodes
(the reference runs one client per instance, local.py:79-91), so
client->node links stay fast.
"""

from __future__ import annotations

import asyncio
import json

from ..utils.clock import default_clock, default_rng

Address = tuple[str, int]

# Default one-way delays (ms) between the reference's five regions,
# derived from typical inter-region RTTs (RTT/2).  Intra-region ~0.5 ms.
DEFAULT_REGIONS = (
    "us-east-1",
    "eu-north-1",
    "ap-southeast-2",
    "us-west-1",
    "ap-northeast-1",
)
DEFAULT_MATRIX = {
    ("us-east-1", "eu-north-1"): 55.0,
    ("us-east-1", "ap-southeast-2"): 100.0,
    ("us-east-1", "us-west-1"): 30.0,
    ("us-east-1", "ap-northeast-1"): 75.0,
    ("eu-north-1", "ap-southeast-2"): 140.0,
    ("eu-north-1", "us-west-1"): 80.0,
    ("eu-north-1", "ap-northeast-1"): 120.0,
    ("ap-southeast-2", "us-west-1"): 70.0,
    ("ap-southeast-2", "ap-northeast-1"): 55.0,
    ("us-west-1", "ap-northeast-1"): 50.0,
}
INTRA_REGION_MS = 0.5
DEFAULT_JITTER_PCT = 10.0


def _addr_key(address: Address) -> str:
    return f"{address[0]}:{address[1]}"


def build_spec(addresses: list[Address]) -> dict:
    """A spec assigning committee addresses round-robin over the five
    default regions (the reference runs one node per instance spread
    over its regions the same way)."""
    regions = {
        _addr_key(a): DEFAULT_REGIONS[i % len(DEFAULT_REGIONS)]
        for i, a in enumerate(addresses)
    }
    matrix = {
        f"{a}|{b}": ms for (a, b), ms in DEFAULT_MATRIX.items()
    }
    return {
        "regions": regions,
        "matrix_one_way_ms": matrix,
        "intra_region_ms": INTRA_REGION_MS,
        "jitter_pct": DEFAULT_JITTER_PCT,
    }


class WanModel:
    """Per-link one-way delay sampling from a spec."""

    def __init__(self, spec: dict, self_address: Address):
        self.regions: dict[str, str] = spec["regions"]
        self.matrix: dict[tuple[str, str], float] = {}
        for key, ms in spec["matrix_one_way_ms"].items():
            a, b = key.split("|")
            self.matrix[(a, b)] = float(ms)
            self.matrix[(b, a)] = float(ms)
        self.intra_ms = float(spec.get("intra_region_ms", INTRA_REGION_MS))
        self.jitter_pct = float(spec.get("jitter_pct", DEFAULT_JITTER_PCT))
        self.self_region = self.regions.get(_addr_key(self_address))

    @classmethod
    def load(cls, path: str, self_address: Address) -> "WanModel":
        with open(path) as f:
            return cls(json.load(f), self_address)

    def delay(self, dst: Address) -> float:
        """Sampled one-way delay (seconds) from this node to ``dst``.
        Unknown peers (not in the spec — e.g. a client) get zero."""
        dst_region = self.regions.get(_addr_key(dst))
        if self.self_region is None or dst_region is None:
            return 0.0
        base = (
            self.intra_ms
            if dst_region == self.self_region
            else self.matrix.get((self.self_region, dst_region), self.intra_ms)
        )
        jitter = default_rng().gauss(0.0, base * self.jitter_pct / 100.0)
        return max(0.0, (base + jitter) / 1e3)


class LinkScheduler:
    """Deliver-at scheduling for one link: each message is delayed
    independently (pipelined), with FIFO clamping so jitter can never
    reorder frames on the TCP stream."""

    __slots__ = ("_delay_fn", "_last_at")

    def __init__(self, delay_fn):
        self._delay_fn = delay_fn
        self._last_at = 0.0

    def deliver_at(self) -> float:
        loop = asyncio.get_running_loop()
        at = loop.time() + self._delay_fn()
        self._last_at = at = max(at, self._last_at)
        return at

    @staticmethod
    async def wait_until(at: float) -> None:
        remaining = at - asyncio.get_running_loop().time()
        if remaining > 0:
            await default_clock().sleep(remaining)


__all__ = ["WanModel", "LinkScheduler", "build_spec", "DEFAULT_REGIONS"]
