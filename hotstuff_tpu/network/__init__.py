"""Network layer: length-delimited TCP receiver and senders.

Parity map (SURVEY.md §2.3): Receiver/MessageHandler/Writer, SimpleSender
(best-effort), ReliableSender (ACK-paired with backoff retransmit) —
reference crate ``network/``.
"""

from .errors import (
    AckError,
    ConnectError,
    ListenError,
    NetworkError,
    ReceiveError,
    SendError,
)
from .framing import FramingError, read_frame, send_frame, write_frame
from .receiver import MessageHandler, Receiver, Writer
from .reliable_sender import CancelHandler, ReliableSender
from .simple_sender import SimpleSender

__all__ = [
    "NetworkError",
    "ConnectError",
    "ListenError",
    "SendError",
    "ReceiveError",
    "AckError",
    "FramingError",
    "read_frame",
    "send_frame",
    "write_frame",
    "MessageHandler",
    "Receiver",
    "Writer",
    "CancelHandler",
    "ReliableSender",
    "SimpleSender",
]
