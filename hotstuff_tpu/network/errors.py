"""Network error taxonomy.

Parity target: ``NetworkError`` (reference network/src/error.rs:6-25) —
a typed connect/listen/send/receive/ACK error family, so callers can
classify failures programmatically instead of string-matching log
lines.  The asyncio layers historically surfaced raw OSError/
ConnectionError; these wrappers carry the peer address and operation,
and ``classify`` maps any raw transport exception into the taxonomy
(used by diagnostics and tests; the hot paths keep catching the raw
tuple for speed — every wrapper here IS also an OSError subclass, so
both styles interoperate)."""

from __future__ import annotations

from .framing import FramingError

Address = tuple[str, int]


class NetworkError(OSError):
    """Base of the taxonomy (reference error.rs:6)."""

    op = "network"

    def __init__(self, address: Address | None = None, detail: str = ""):
        self.address = address
        where = f" to {address[0]}:{address[1]}" if address else ""
        super().__init__(f"failed to {self.op}{where}: {detail}")


class ConnectError(NetworkError):
    """Could not establish a connection (error.rs FailedToConnect)."""

    op = "connect"


class ListenError(NetworkError):
    """Could not bind/listen on the address (error.rs FailedToListen)."""

    op = "listen"


class SendError(NetworkError):
    """A frame could not be written (error.rs FailedToSendMessage)."""

    op = "send a message"


class ReceiveError(NetworkError):
    """A frame could not be read (error.rs FailedToReceiveMessage)."""

    op = "receive a message"


class AckError(NetworkError):
    """The ACK pairing broke (error.rs FailedToReceiveAck)."""

    op = "receive an ack"


class UnexpectedAckError(NetworkError):
    """An ACK arrived with no sent frame awaiting one (error.rs
    UnexpectedAck) — a protocol desync the reliable sender surfaces as
    a diagnostic rather than silently consuming."""

    op = "pair an unexpected ack"


def classify(
    exc: BaseException, op: str, address: Address | None = None
) -> NetworkError:
    """Wrap a raw transport exception into the taxonomy.

    ``op``: one of connect/listen/send/receive/ack."""
    cls = {
        "connect": ConnectError,
        "listen": ListenError,
        "send": SendError,
        "receive": ReceiveError,
        "ack": AckError,
    }.get(op, NetworkError)
    return cls(address, f"{type(exc).__name__}: {exc}")


__all__ = [
    "NetworkError",
    "ConnectError",
    "ListenError",
    "SendError",
    "ReceiveError",
    "AckError",
    "UnexpectedAckError",
    "FramingError",
    "classify",
]
