"""Network receiver: TCP listener dispatching frames to a handler.

Parity target: reference ``Receiver<Handler>`` (network/src/receiver.rs:
18-89): bind a TCP listener, spawn one runner per accepted connection,
decode length-delimited frames, hand each to ``handler.dispatch(writer,
bytes)``. The handler gets the connection's writer so it can send replies
or ACKs back on the same socket (the proposer's quorum-ACK back-pressure
depends on this, SURVEY.md §2.3).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Protocol

from ..crypto.async_service import ingest_note_frame, zero_copy_ingest
from .framing import FramingError, read_frame, send_frame, set_nodelay

log = logging.getLogger(__name__)

#: wire tags mirrored from consensus/wire.py — importing it here would
#: cycle (consensus imports this module for the Writer protocol);
#: tests/test_wire_fuzz.py asserts these against the live constants
_TAG_VOTE = 1
_TAG_PRODUCER_V2 = 6


async def dispatch_ingest(handler, writer, frame: bytes) -> None:
    """Frame dispatch through the zero-copy ingest taps (ISSUE 20),
    shared by the asyncio and native receivers.

    Vote frames are additionally noted to the native wave packer — the
    verify service later adopts the packed digest/pk/sig columns
    instead of flattening Python claim tuples.  Batched producer-v2
    frames parse natively into a digest column + body spans and skip
    per-item payload tuples entirely when the handler exposes
    ``dispatch_producer_v2``.  Every miss — plane disabled, native
    library unavailable, handler without the fast path, frame the
    native parser rejects — falls through to ``handler.dispatch``
    unchanged (the differential fuzz corpus pins native and Python
    accept/reject to byte parity, so only frames BOTH reject ever
    double-parse)."""
    if frame:
        tag = frame[0]
        if tag == _TAG_VOTE:
            ingest_note_frame(frame)
        elif tag == _TAG_PRODUCER_V2:
            fast = getattr(handler, "dispatch_producer_v2", None)
            if fast is not None and zero_copy_ingest() is not None:
                from ..crypto import native_ed25519

                parsed = native_ed25519.parse_producer(frame)
                if parsed is not None:
                    digests, spans = parsed
                    await fast(writer, frame, digests, spans)
                    return
    await handler.dispatch(writer, frame)


class Writer:
    """Reply-channel handed to MessageHandler.dispatch."""

    def __init__(self, stream_writer: asyncio.StreamWriter, flows=None):
        self._writer = stream_writer
        self._flows = flows

    async def send(self, payload: bytes) -> None:
        # replies (ACKs, state-read values) leave on the accepted
        # socket, not through a sender — charge their egress here
        if self._flows is not None:
            self._flows.tx(self.peer, payload)
        await send_frame(self._writer, payload)

    @property
    def peer(self):
        return self._writer.get_extra_info("peername")


class MessageHandler(Protocol):
    async def dispatch(self, writer: Writer, message: bytes) -> None: ...


class Receiver:
    """Listens on ``address`` and dispatches every frame to ``handler``.

    ``fault_plane`` (chaos plane, faults/plane.py): inbound faulting is
    all-or-nothing — accepted connections arrive from ephemeral ports,
    so frames can't be attributed to a committee peer; committee-pair
    partitions are fully enforced sender-side (every node shares the
    scenario spec).  The receiver-side cut exists for ``isolate``
    windows, where frames from planeless senders (benchmark clients)
    must die too."""

    def __init__(
        self,
        host: str,
        port: int,
        handler: MessageHandler,
        fault_plane=None,
        flows=None,
    ):
        self.host = host
        self.port = port
        self.handler = handler
        self._faults = fault_plane
        self._flows = flows
        self._server: asyncio.AbstractServer | None = None
        # insertion-ordered (dict-as-set): shutdown closes connections
        # in accept order, so teardown is reproducible — a plain set
        # iterates in id() order, which varies with heap layout
        self._writers: dict[asyncio.StreamWriter, None] = {}

    async def spawn(self) -> None:
        try:
            self._server = await asyncio.start_server(
                self._handle_connection, self.host, self.port
            )
        except OSError as e:
            from .errors import classify

            raise classify(e, "listen", (self.host, self.port)) from e
        log.debug("Listening on %s:%d", self.host, self.port)

    async def _handle_connection(
        self, reader: asyncio.StreamReader, stream_writer: asyncio.StreamWriter
    ) -> None:
        peer = stream_writer.get_extra_info("peername")
        set_nodelay(stream_writer)
        log.debug("Incoming connection from %s", peer)
        self._writers[stream_writer] = None
        writer = Writer(stream_writer, flows=self._flows)
        try:
            while True:
                frame = await read_frame(reader)
                # charged before the inbound cut: the bytes crossed the
                # wire whether or not the isolate window swallows them
                if self._flows is not None:
                    self._flows.rx(peer, frame)
                if self._faults is not None and self._faults.inbound_cut():
                    continue  # isolate window: swallow the frame unACKed
                await dispatch_ingest(self.handler, writer, frame)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            FramingError,
        ):
            log.debug("Connection from %s closed", peer)
        finally:
            self._writers.pop(stream_writer, None)
            stream_writer.close()

    @property
    def connections(self) -> int:
        """Live accepted connections (ingest_connections gauge)."""
        return len(self._writers)

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            # Persistent peers hold their connections open; close them so
            # wait_closed() (which in 3.12 waits on every live connection)
            # can complete.
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
            self._server = None
