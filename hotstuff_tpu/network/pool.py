"""Bounded per-peer connection pooling shared by the senders.

Reference parity keeps one persistent connection per (sender, peer)
pair forever (simple_sender.rs / reliable_sender.rs) — harmless at the
reference's committee sizes on separate hosts, but a co-located
committee holds BOTH endpoints of every connection in one process:
at 256 nodes the per-round leader-broadcast + vote connections grow
~1k fds/round, monotonically, into the process fd limit (measured —
docs/ROUND5.md, "The 256-node fd wall").

``BoundedPoolMixin`` gives a sender an optional ``max_conns`` bound
enforced by LRU eviction over IDLE connections only (each connection
class defines ``idle`` such that eviction can never drop a queued or
in-flight message), plus a self-terminating sweeper that shrinks
dormant burst pools (a proposer's committee-wide broadcast pool would
otherwise persist until its next leadership, ~committee-size rounds
later).  The host class supplies ``self._connections`` (an insertion-
ordered dict used as the LRU), ``self._max_conns`` and
``self._sweeper``.
"""

from __future__ import annotations

import asyncio

from ..utils.clock import default_clock


class BoundedPoolMixin:
    _connections: dict
    _max_conns: int | None
    _sweeper: asyncio.Task | None

    #: idle connections evicted under the bound (telemetry reads this;
    #: class attr so unevicting senders pay no per-instance slot)
    pool_evictions = 0

    def _lru_hit(self, address) -> object | None:
        """The live connection for ``address`` refreshed to
        most-recently-used, or None if absent/finished."""
        conn = self._connections.get(address)
        if conn is None or conn.task.done():
            return None
        del self._connections[address]
        self._connections[address] = conn
        return conn

    def _admit(self, address, conn) -> None:
        """Register a NEW connection, evicting idle LRU entries to stay
        under the bound and arming the sweeper."""
        if self._max_conns is not None:
            self._evict_idle(self._max_conns - 1)
            self._ensure_sweeper()
        self._connections[address] = conn

    def _evict_idle(self, keep: int) -> None:
        if len(self._connections) <= keep:
            return
        for addr in list(self._connections):
            if len(self._connections) <= keep:
                return
            conn = self._connections[addr]
            if conn.task.done():
                del self._connections[addr]
            elif conn.idle:
                conn.close()
                del self._connections[addr]
                self.pool_evictions += 1

    def _ensure_sweeper(self) -> None:
        """Shrink-to-cap sweeper, armed only while the pool exceeds the
        bound: it exits once back under cap (re-armed on the next
        connection creation), so a big co-located committee does not
        carry hundreds of permanently-waking tasks."""
        if self._sweeper is not None and not self._sweeper.done():
            return

        async def sweep():
            while len(self._connections) > self._max_conns:
                await default_clock().sleep(3.0)
                self._evict_idle(self._max_conns)

        self._sweeper = asyncio.get_running_loop().create_task(sweep())

    def _close_pool(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            self._sweeper = None
        for conn in self._connections.values():
            conn.close()
        self._connections.clear()


def parse_max_conns(raw: str | None) -> int | None:
    """Env-knob parsing: absent/empty/non-positive/garbage = unbounded
    (a negative value must never morph into 'broadcast to nobody')."""
    try:
        v = int(raw or 0)
    except ValueError:
        return None
    return v if v > 0 else None


def abort_writer(writer: asyncio.StreamWriter | None) -> None:
    """Release a socket NOW instead of when the cancelled owner task
    next gets scheduled — on a saturated loop that lag let closing
    sockets pile up against the fd limit.  abort() skips the flush;
    callers only use it on idle connections."""
    if writer is not None:
        try:
            writer.transport.abort()
        except (RuntimeError, AttributeError, OSError):
            pass
