"""Node-level JSON config I/O.

Parity target: reference ``node/src/config.rs:21-85`` — the ``Export``
read/write-JSON-file pattern for ``Secret`` keypair files, committee
files, and parameters files.
"""

from __future__ import annotations

import json
import os

from ..consensus import Committee, Parameters
from ..crypto import PublicKey
from ..crypto.scheme import keygen_production, read_secret


class ConfigError(Exception):
    pass


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ConfigError(f"Failed to read config file '{path}': {e}") from e


def _write_json(path: str, data: dict) -> None:
    try:
        with open(path, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError as e:
        raise ConfigError(f"Failed to write config file '{path}': {e}") from e


class Secret:
    """A node's identity: {name, secret, scheme[, pop]} as base64 JSON
    (reference node/src/config.rs:52-68; ``scheme`` is this framework's
    addition — "ed25519" default, "bls" for BLS12-381 committees).

    For BLS keys the file also records the proof of possession: it is
    public committee material (``Authority.pop``) that the operator
    pastes into the committee file next to the public key — publishing
    a BLS key without it is useless, since ``Consensus.spawn`` refuses
    PoP-less BLS committees (rogue-key defence)."""

    def __init__(
        self,
        name: PublicKey,
        secret,
        scheme: str = "ed25519",
        pop: bytes | None = None,
    ):
        self.name = name
        self.secret = secret  # SecretKey (ed25519) or OpaqueSecret (bls)
        self.scheme = scheme
        self.pop = pop

    @classmethod
    def new(cls, scheme: str = "ed25519") -> "Secret":
        name, secret = keygen_production(scheme)
        pop = None
        if scheme == "bls":
            from ..crypto.scheme import bls_pop

            pop = bls_pop(secret.to_bytes())
        return cls(name, secret, scheme, pop)

    def write(self, path: str) -> None:
        import base64

        data = {
            "name": self.name.encode_base64(),
            "secret": self.secret.encode_base64(),
            "scheme": self.scheme,
        }
        if self.pop is not None:
            data["pop"] = base64.b64encode(self.pop).decode()
        _write_json(path, data)
        os.chmod(path, 0o600)

    @classmethod
    def read(cls, path: str) -> "Secret":
        import base64

        data = _read_json(path)
        scheme = data.get("scheme", "ed25519")
        return cls(
            PublicKey.decode_base64(data["name"]),
            read_secret(scheme, data["secret"]),
            scheme,
            base64.b64decode(data["pop"]) if "pop" in data else None,
        )


def write_committee(committee: Committee, path: str) -> None:
    """Accepts a Committee or a CommitteeSchedule (epoch handoff) —
    both carry their own to_json shape."""
    _write_json(path, {"consensus": committee.to_json()})


def read_committee(path: str) -> Committee:
    """Returns a Committee, or a CommitteeSchedule when the file holds
    one (a ``schedule`` key) — callers use them interchangeably via the
    for_round seam."""
    from ..consensus.config import committee_from_json

    data = _read_json(path)
    return committee_from_json(data.get("consensus", data))


def write_parameters(parameters: Parameters, path: str) -> None:
    _write_json(path, {"consensus": parameters.to_json()})


def read_parameters(path: str) -> Parameters:
    data = _read_json(path)
    return Parameters.from_json(data.get("consensus", data))
