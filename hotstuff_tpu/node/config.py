"""Node-level JSON config I/O.

Parity target: reference ``node/src/config.rs:21-85`` — the ``Export``
read/write-JSON-file pattern for ``Secret`` keypair files, committee
files, and parameters files.
"""

from __future__ import annotations

import json
import os

from ..consensus import Committee, Parameters
from ..crypto import PublicKey, SecretKey, generate_production_keypair


class ConfigError(Exception):
    pass


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ConfigError(f"Failed to read config file '{path}': {e}") from e


def _write_json(path: str, data: dict) -> None:
    try:
        with open(path, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError as e:
        raise ConfigError(f"Failed to write config file '{path}': {e}") from e


class Secret:
    """A node's identity: {name, secret} as base64 JSON
    (reference node/src/config.rs:52-68)."""

    def __init__(self, name: PublicKey, secret: SecretKey):
        self.name = name
        self.secret = secret

    @classmethod
    def new(cls) -> "Secret":
        return cls(*generate_production_keypair())

    def write(self, path: str) -> None:
        _write_json(
            path,
            {
                "name": self.name.encode_base64(),
                "secret": self.secret.encode_base64(),
            },
        )
        os.chmod(path, 0o600)

    @classmethod
    def read(cls, path: str) -> "Secret":
        data = _read_json(path)
        return cls(
            PublicKey.decode_base64(data["name"]),
            SecretKey.decode_base64(data["secret"]),
        )


def write_committee(committee: Committee, path: str) -> None:
    _write_json(path, {"consensus": committee.to_json()})


def read_committee(path: str) -> Committee:
    data = _read_json(path)
    return Committee.from_json(data.get("consensus", data))


def write_parameters(parameters: Parameters, path: str) -> None:
    _write_json(path, {"consensus": parameters.to_json()})


def read_parameters(path: str) -> Parameters:
    data = _read_json(path)
    return Parameters.from_json(data.get("consensus", data))
