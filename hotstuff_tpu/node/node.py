"""Node: the composition root wiring store, crypto, and consensus.

Parity target: reference ``Node`` (node/src/node.rs:16-65): read the
committee/secret/parameters files, open the store, start the signature
service, spawn Consensus, and expose (and optionally drain) the commit
channel.

TPU addition: ``verifier_backend`` selects where signature batches are
verified — "cpu" (default) or "tpu" (the JAX batch kernel,
hotstuff_tpu/tpu/ed25519.py) — the SignatureService-boundary plug point
from BASELINE.json.
"""

from __future__ import annotations

import asyncio
import os
import logging

from ..consensus import Consensus, Parameters
from ..crypto.scheme import (
    make_cpu_verifier,
    make_device_verifier,
    make_signing_service,
)
from ..crypto.service import CpuVerifier, VerifierBackend
from ..store import Store
from .config import ConfigError, Secret, read_committee, read_parameters

log = logging.getLogger(__name__)

#: provenance tag on persisted state: the hash of the committee the
#: store's consensus/state records were produced under.  Disjoint from
#: every other store namespace (32-byte digests, 8-byte round keys,
#: ``consensus_state``, ``latest_round``, ``p<digest>``, ``s/...``).
COMMITTEE_HASH_KEY = b"committee_hash"


def committee_hash(committee) -> bytes:
    """Canonical identity of a committee (or schedule): the digest of
    its sorted-key JSON form — the same serialization the config files
    carry, so identical files hash identically across nodes."""
    import json

    from ..crypto.digest import sha512_trunc

    return sha512_trunc(
        json.dumps(committee.to_json(), sort_keys=True).encode()
    )


class _DeviceDispatch:
    """Forced-device view of a shared BatchVerifier for the async verify
    service (crypto/async_service.py): the service makes the
    device-vs-CPU routing decision itself, so this view must never
    silently re-route a batch back to the host the way the hybrid
    ``verify_many`` would.  One instance per device kind, process-wide —
    its identity is the coalescing key: every in-process core's claims
    land in the same dispatch stream."""

    def __init__(self, device):
        self._device = device
        self.name = getattr(device, "name", "tpu")
        # forward the fixed-shape padding capability (ISSUE 6): the
        # async service pads device waves only when the real verifier
        # behind this view opted in
        self.supports_wave_padding = getattr(
            device, "supports_wave_padding", False
        )

    def verify_many(
        self, digests, pks, sigs, aggregate_ok: bool = False
    ) -> list:
        return [bool(v) for v in self._device.verify_device(digests, pks, sigs)]


class LazyDeviceVerifier:
    """Defers the jax/numpy import (seconds of interpreter time per node
    process, serialized across a co-located committee sharing few cores)
    until a batch is actually big enough for the device.  Small batches
    route to the CPU backend exactly like the device verifier's own
    hybrid routing, so committees whose batches never reach
    ``min_device_batch`` boot and run without ever importing jax.

    The materialized device verifier is shared per kind, process-wide:
    an in-process committee holds ONE point cache and ONE compiled
    kernel set, and the async verify service (``async_backend``)
    coalesces every core's claims into one dispatch stream."""

    min_device_batch = 64

    # both lazy kinds ("tpu", "tpu-sharded") materialize ed25519
    # BatchVerifiers, which accept fixed-shape wave padding (ISSUE 6)
    supports_wave_padding = True

    _shared_device: dict[str, VerifierBackend] = {}
    _shared_dispatch: dict[str, _DeviceDispatch] = {}
    # kinds whose device kernel has been warmed (compiled/cache-loaded)
    # in THIS process — the async service routes to the device only then
    _warm: set[str] = set()

    #: "mesh" is the user-facing spelling of the sharded backend
    #: (benchmark profile --verifier mesh, node --verifier mesh); it
    #: normalizes to the canonical kind at construction so both names
    #: share the same process-wide device singleton and warm state
    _KIND_ALIASES = {"mesh": "tpu-sharded"}

    def __init__(self, kind: str):
        kind = self._KIND_ALIASES.get(kind, kind)
        self._kind = kind
        self._cpu = CpuVerifier()
        self._precomputed: list[bytes] = []
        self.name = kind
        # Advertises the async off-loop claim path to AsyncVerifyService
        # (one coalescing service per kind per loop).
        self.async_kind = kind

    @property
    def cpu_backend(self) -> CpuVerifier:
        return self._cpu

    @property
    def device_ready(self) -> bool:
        """True once the device kernel is warm — the async service must
        never trigger a cold jax import or Mosaic compile mid-consensus."""
        return self._kind in self._warm

    @property
    def _device(self) -> VerifierBackend | None:
        return self._shared_device.get(self._kind)

    @property
    def wave_bucket_shapes(self) -> tuple | None:
        """The device verifier's advertised wave bucket ladder (the mesh
        backend's mesh-multiple shapes, ISSUE 7) — None until the device
        materializes, so the async service's lazy bucket resolution
        falls back to the canonical ladder before warmup and picks the
        mesh grid up the moment it exists."""
        device = self._device
        if device is None:
            return None
        return getattr(device, "wave_bucket_shapes", None)

    def _materialize(self) -> VerifierBackend:
        device = self._shared_device.get(self._kind)
        if device is None:
            if self._kind == "tpu":
                from ..tpu.ed25519 import BatchVerifier

                device = BatchVerifier(min_device_batch=self.min_device_batch)
            else:  # tpu-sharded: batch sharded over the device mesh
                from ..parallel.mesh import (
                    ShardedBatchVerifier,
                    default_mesh,
                    mesh_devices_from_env,
                )

                # HOTSTUFF_MESH_DEVICES (node --mesh-devices) sizes the
                # production mesh; unset means every visible device.
                # Read HERE, at materialization, because that is the
                # moment the mesh is actually built — the CLI bridge
                # sets the env before any verifier exists.
                n = mesh_devices_from_env()
                device = ShardedBatchVerifier(
                    mesh=default_mesh(n) if n else None,
                    min_device_batch=self.min_device_batch,
                )
            self._shared_device[self._kind] = device
        if self._precomputed:
            device.precompute(self._precomputed)
            self._precomputed = []
        return device

    @property
    def async_backend(self) -> _DeviceDispatch:
        """The shared forced-device dispatch view (one per kind) the
        async verify service coalesces on."""
        dispatch = self._shared_dispatch.get(self._kind)
        if dispatch is None:
            dispatch = _DeviceDispatch(self._materialize())
            self._shared_dispatch[self._kind] = dispatch
        return dispatch

    def precompute(self, pubkeys: list[bytes]) -> None:
        self._precomputed = list(pubkeys)
        if self._device is not None:
            self._device.precompute(pubkeys)

    def warmup(self, batch: int | None = None) -> None:
        if self._kind in self._warm:
            return  # the shared device instance is already warm
        self._materialize().warmup(batch)
        self._warm.add(self._kind)

    def verify_one(self, digest, pk, sig) -> bool:
        return self._cpu.verify_one(digest, pk, sig)

    def verify_shared_msg(self, digest, votes) -> bool:
        if len(votes) < self.min_device_batch:
            return self._cpu.verify_shared_msg(digest, votes)
        return self._materialize().verify_shared_msg(digest, votes)

    def verify_many(
        self, digests, pks, sigs, aggregate_ok: bool = False
    ) -> list[bool]:
        if len(digests) < self.min_device_batch:
            return self._cpu.verify_many(digests, pks, sigs)
        return self._materialize().verify_many(
            digests, pks, sigs, aggregate_ok=aggregate_ok
        )


def make_verifier(kind: str, scheme: str = "ed25519") -> VerifierBackend:
    if kind == "cpu":
        return make_cpu_verifier(scheme)
    if kind in ("tpu", "tpu-sharded", "mesh"):
        if scheme == "bls":
            # BLS device path: G1 vote-signature aggregation on device
            # (hotstuff_tpu/tpu/bls.py), host pairing equality per QC.
            return make_device_verifier(
                scheme, "tpu-sharded" if kind == "mesh" else kind
            )
        return LazyDeviceVerifier(kind)
    raise ValueError(f"unknown verifier backend '{kind}'")


class Node:
    CHANNEL_CAPACITY = 1_000

    def __init__(self):
        self.commit: asyncio.Queue | None = None
        self.consensus: Consensus | None = None
        self.store: Store | None = None

    @classmethod
    async def new(
        cls,
        committee_file: str,
        key_file: str,
        store_path: str,
        parameters_file: str | None = None,
        verifier_backend: str = "cpu",
        bind_host: str = "0.0.0.0",
        transport: str = "asyncio",
    ) -> "Node":
        self = cls()
        committee = read_committee(committee_file)
        # Live reconfiguration (docs/RECONFIG.md) needs a spliceable
        # schedule: a bare committee file is promoted to a
        # single-entry schedule so a committed epoch change can extend
        # it at runtime.  for_round keeps every consumer oblivious.
        if not hasattr(committee, "splice"):
            from ..consensus.config import CommitteeSchedule

            committee = CommitteeSchedule([(1, committee)])
        secret = Secret.read(key_file)
        schemes = {c.scheme for c in committee.committees()}
        if len(schemes) == 1:
            if secret.scheme != next(iter(schemes)):
                raise ConfigError(
                    f"key file scheme '{secret.scheme}' does not match the "
                    f"committee scheme '{next(iter(schemes))}'"
                )
        else:
            # Mixed-scheme schedule (scheme changeover at an epoch
            # boundary): identities are per-scheme — this node signs
            # under its own key's scheme and must be a member of at
            # least one epoch using it; verification must handle BOTH
            # schemes (old-epoch certificates keep verifying after the
            # changeover), so the verifier is the dual router.
            my_epochs = [
                c for c in committee.committees()
                if secret.name in c.authorities
            ]
            if not my_epochs:
                raise ConfigError(
                    "key is not a member of any epoch in the schedule"
                )
            if any(c.scheme != secret.scheme for c in my_epochs):
                raise ConfigError(
                    f"key file scheme '{secret.scheme}' does not match an "
                    "epoch this key belongs to"
                )
        parameters = (
            read_parameters(parameters_file) if parameters_file else Parameters()
        )

        self.store = Store(store_path)
        # Committee-hash provenance: persisted consensus/execution state
        # is only valid under the committee that produced it.  A store
        # carrying another committee's history (the testbed's recycled
        # .db_* paths — the "fresh deploy recovers to round ~800" class)
        # is rejected EXPLICITLY and discarded, which is what makes the
        # old boot-time blanket wipe unnecessary on the happy path.
        # HOTSTUFF_FRESH_STATE=1 (--fresh-state) stays as the escape
        # hatch to force a clean slate regardless of provenance.
        #
        # The hash anchors on the GENESIS-era committee only: under live
        # reconfiguration the on-disk file stays the genesis artifact
        # while the store's schedule legitimately evolves past it — the
        # evolution itself is re-proven at boot from the certified
        # schedule links persisted at each commit (verified-successor
        # acceptance, below), not trusted from the provenance tag.
        chash = committee_hash(committee.committees()[0])
        # lint: allow(no-blocking-in-async) -- one-time boot path: the
        # node serves no traffic until new() returns, so a synchronous
        # engine read cannot stall a live round
        stored_hash = self.store.engine.get(COMMITTEE_HASH_KEY)
        fresh = os.environ.get("HOTSTUFF_FRESH_STATE", "") not in ("", "0")
        if fresh or (stored_hash is not None and stored_hash != chash):
            if fresh:
                log.info("Discarding persisted state (--fresh-state)")
            else:
                log.warning(
                    "Rejecting persisted state from a different committee "
                    "(stored %s, ours %s): starting fresh",
                    stored_hash.hex()[:16],
                    chash.hex()[:16],
                )
            self.store.close()
            import shutil

            shutil.rmtree(store_path, ignore_errors=True)
            self.store = Store(store_path)
        # lint: allow(no-blocking-in-async) -- same one-time boot path
        self.store.engine.put(COMMITTEE_HASH_KEY, chash)
        signature_service = make_signing_service(secret.scheme, secret.secret)
        if len(schemes) == 1:
            verifier = make_verifier(verifier_backend, next(iter(schemes)))
        else:
            from ..crypto.scheme import make_dual_verifier

            verifier = make_dual_verifier(
                lambda s: make_verifier(verifier_backend, s)
            )
        # Verified-successor acceptance: replay the certified schedule
        # links a previous process lifetime persisted (core commit path,
        # SCHEDULE_LINKS_KEY) so a restart resumes with the same epoch
        # schedule it shut down with — each link is re-verified against
        # the schedule as extended so far, never trusted from disk.
        from ..consensus.core import SCHEDULE_LINKS_KEY
        from ..consensus.reconfig import splice_schedule_links
        from ..consensus.wire import decode_schedule_links

        # lint: allow(no-blocking-in-async) -- same one-time boot path
        raw_links = self.store.engine.get(SCHEDULE_LINKS_KEY)
        if raw_links:
            from ..consensus.errors import InvalidReconfig
            from ..utils.codec import CodecError

            try:
                n = splice_schedule_links(
                    decode_schedule_links(raw_links),
                    committee,
                    verifier,
                    log=log,
                )
                if n:
                    log.info(
                        "Replayed %d certified schedule links from the "
                        "store (newest epoch %d)",
                        n,
                        max(c.epoch for c in committee.committees()),
                    )
            except (CodecError, InvalidReconfig) as e:
                log.warning("Ignoring persisted schedule links: %s", e)
        if hasattr(verifier, "precompute"):
            # warm the TPU backend's committee point cache (epoch setup)
            verifier.precompute(
                [pk.to_bytes() for pk in committee.authorities]
            )
        committee_size = len(committee.authorities)
        # Nodes co-located in this process (run-many sets the hint): their
        # verification claims coalesce into ONE dispatch stream, so the
        # device pays off far below the per-node min_device_batch and the
        # warm shapes must cover whole-committee waves.
        colocated = int(os.environ.get("HOTSTUFF_COLOCATED_NODES", "1") or 1)
        # HOTSTUFF_SKIP_WARMUP (diagnostic): run the device-verifier
        # plumbing with jax never imported — the service's ready gate
        # keeps everything on CPU.  Must skip the WHOLE warmup block,
        # not just the co-location boost.
        if hasattr(verifier, "warmup") and not os.environ.get(
            "HOTSTUFF_SKIP_WARMUP"
        ) and (
            committee_size >= getattr(verifier, "min_device_batch", 0)
            or colocated > 1
        ):
            # compile/cache-load the device kernel BEFORE binding the
            # consensus port: a cold compile on the first QC verify
            # would stall past the round timeout and trigger view
            # changes (clients wait for the port, so boot-time cost is
            # invisible to the measured window).  Skipped when every
            # possible batch (<= committee size) routes to the CPU
            # hybrid path anyway — then the kernel is never dispatched.
            quorum = committee_size * 2 // 3 + 1
            wave = (
                committee_size
                if colocated <= 1
                else min(1024, colocated * (quorum + 2))
            )
            verifier.warmup(batch=wave)

        from .. import telemetry

        tel = telemetry.for_node(str(secret.name)[:8])
        # Flight recorder (telemetry/journal.py): must attach BEFORE
        # Consensus.spawn — the consensus actors capture
        # ``telemetry.journal`` at construction time.
        self._journal = None
        jdir = telemetry.journal_dir(store_path)
        if tel is not None and jdir:
            from ..telemetry.journal import Journal

            self._journal = Journal(tel.node, jdir)
            tel.attach_journal(self._journal)
            if telemetry.spans.enabled():
                # verify-pipeline spans render as one per-process track
                # in the merged trace (first journaled node wins)
                telemetry.spans.attach_journal(self._journal)
            log.info("Flight recorder journaling to %s", jdir)
        stats_task = None
        probe_running = False
        if tel is not None or os.environ.get("HOTSTUFF_WORK_STATS"):
            # per-node work accounting for the committee-scaling
            # decomposition (utils/workstats.py): counted verifier +
            # loop-lag probe, one parseable log line every few seconds.
            # Telemetry reuses the same counted-verifier wrapper; the
            # snapshot document is a superset of the Work stats one.
            from ..utils.workstats import CountingVerifier, WorkStats, run_probe

            stats = WorkStats()
            verifier = CountingVerifier(verifier, stats)
            if os.environ.get("HOTSTUFF_WORK_STATS"):
                probe_running = True
                stats_task = asyncio.ensure_future(
                    run_probe(
                        stats, logging.getLogger(f"workstats.{secret.name}")
                    )
                )
            if tel is not None:
                tel.attach_workstats(stats)

        self.commit = asyncio.Queue(maxsize=self.CHANNEL_CAPACITY)
        self.consensus = await Consensus.spawn(
            secret.name,
            committee,
            parameters,
            signature_service,
            self.store,
            self.commit,
            verifier=verifier,
            bind_host=bind_host,
            transport=transport,
            telemetry=tel,
        )
        self._stats_task = stats_task
        self._snapshot_task = None
        if tel is not None:
            from ..telemetry.exporter import run_snapshot_logger

            # the snapshot logger samples loop lag only when no workstats
            # probe is doing it already (double-counting would halve the
            # reported mean)
            self._snapshot_task = asyncio.ensure_future(
                run_snapshot_logger(
                    tel,
                    logging.getLogger(f"telemetry.{secret.name}"),
                    sample_lag=not probe_running,
                )
            )
        self._health_task = None
        self._health_monitor = None
        if tel is not None and telemetry.health_enabled():
            from ..telemetry.health import CAMPAIGN_SUFFIX, HealthMonitor

            # campaign ring persists beside the journal (when journaling
            # is on) as <node>-campaign.json — a name the journal
            # loader's *.jsonl glob never matches
            campaign_path = (
                os.path.join(jdir, f"{tel.node}{CAMPAIGN_SUFFIX}")
                if jdir
                else None
            )
            from ..telemetry.critpath import rolling_attribution

            self._health_monitor = HealthMonitor(
                tel,
                tel.node,
                timeout_s=parameters.timeout_delay / 1000.0,
                campaign_path=campaign_path,
                logger=logging.getLogger(f"health.{secret.name}"),
                # rolling critical-path attribution over the node's own
                # trace ring (health.py is import-free, so the engine
                # hook is injected here)
                attribution_fn=lambda t=tel: rolling_attribution(
                    t.trace.recent(64)
                ),
            )
            self._health_task = asyncio.ensure_future(
                self._health_monitor.run()
            )
            # the live watch scrapes node-local incidents out of the
            # snapshot: the node's own monitor sees a commit stall a
            # fleet-side detector could only infer
            tel.add_section(
                "health",
                lambda m=self._health_monitor: {
                    "open": sorted(i.kind for i in m.open_incidents()),
                    **(
                        {
                            "dominant_stage": m.last_attribution.get(
                                "dominant", ""
                            ),
                            "regime": m.last_attribution.get("regime", ""),
                        }
                        if m.last_attribution
                        else {}
                    ),
                },
            )
            log.info("Health monitor running for node %s", tel.node)
        log.info("Node %s successfully booted", secret.name)
        return self

    async def analyze_block(self) -> None:
        """Drain the commit channel — the application layer stub
        (node/src/node.rs:61-65)."""
        while True:
            _block = await self.commit.get()
            # Here the application would execute the committed payload.

    async def serve(self) -> None:
        """Drain commits until the core retires — a committed epoch
        change excluded this node and its grace window elapsed
        (docs/RECONFIG.md) — then linger briefly so straggling peers can
        still fetch boundary certificates and snapshots, and shut down
        cleanly.  Nodes that are never voted out serve forever."""
        drain = asyncio.ensure_future(self.analyze_block())
        try:
            core = self.consensus.core
            while not getattr(core, "retired", False):
                await asyncio.sleep(0.5)
            linger = float(
                os.environ.get("HOTSTUFF_RECONFIG_LINGER_S", "5") or 5
            )
            log.info(
                "Core retired; lingering %.1f s for boundary sync "
                "before shutdown",
                linger,
            )
            await asyncio.sleep(linger)
        finally:
            drain.cancel()
        await self.shutdown()
        log.info("Node retired cleanly")

    async def shutdown(self) -> None:
        for attr in ("_stats_task", "_snapshot_task", "_health_task"):
            task = getattr(self, attr, None)
            if task is not None:
                task.cancel()
        monitor = getattr(self, "_health_monitor", None)
        if monitor is not None:
            monitor.close()
        if self.consensus is not None:
            await self.consensus.shutdown()
        journal = getattr(self, "_journal", None)
        if journal is not None:
            journal.close()
        if self.store is not None:
            self.store.close()
